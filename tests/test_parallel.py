"""Distributed parity tests on the 8-device virtual CPU mesh.

The reference's key distributed test is EQUIVALENCE
(``TestCompareParameterAveragingSparkVsSingleMachine.java:41``, SURVEY.md
§4 "Distributed without a cluster"): cluster training must produce the
same parameters as single-machine training. Ported here as
multi-device-vs-single-device over ``xla_force_host_platform_device_count=8``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.datasets.iris import load_iris_dataset
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ops.attention import multi_head_attention, scaled_dot_product_attention
from deeplearning4j_tpu.parallel import MeshContext, ParallelWrapper, make_mesh
from deeplearning4j_tpu.parallel.ring_attention import ring_attention
from deeplearning4j_tpu.parallel.tensor_parallel import apply_shardings, dense_tp_specs


def _mlp(seed=42, lr=0.1, updater="sgd"):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).learning_rate(lr).updater(updater).activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _data(n=96, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)]
    return DataSet(x, y)


class TestMesh:
    def test_make_mesh_axes(self):
        mesh = make_mesh({"data": 4, "model": 2})
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_bad_axis_product(self):
        with pytest.raises(ValueError, match="devices"):
            make_mesh({"data": 5})


class TestDataParallelEquivalence:
    """Spark-vs-single-machine equivalence, TPU edition."""

    def test_allreduce_matches_single_device(self):
        ds = _data()
        single = _mlp()
        for _ in range(5):
            single.fit(ds)

        dist = _mlp()
        pw = ParallelWrapper(dist, mesh=make_mesh({"data": 8}))
        for _ in range(5):
            pw.fit(ds)
        np.testing.assert_allclose(dist.params_flat(), single.params_flat(),
                                   rtol=2e-5, atol=1e-6)

    def test_averaging_freq1_sgd_equals_allreduce(self):
        """Param averaging at freq=1 with SGD == per-step gradient
        all-reduce (the §7.7 semantic note)."""
        ds = _data()
        a = _mlp()
        pa = ParallelWrapper(a, mesh=make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=1)
        for _ in range(3):
            pa.fit(ds)

        b = _mlp()
        pb = ParallelWrapper(b, mesh=make_mesh({"data": 8}), mode="allreduce")
        for _ in range(3):
            pb.fit(ds)
        np.testing.assert_allclose(a.params_flat(), b.params_flat(),
                                   rtol=2e-4, atol=1e-5)

    def test_averaging_frequency_divergence_then_average(self):
        """avgFreq=4: workers diverge between averages, then re-sync."""
        ds = _data()
        net = _mlp(updater="nesterovs")
        pw = ParallelWrapper(net, mesh=make_mesh({"data": 8}), mode="averaging",
                             averaging_frequency=4)
        it = ListDataSetIterator(ds, 48)  # 2 batches/epoch
        for _ in range(4):
            pw.fit(it)
        # training happened and final params are finite + synced
        assert np.all(np.isfinite(net.params_flat()))
        preds = net.output(ds.features)
        assert preds.shape == (96, 3)

    def test_distributed_training_learns_iris(self):
        ds = load_iris_dataset(shuffle_seed=6)
        conf = (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.5).updater("nesterovs").activation("relu")
                .weight_init("relu").list()
                .layer(DenseLayer(n_in=4, n_out=16))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        pw = ParallelWrapper(net, mesh=make_mesh({"data": 8}))
        ds_pad = DataSet(np.concatenate([ds.features, ds.features[:10]]),
                         np.concatenate([ds.labels, ds.labels[:10]]))  # 160 % 8 == 0
        for _ in range(150):
            pw.fit(ds_pad)
        acc = float(np.mean(net.predict(ds.features) == np.argmax(ds.labels, axis=1)))
        assert acc >= 0.95, acc


class TestTensorParallel:
    def test_tp_sharded_training_matches_replicated(self):
        ds = _data(64)
        ref = _mlp(lr=0.3)
        for _ in range(5):
            ref.fit(ds)

        tp = _mlp(lr=0.3)
        mesh = make_mesh({"model": 8})
        apply_shardings(tp, mesh, dense_tp_specs(["layer0"]))
        for _ in range(5):
            tp.fit(ds)
        np.testing.assert_allclose(tp.params_flat(), ref.params_flat(),
                                   rtol=2e-5, atol=1e-6)

    def test_dp_tp_mixed_mesh(self):
        ds = _data(64)
        ref = _mlp(lr=0.3)
        for _ in range(3):
            ref.fit(ds)

        net = _mlp(lr=0.3)
        mesh = make_mesh({"data": 4, "model": 2})
        apply_shardings(net, mesh, dense_tp_specs(["layer0"]))
        pw = ParallelWrapper(net, mesh=mesh)
        # note: ParallelWrapper re-places params replicated; re-apply TP specs
        apply_shardings(net, mesh, dense_tp_specs(["layer0"]))
        ctx = MeshContext(mesh)
        rng_key = jax.random.PRNGKey(net.gc.seed + 7919)
        step = net._get_jit("train", fm=False, lm=False)
        x, y = ctx.shard_batch(ds.features, ds.labels)
        zero = jnp.zeros((), net._dtype)
        for _ in range(3):
            net.params, net.opt_state, net.states, _ = step(
                net.params, net.opt_state, net.states, x, y, zero, zero, rng_key)
        np.testing.assert_allclose(net.params_flat(), ref.params_flat(),
                                   rtol=2e-5, atol=1e-6)


class TestRingAttention:
    def test_matches_full_attention(self):
        rng = np.random.default_rng(0)
        b, t, h, d = 2, 32, 4, 8
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        mesh = make_mesh({"seq": 8})
        full = scaled_dot_product_attention(q, k, v)
        ring = ring_attention(q, k, v, mesh, axis="seq")
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), rtol=2e-4, atol=2e-5)

    def test_causal_matches_full(self):
        rng = np.random.default_rng(1)
        b, t, h, d = 1, 16, 2, 4
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        mesh = make_mesh({"seq": 8})
        full = scaled_dot_product_attention(q, k, v, causal=True)
        ring = ring_attention(q, k, v, mesh, axis="seq", causal=True)
        np.testing.assert_allclose(np.asarray(ring), np.asarray(full), rtol=2e-4, atol=2e-5)

    def test_gradients_flow_through_ring(self):
        rng = np.random.default_rng(2)
        b, t, h, d = 1, 8, 1, 4
        mesh = make_mesh({"seq": 8})
        q = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((b, t, h, d)), jnp.float32)

        g_ring = jax.grad(lambda q: jnp.sum(ring_attention(q, k, v, mesh, "seq") ** 2))(q)
        g_full = jax.grad(lambda q: jnp.sum(scaled_dot_product_attention(q, k, v) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_full),
                                   rtol=5e-4, atol=5e-5)


class TestMultiHeadAttention:
    def test_shapes_and_causality(self):
        rng = np.random.default_rng(0)
        b, t, f, hd = 2, 6, 8, 8
        x = jnp.asarray(rng.standard_normal((b, t, f)), jnp.float32)
        wq, wk, wv = (jnp.asarray(rng.standard_normal((f, hd)) * 0.1, jnp.float32) for _ in range(3))
        wo = jnp.asarray(rng.standard_normal((hd, f)) * 0.1, jnp.float32)
        out = multi_head_attention(x, wq, wk, wv, wo, num_heads=2, causal=True)
        assert out.shape == (b, t, f)
        # causality: output at t=0 must not depend on x at t>0
        x2 = x.at[:, 3:, :].set(99.0)
        out2 = multi_head_attention(x2, wq, wk, wv, wo, num_heads=2, causal=True)
        np.testing.assert_allclose(np.asarray(out[:, :3]), np.asarray(out2[:, :3]),
                                   rtol=1e-5)
