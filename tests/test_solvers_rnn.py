"""Classic solvers, TBPTT, rnnTimeStep — ports of ``TestOptimizers``,
``MultiLayerTestRNN.java`` TBPTT equivalence tests (SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iris import load_iris_dataset
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, GravesLSTM, OutputLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.optimize.solvers import Solver


def _mlp(algo, seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).optimization_algo(algo).iterations(20)
            .activation("tanh").learning_rate(0.1).list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init(dtype=jnp.float64)


class TestClassicSolvers:
    @pytest.mark.parametrize("algo", ["lbfgs", "conjugate_gradient", "line_gradient_descent"])
    def test_full_batch_convergence(self, algo):
        net = _mlp(algo)
        ds = load_iris_dataset(shuffle_seed=4)
        s0 = net.score(ds)
        f = Solver(net).optimize(ds, iterations=25)
        assert f < s0 / 2, f"{algo}: {s0} -> {f}"
        acc = float(np.mean(net.predict(ds.features) == np.argmax(ds.labels, axis=1)))
        assert acc > 0.9, f"{algo}: acc {acc}"

    def test_lbfgs_beats_plain_gd_on_same_budget(self):
        ds = load_iris_dataset(shuffle_seed=4)
        a = _mlp("lbfgs")
        fa = Solver(a).optimize(ds, iterations=15)
        b = _mlp("line_gradient_descent")
        fb = Solver(b).optimize(ds, iterations=15)
        assert fa <= fb * 1.2  # lbfgs at least competitive


class TestTBPTT:
    def _seq_conf(self, backprop_type="standard", tbptt_len=5):
        b = (NeuralNetConfiguration.builder()
             .seed(3).learning_rate(0.05).updater("adam").activation("tanh")
             .list()
             .layer(GravesLSTM(n_in=2, n_out=8))
             .layer(RnnOutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss_function="mcxent")))
        b = b.backprop_type(backprop_type)
        b = b.t_bptt_forward_length(tbptt_len).t_bptt_backward_length(tbptt_len)
        return b.build()

    def test_tbptt_trains_long_sequence(self):
        rng = np.random.default_rng(0)
        B, T = 8, 20
        x = np.zeros((B, T, 2), np.float32)
        bits = rng.integers(0, 2, (B, T))
        x[np.arange(B)[:, None], np.arange(T)[None, :], bits] = 1
        y = x.copy()
        net = MultiLayerNetwork(self._seq_conf("truncated_bptt", 5)).init()
        ds = DataSet(x, y)
        net.fit(ds)
        s0 = net.score()
        for _ in range(40):
            net.fit(ds)
        assert net.score() < s0 / 2

    def test_tbptt_float_sequence_level_labels_rejected(self):
        """ADVICE r2: a dense [b, nOut] label matrix whose nOut equals T
        must NOT be silently reinterpreted as sparse per-timestep ids —
        the sparse path demands integer dtype."""
        rng = np.random.default_rng(5)
        B, T = 4, 6
        x = rng.standard_normal((B, T, 2)).astype(np.float32)
        y_float = rng.random((B, T)).astype(np.float32)  # shape collides
        net = MultiLayerNetwork(self._seq_conf("truncated_bptt", 3)).init()
        with pytest.raises(ValueError, match="integer dtype"):
            net.fit(DataSet(x, y_float))

    def test_tbptt_sparse_int_labels_train(self):
        rng = np.random.default_rng(6)
        B, T = 4, 6
        x = rng.standard_normal((B, T, 2)).astype(np.float32)
        y_ids = rng.integers(0, 2, (B, T))
        net = MultiLayerNetwork(self._seq_conf("truncated_bptt", 3)).init()
        net.fit(DataSet(x, y_ids))  # must not raise
        assert np.isfinite(net.score())

    def test_tbptt_single_chunk_equals_standard(self):
        """T <= tbptt length -> identical to standard backprop."""
        rng = np.random.default_rng(1)
        x = rng.standard_normal((4, 5, 2)).astype(np.float32)
        y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (4, 5))]
        a = MultiLayerNetwork(self._seq_conf("standard")).init()
        b = MultiLayerNetwork(self._seq_conf("truncated_bptt", 10)).init()
        for _ in range(3):
            a.fit(DataSet(x, y))
            b.fit(DataSet(x, y))
        np.testing.assert_allclose(a.params_flat(), b.params_flat(), rtol=1e-6)


class TestRnnTimeStep:
    def test_stream_matches_full_forward(self):
        rng = np.random.default_rng(2)
        conf = (NeuralNetConfiguration.builder()
                .seed(5).activation("tanh").list()
                .layer(GravesLSTM(n_in=3, n_out=6))
                .layer(RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = rng.standard_normal((2, 7, 3)).astype(np.float32)
        full = net.output(x)
        net.rnn_clear_previous_state()
        step_outs = [net.rnn_time_step(x[:, t]) for t in range(7)]
        for t in range(7):
            np.testing.assert_allclose(step_outs[t], full[:, t], rtol=1e-4, atol=1e-6)
        # burst API
        net.rnn_clear_previous_state()
        burst = net.rnn_time_step(x)
        np.testing.assert_allclose(burst, full, rtol=1e-4, atol=1e-6)

    def test_state_persists_across_calls(self):
        conf = (NeuralNetConfiguration.builder()
                .seed(5).activation("tanh").list()
                .layer(GravesLSTM(n_in=2, n_out=4))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax",
                                      loss_function="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        x = np.random.default_rng(3).standard_normal((1, 2)).astype(np.float32)
        o1 = net.rnn_time_step(x)
        o2 = net.rnn_time_step(x)  # same input, different state -> different out
        assert not np.allclose(o1, o2)
        net.rnn_clear_previous_state()
        o3 = net.rnn_time_step(x)
        np.testing.assert_allclose(o1, o3, rtol=1e-6)


def test_termination_conditions_stop_converged_solvers(rng):
    """optimize/terminations parity: EpsTermination/Norm2 stop the
    classic optimizers early once converged (a quadratic bowl converges
    in far fewer than the requested iterations)."""
    from deeplearning4j_tpu.optimize.solvers import (
        TerminationConditions, conjugate_gradient, lbfgs,
        line_gradient_descent)

    class Bowl:
        flat0 = np.asarray([3.0, -2.0], np.float32)

        def loss(self, v):
            import jax.numpy as jnp
            return jnp.sum(v * v)

        def value_and_grad(self, v):
            import jax
            return jax.value_and_grad(self.loss)(v)

    calls = []

    class Counting(Bowl):
        def value_and_grad(self, v):
            calls.append(1)
            return super().value_and_grad(v)

    for solver in (line_gradient_descent, conjugate_gradient, lbfgs):
        calls.clear()
        x, f = solver(Counting(), 200)
        assert f < 1e-4, (solver.__name__, f)
        assert len(calls) < 100, (solver.__name__, len(calls))

    t = TerminationConditions()
    assert not t.eps_terminate(0.0, 0.0)   # initial special case
    assert t.eps_terminate(1.0, 1.0 + 1e-9)
    assert t.terminate(5.0, 9.0, np.zeros(3))  # zero direction
