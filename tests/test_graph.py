"""ComputationGraph tests — ports of
``TestComputationGraphNetwork.java`` + ``GradientCheckTestsComputationGraph.java``."""

import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iris import load_iris_dataset
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph import (
    ElementWiseVertex,
    L2NormalizeVertex,
    L2Vertex,
    LastTimeStepVertex,
    MergeVertex,
    StackVertex,
    SubsetVertex,
    UnstackVertex,
)
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer,
    GravesLSTM,
    OutputLayer,
)
from deeplearning4j_tpu.nn.gradientcheck import check_gradients_graph
from deeplearning4j_tpu.nn.graph import (
    ComputationGraph,
    ComputationGraphConfiguration,
    topological_order,
    VertexDef,
)


def _conf(**kw):
    c = NeuralNetConfiguration(seed=42, activation="tanh", weight_init="xavier")
    for k, v in kw.items():
        setattr(c, k, v)
    return c


class TestTopology:
    def test_topological_order(self):
        verts = [
            VertexDef("in", "input", []),
            VertexDef("c", "op", ["a", "b"]),
            VertexDef("a", "op", ["in"]),
            VertexDef("b", "op", ["a"]),
        ]
        order = topological_order(verts)
        assert order.index("in") < order.index("a") < order.index("b") < order.index("c")

    def test_cycle_detection(self):
        verts = [
            VertexDef("in", "input", []),
            VertexDef("a", "op", ["in", "b"]),
            VertexDef("b", "op", ["a"]),
        ]
        with pytest.raises(ValueError, match="cycle"):
            topological_order(verts)

    def test_unknown_input(self):
        with pytest.raises(ValueError, match="unknown input"):
            topological_order([VertexDef("a", "op", ["ghost"])])


class TestGraphTraining:
    def test_iris_mlp_as_graph(self):
        conf = (ComputationGraphConfiguration.builder(_conf(learning_rate=0.5, updater="nesterovs"))
                .add_inputs("in")
                .add_layer("dense", DenseLayer(n_in=4, n_out=16, activation="relu",
                                               weight_init="relu"), "in")
                .add_layer("out", OutputLayer(n_in=16, n_out=3, activation="softmax",
                                              loss_function="mcxent"), "dense")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init()
        ds = load_iris_dataset(shuffle_seed=6)
        for _ in range(150):
            g.fit(ds)
        acc = float(np.mean(np.argmax(g.output(ds.features), axis=1) ==
                            np.argmax(ds.labels, axis=1)))
        assert acc >= 0.95, acc

    def test_multi_input_merge_gradcheck(self, rng):
        conf = (ComputationGraphConfiguration.builder(_conf())
                .add_inputs("in1", "in2")
                .add_layer("d1", DenseLayer(n_in=3, n_out=4), "in1")
                .add_layer("d2", DenseLayer(n_in=2, n_out=3), "in2")
                .add_vertex("merge", MergeVertex(), "d1", "d2")
                .add_layer("out", OutputLayer(n_in=7, n_out=2, activation="softmax",
                                              loss_function="mcxent"), "merge")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init(dtype=jnp.float64)
        mds = MultiDataSet(
            features=[rng.standard_normal((5, 3)), rng.standard_normal((5, 2))],
            labels=[np.eye(2)[rng.integers(0, 2, 5)]])
        res = check_gradients_graph(g, mds)
        assert res.ok, "; ".join(res.failures[:3])

    def test_multi_output_gradcheck(self, rng):
        conf = (ComputationGraphConfiguration.builder(_conf())
                .add_inputs("in")
                .add_layer("shared", DenseLayer(n_in=4, n_out=5), "in")
                .add_layer("out1", OutputLayer(n_in=5, n_out=2, activation="softmax",
                                               loss_function="mcxent"), "shared")
                .add_layer("out2", OutputLayer(n_in=5, n_out=3, activation="identity",
                                               loss_function="mse"), "shared")
                .set_outputs("out1", "out2")
                .build())
        g = ComputationGraph(conf).init(dtype=jnp.float64)
        mds = MultiDataSet(
            features=[rng.standard_normal((6, 4))],
            labels=[np.eye(2)[rng.integers(0, 2, 6)], rng.standard_normal((6, 3))])
        res = check_gradients_graph(g, mds)
        assert res.ok, "; ".join(res.failures[:3])

    def test_residual_block_gradcheck(self, rng):
        """Skip connection via ElementWiseVertex add (ResNet pattern)."""
        conf = (ComputationGraphConfiguration.builder(_conf())
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=4, n_out=4), "in")
                .add_vertex("res", ElementWiseVertex(op="add"), "d1", "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                              loss_function="mcxent"), "res")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init(dtype=jnp.float64)
        mds = MultiDataSet(features=[rng.standard_normal((5, 4))],
                           labels=[np.eye(2)[rng.integers(0, 2, 5)]])
        res = check_gradients_graph(g, mds)
        assert res.ok, "; ".join(res.failures[:3])

    def test_lstm_last_timestep_vertex(self, rng):
        conf = (ComputationGraphConfiguration.builder(_conf())
                .add_inputs("in")
                .add_layer("lstm", GravesLSTM(n_in=3, n_out=4), "in")
                .add_vertex("last", LastTimeStepVertex(mask_input="in"), "lstm")
                .add_layer("out", OutputLayer(n_in=4, n_out=2, activation="softmax",
                                              loss_function="mcxent"), "last")
                .set_outputs("out")
                .build())
        g = ComputationGraph(conf).init(dtype=jnp.float64)
        x = rng.standard_normal((3, 6, 3))
        mask = np.ones((3, 6))
        mask[1, 3:] = 0
        y = np.eye(2)[rng.integers(0, 2, 3)]
        mds = MultiDataSet(features=[x], labels=[y], features_masks=[mask])
        res = check_gradients_graph(g, mds, subset=100)
        assert res.ok, "; ".join(res.failures[:3])


class TestVertexOps:
    def test_subset_stack_unstack(self, rng):
        x = jnp.asarray(rng.standard_normal((4, 6)))
        sub = SubsetVertex(from_index=1, to_index=3).forward([x])
        np.testing.assert_allclose(np.asarray(sub), np.asarray(x)[:, 1:4])
        a, b = x[:2], x[2:]
        st = StackVertex().forward([a, b])
        np.testing.assert_allclose(np.asarray(st), np.asarray(x))
        u = UnstackVertex(from_index=1, stack_size=2).forward([st])
        np.testing.assert_allclose(np.asarray(u), np.asarray(b))

    def test_l2_vertices(self, rng):
        a = jnp.asarray(rng.standard_normal((3, 4)))
        b = jnp.asarray(rng.standard_normal((3, 4)))
        d = L2Vertex().forward([a, b])
        expected = np.linalg.norm(np.asarray(a) - np.asarray(b), axis=1)
        np.testing.assert_allclose(np.asarray(d)[:, 0], expected, rtol=1e-5)
        n = L2NormalizeVertex().forward([a])
        np.testing.assert_allclose(np.linalg.norm(np.asarray(n), axis=1), 1.0, rtol=1e-5)


class TestGraphSerialization:
    def test_json_round_trip(self):
        conf = (ComputationGraphConfiguration.builder(_conf())
                .add_inputs("in1", "in2")
                .add_layer("d1", DenseLayer(n_in=3, n_out=4), "in1")
                .add_vertex("merge", MergeVertex(), "d1", "in2")
                .add_layer("out", OutputLayer(n_in=6, n_out=2, activation="softmax",
                                              loss_function="mcxent"), "merge")
                .set_outputs("out")
                .build())
        s = conf.to_json()
        conf2 = ComputationGraphConfiguration.from_json(s)
        assert conf2.to_json() == s
        g1 = ComputationGraph(conf).init()
        g2 = ComputationGraph(conf2).init()
        np.testing.assert_array_equal(g1.params_flat(), g2.params_flat())
