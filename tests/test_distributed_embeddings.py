"""Mesh-sharded embedding training equivalence tests.

VERDICT r1 #7 'done' criterion: 8-device CPU word2vec == single-device
vectors (same seed). The Spark-NLP distributed word2vec role
(``dl4j-spark-nlp/.../TextPipeline.java``, ``Word2VecPerformer``)
re-formulated as synchronous SPMD (models/sequencevectors/distributed.py).
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import make_mesh

CORPUS = [
    "the quick brown fox jumps over the lazy dog".split(),
    "the dog barks at the quick fox".split(),
    "a lazy brown dog sleeps all day".split(),
    "the fox and the dog play in the field".split(),
] * 8


def _fit(mesh=None, model_axis="model", **kw):
    from deeplearning4j_tpu.models.sequencevectors.engine import SequenceVectors
    # device_pairgen=False: both sides must run the identical host
    # per-batch pair stream for exact equivalence (the scan path draws
    # its pairs/negatives from a different on-device RNG stream)
    sv = SequenceVectors(vector_length=16, window=2, epochs=2, batch_size=64,
                         seed=99, mesh=mesh, model_axis=model_axis,
                         device_pairgen=False, **kw)
    sv.fit(CORPUS)
    return sv


def _mesh(axes):
    devs = jax.devices()
    need = int(np.prod(list(axes.values())))
    if len(devs) < need:
        pytest.skip(f"needs {need} CPU devices")
    return make_mesh(axes, devices=devs[:need])


@pytest.mark.parametrize("kw", [
    dict(negative=4),                                      # SGNS
    dict(negative=0, use_hierarchic_softmax=True),         # HS
    dict(negative=4, elements_learning_algorithm="cbow"),  # CBOW
])
def test_sharded_matches_single_device(kw):
    mesh = _mesh({"data": 4, "model": 2})
    single = _fit(mesh=None, **kw)
    sharded = _fit(mesh=mesh, **kw)
    np.testing.assert_allclose(sharded.lookup_table.syn0,
                               single.lookup_table.syn0,
                               rtol=1e-4, atol=1e-5)


def test_sharded_data_axis_only():
    mesh = _mesh({"data": 8})
    single = _fit(mesh=None, negative=4)
    sharded = _fit(mesh=mesh, negative=4)
    np.testing.assert_allclose(sharded.lookup_table.syn0,
                               single.lookup_table.syn0,
                               rtol=1e-4, atol=1e-5)
