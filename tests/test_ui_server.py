"""UiServer + remote stats transport + component DSL tests.

Parity: ``UiServer.java:25-32`` (live dashboard server),
``HistogramIterationListener.java:35-52`` (HTTP report transport),
``deeplearning4j-ui-components`` (declarative chart/table/text DSL).
"""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import (
    ChartHistogram, ChartHorizontalBar, ChartLine, ChartScatter, Component,
    ComponentDiv, ComponentTable, ComponentText, InMemoryStatsStorage,
    RemoteStatsStorageRouter, UiServer)
from deeplearning4j_tpu.ui.stats import StatsReport


def _report(i, session="s1", worker="w0", score=1.0):
    return StatsReport(session_id=session, worker_id=worker, iteration=i,
                       timestamp=1000.0 + i, score=score,
                       param_norms={"layer0/W": 1.5})


@pytest.fixture()
def server():
    storage = InMemoryStatsStorage()
    srv = UiServer(storage, port=0).start()
    yield srv, storage
    srv.stop()


def test_server_api_roundtrip(server):
    srv, storage = server
    for i in range(3):
        storage.put_report(_report(i))
    storage.put_report(_report(0, worker="w1"))

    def get(path):
        with urllib.request.urlopen(srv.url + path, timeout=5) as r:
            return json.loads(r.read())

    assert get("/api/sessions") == ["s1"]
    assert get("/api/sessions/s1/workers") == ["w0", "w1"]
    reports = get("/api/sessions/s1/reports")
    assert len(reports) == 4
    assert get("/api/sessions/s1/reports?worker=w1")[0]["worker_id"] == "w1"
    with urllib.request.urlopen(srv.url + "/train/s1", timeout=5) as r:
        page = r.read().decode()
    assert "<svg" in page and "Score vs iteration" in page
    with urllib.request.urlopen(srv.url + "/", timeout=5) as r:
        index = r.read().decode()
    assert "s1" in index


def test_remote_router_ships_reports(server):
    srv, storage = server
    router = RemoteStatsStorageRouter(srv.url)
    for i in range(4):
        router.put_report(_report(i, session="remote"))
    # landed in the server-side storage
    assert len(storage.get_reports("remote")) == 4
    # reads proxy through the API
    assert router.list_sessions() == ["remote"]
    got = router.get_reports("remote")
    assert [r.iteration for r in got] == [0, 1, 2, 3]
    assert got[0].param_norms == {"layer0/W": 1.5}


def test_server_404_and_bad_post(server):
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(srv.url + "/api/nope", timeout=5)
    assert e.value.code == 404
    req = urllib.request.Request(srv.url + "/api/reports", data=b"not json",
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=5)
    assert e.value.code == 400


def test_words_nearest_endpoint():
    """Nearest-neighbor serving (legacy dl4j-scaleout nlp render role)."""
    class FakeWV:
        def words_nearest(self, word, n=10):
            if word != "king":
                raise KeyError(word)
            return ["queen", "prince"][:n]

    storage = InMemoryStatsStorage()
    srv = UiServer(storage, port=0, word_vectors=FakeWV()).start()
    try:
        got = json.loads(urllib.request.urlopen(
            srv.url + "/api/words/nearest?word=king&n=2", timeout=5).read())
        assert got["nearest"] == [["queen", None], ["prince", None]]
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/api/words/nearest?word=zzz",
                                   timeout=5)
        assert e.value.code == 404
        page = urllib.request.urlopen(srv.url + "/words?word=king",
                                      timeout=5).read().decode()
        assert "queen" in page
    finally:
        srv.stop()


def test_words_endpoint_absent_without_vectors(server):
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(srv.url + "/api/words/nearest?word=x", timeout=5)
    assert e.value.code == 404


def test_component_dsl_roundtrip_and_render():
    rng = np.random.default_rng(0)
    counts, edges = np.histogram(rng.standard_normal(500), bins=10)
    page = ComponentDiv(
        ComponentText("LeNet run", size=18, bold=True),
        ChartLine("score", x=[[0, 1, 2]], y=[[3.0, 2.0, 1.5]],
                  series_names=["score"]),
        ChartScatter("pts", x=[[0, 1]], y=[[1.0, 2.0]]),
        ChartHistogram("W dist", lower=edges[:-1].tolist(),
                       upper=edges[1:].tolist(), counts=counts.tolist()),
        ChartHorizontalBar("norms", labels=["layer0/W", "layer0/b"],
                           values=[1.5, 0.1]),
        ComponentTable(header=["layer", "norm"],
                       content=[["layer0/W", 1.5]], title="params"),
        style="margin:8px",
    )
    blob = json.dumps(page.to_dict())
    back = Component.from_dict(json.loads(blob))
    assert isinstance(back, ComponentDiv) and len(back.children) == 6
    assert json.dumps(back.to_dict()) == blob  # stable round-trip
    html_page = back.render_page()
    assert html_page.startswith("<!DOCTYPE html>")
    for frag in ("LeNet run", "score", "W dist", "layer0/W", "<svg", "<table"):
        assert frag in html_page
    # scatter draws point marks, line draws polylines
    assert "<circle" in back.children[2].render_html()
    assert "<polyline" in back.children[1].render_html()


def test_component_dsl_validation():
    with pytest.raises(ValueError):
        ChartLine("x", x=[[1]], y=[])
    with pytest.raises(ValueError):
        ChartHistogram("h", lower=[0], upper=[1, 2], counts=[1])
    with pytest.raises(ValueError):
        Component.from_dict({"componentType": "NoSuch"})
