"""UiServer + remote stats transport + component DSL tests.

Parity: ``UiServer.java:25-32`` (live dashboard server),
``HistogramIterationListener.java:35-52`` (HTTP report transport),
``deeplearning4j-ui-components`` (declarative chart/table/text DSL).
"""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.ui import (
    ChartHistogram, ChartHorizontalBar, ChartLine, ChartScatter, Component,
    ComponentDiv, ComponentTable, ComponentText, InMemoryStatsStorage,
    RemoteStatsStorageRouter, UiServer)
from deeplearning4j_tpu.ui.stats import StatsReport


def _report(i, session="s1", worker="w0", score=1.0):
    return StatsReport(session_id=session, worker_id=worker, iteration=i,
                       timestamp=1000.0 + i, score=score,
                       param_norms={"layer0/W": 1.5})


@pytest.fixture()
def server():
    storage = InMemoryStatsStorage()
    srv = UiServer(storage, port=0).start()
    yield srv, storage
    srv.stop()


def test_server_api_roundtrip(server):
    srv, storage = server
    for i in range(3):
        storage.put_report(_report(i))
    storage.put_report(_report(0, worker="w1"))

    def get(path):
        with urllib.request.urlopen(srv.url + path, timeout=5) as r:
            return json.loads(r.read())

    assert get("/api/sessions") == ["s1"]
    assert get("/api/sessions/s1/workers") == ["w0", "w1"]
    reports = get("/api/sessions/s1/reports")
    assert len(reports) == 4
    assert get("/api/sessions/s1/reports?worker=w1")[0]["worker_id"] == "w1"
    with urllib.request.urlopen(srv.url + "/train/s1", timeout=5) as r:
        page = r.read().decode()
    assert "<svg" in page and "Score vs iteration" in page
    with urllib.request.urlopen(srv.url + "/", timeout=5) as r:
        index = r.read().decode()
    assert "s1" in index


def test_remote_router_ships_reports(server):
    srv, storage = server
    router = RemoteStatsStorageRouter(srv.url)
    for i in range(4):
        router.put_report(_report(i, session="remote"))
    # landed in the server-side storage
    assert len(storage.get_reports("remote")) == 4
    # reads proxy through the API
    assert router.list_sessions() == ["remote"]
    got = router.get_reports("remote")
    assert [r.iteration for r in got] == [0, 1, 2, 3]
    assert got[0].param_norms == {"layer0/W": 1.5}


def test_server_404_and_bad_post(server):
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(srv.url + "/api/nope", timeout=5)
    assert e.value.code == 404
    req = urllib.request.Request(srv.url + "/api/reports", data=b"not json",
                                 method="POST")
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=5)
    assert e.value.code == 400


def test_words_nearest_endpoint():
    """Nearest-neighbor serving (legacy dl4j-scaleout nlp render role)."""
    class FakeWV:
        def words_nearest(self, word, n=10):
            if word != "king":
                raise KeyError(word)
            return ["queen", "prince"][:n]

    storage = InMemoryStatsStorage()
    srv = UiServer(storage, port=0, word_vectors=FakeWV()).start()
    try:
        got = json.loads(urllib.request.urlopen(
            srv.url + "/api/words/nearest?word=king&n=2", timeout=5).read())
        assert got["nearest"] == [["queen", None], ["prince", None]]
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(srv.url + "/api/words/nearest?word=zzz",
                                   timeout=5)
        assert e.value.code == 404
        page = urllib.request.urlopen(srv.url + "/words?word=king",
                                      timeout=5).read().decode()
        assert "queen" in page
    finally:
        srv.stop()


def test_words_endpoint_absent_without_vectors(server):
    srv, _ = server
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(srv.url + "/api/words/nearest?word=x", timeout=5)
    assert e.value.code == 404


def test_component_dsl_roundtrip_and_render():
    rng = np.random.default_rng(0)
    counts, edges = np.histogram(rng.standard_normal(500), bins=10)
    page = ComponentDiv(
        ComponentText("LeNet run", size=18, bold=True),
        ChartLine("score", x=[[0, 1, 2]], y=[[3.0, 2.0, 1.5]],
                  series_names=["score"]),
        ChartScatter("pts", x=[[0, 1]], y=[[1.0, 2.0]]),
        ChartHistogram("W dist", lower=edges[:-1].tolist(),
                       upper=edges[1:].tolist(), counts=counts.tolist()),
        ChartHorizontalBar("norms", labels=["layer0/W", "layer0/b"],
                           values=[1.5, 0.1]),
        ComponentTable(header=["layer", "norm"],
                       content=[["layer0/W", 1.5]], title="params"),
        style="margin:8px",
    )
    blob = json.dumps(page.to_dict())
    back = Component.from_dict(json.loads(blob))
    assert isinstance(back, ComponentDiv) and len(back.children) == 6
    assert json.dumps(back.to_dict()) == blob  # stable round-trip
    html_page = back.render_page()
    assert html_page.startswith("<!DOCTYPE html>")
    for frag in ("LeNet run", "score", "W dist", "layer0/W", "<svg", "<table"):
        assert frag in html_page
    # scatter draws point marks, line draws polylines
    assert "<circle" in back.children[2].render_html()
    assert "<polyline" in back.children[1].render_html()


def test_component_dsl_validation():
    with pytest.raises(ValueError):
        ChartLine("x", x=[[1]], y=[])
    with pytest.raises(ValueError):
        ChartHistogram("h", lower=[0], upper=[1, 2], counts=[1])
    with pytest.raises(ValueError):
        Component.from_dict({"componentType": "NoSuch"})


class TestActivationAndFlowViews:
    """VERDICT r2 missing #2: ConvolutionalIterationListener (activation
    PNG montages) + FlowIterationListener (model-graph view) — a LeNet
    run must render both."""

    def _lenet(self):
        from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        from deeplearning4j_tpu.nn.conf.layers import (
            ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf = (NeuralNetConfiguration.builder()
                .seed(1).learning_rate(0.01).updater("adam").activation("relu")
                .list()
                .layer(ConvolutionLayer(n_out=4, kernel_size=(3, 3)))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(n_out=16))
                .layer(OutputLayer(n_out=3, activation="softmax",
                                   loss_function="mcxent"))
                .set_input_type(InputType.convolutional(10, 10, 1))
                .build())
        return MultiLayerNetwork(conf).init()

    def test_lenet_run_renders_activations_and_flow(self, rng, tmp_path):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.ui.activations import (
            ConvolutionalIterationListener, FlowIterationListener)

        net = self._lenet()
        probe = rng.standard_normal((2, 10, 10, 1)).astype(np.float32)
        conv = ConvolutionalIterationListener(probe, frequency=1,
                                              output_dir=str(tmp_path))
        flow = FlowIterationListener(frequency=1)
        net.set_listeners(conv, flow)
        x = rng.standard_normal((16, 10, 10, 1)).astype(np.float32)
        y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
        net.fit(DataSet(x, y))

        # activation grids rendered for the conv + pool feature maps
        assert conv.latest, "no activation images captured"
        for name, png in conv.latest.items():
            assert png[:8] == b"\x89PNG\r\n\x1a\n", name
            assert len(png) > 100, name
        files = list(tmp_path.glob("iter*_*.png"))
        assert files, "no PNG files written"
        # flow snapshot carries the full layer chain
        assert flow.latest is not None
        names = [l["name"] for l in flow.latest["layers"]]
        assert names == [f"layer{i}" for i in range(4)]

        # and the server serves both views
        storage = InMemoryStatsStorage()
        srv = UiServer(storage, port=0, conv_listener=conv,
                       flow_listener=flow).start()
        try:
            with urllib.request.urlopen(srv.url + "/activations") as r:
                page = r.read().decode()
            assert "data:image/png;base64," in page
            with urllib.request.urlopen(srv.url + "/flow") as r:
                flow_page = r.read().decode()
            assert "<svg" in flow_page and "layer0" in flow_page
            with urllib.request.urlopen(srv.url + "/api/flow") as r:
                info = json.loads(r.read())
            assert info["kind"] == "MultiLayerNetwork"
            assert len(info["layers"]) == 4
        finally:
            srv.stop()

    def test_flow_view_from_live_model_and_graph(self, rng):
        """/flow also renders straight from an attached model, and the
        ComputationGraph DAG keeps its multi-input edges."""
        from deeplearning4j_tpu.nn.graph import ComputationGraphConfiguration
        from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        from deeplearning4j_tpu.ui.activations import (
            model_flow_info, render_flow_svg)

        b = (ComputationGraphConfiguration.GraphBuilder()
             .add_inputs("in")
             .add_layer("d1", DenseLayer(n_in=4, n_out=8), "in")
             .add_layer("d2", DenseLayer(n_in=4, n_out=8), "in")
             .add_vertex("merge", "merge", "d1", "d2")
             .add_layer("out", OutputLayer(n_in=16, n_out=2,
                                           activation="softmax",
                                           loss_function="mcxent"), "merge")
             .set_outputs("out"))
        net = ComputationGraph(b.build()).init()
        info = model_flow_info(net)
        assert info["kind"] == "ComputationGraph"
        merge = next(l for l in info["layers"] if l["name"] == "merge")
        assert set(merge["inputs"]) == {"d1", "d2"}
        svg = render_flow_svg(info)
        assert "<svg" in svg and "merge" in svg

        storage = InMemoryStatsStorage()
        srv = UiServer(storage, port=0, model=net).start()
        try:
            with urllib.request.urlopen(srv.url + "/flow") as r:
                page = r.read().decode()
            assert "merge" in page
        finally:
            srv.stop()


def test_tsne_view_and_api():
    """VERDICT r4 #7: the ui/tsne dashboard role — scatter page + JSON
    API + POST push, fed by plot/tsne.py coordinates."""
    storage = InMemoryStatsStorage()
    coords = [[0.0, 0.0], [1.0, 2.0], [-1.0, 0.5], [2.0, -1.0]]
    labels = ["king", "queen", "cat", "dog"]
    srv = UiServer(storage, port=0, tsne=(coords, labels)).start()
    try:
        def get(path):
            with urllib.request.urlopen(srv.url + path, timeout=5) as r:
                return r.read().decode()

        data = json.loads(get("/api/tsne"))
        assert data["points"] == coords and data["labels"] == labels
        page = get("/tsne")
        assert "<svg" in page and "king" in page and "4 points" in page
        # class-colored mode: repeated labels render a legend, no text spam
        srv.set_tsne(np.asarray(coords), ["a", "a", "b", "b"])
        page = json.loads(get("/api/tsne"))
        assert page["labels"] == ["a", "a", "b", "b"]
        # POST push replaces the embedding (remote-trainer seam)
        req = urllib.request.Request(
            srv.url + "/api/tsne",
            data=json.dumps({"points": [[0, 1], [1, 0]],
                             "labels": ["x", "y"]}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["ok"]
        assert json.loads(get("/api/tsne"))["labels"] == ["x", "y"]
        # bad push is diagnosed, not a 500
        req = urllib.request.Request(
            srv.url + "/api/tsne",
            data=json.dumps({"points": [[0, 1]], "labels": ["x", "y"]}).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=5)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        srv.stop()


def test_tsne_view_unattached_404s():
    storage = InMemoryStatsStorage()
    srv = UiServer(storage, port=0).start()
    try:
        try:
            urllib.request.urlopen(srv.url + "/api/tsne", timeout=5)
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        with urllib.request.urlopen(srv.url + "/tsne", timeout=5) as r:
            assert "no t-SNE data" in r.read().decode()
    finally:
        srv.stop()


def test_tsne_end_to_end_from_model():
    """plot/tsne.py -> UiServer: the full wiring the reference's tsne
    dashboard expects (embedding of real high-dim points)."""
    from deeplearning4j_tpu.plot.tsne import TSNE

    rng = np.random.default_rng(0)
    # two separated gaussian blobs in 16-D
    data = np.vstack([rng.normal(0, 0.1, (10, 16)),
                      rng.normal(3, 0.1, (10, 16))]).astype(np.float32)
    coords = TSNE(n_iter=30, perplexity=5.0).fit_transform(data)
    labels = ["blob0"] * 10 + ["blob1"] * 10
    storage = InMemoryStatsStorage()
    srv = UiServer(storage, port=0, tsne=(coords, labels)).start()
    try:
        with urllib.request.urlopen(srv.url + "/tsne", timeout=5) as r:
            page = r.read().decode()
        assert "<svg" in page and "blob0" in page and "20 points" in page
    finally:
        srv.stop()
