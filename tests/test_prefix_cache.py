"""Cross-request prefix cache tests (serving/prefixcache.py +
nn/kvpool.py refcounts/COW + the scheduler's cached-admission path).

The ISSUE-11 battery: pool refcount semantics (share, last-drop frees,
double-free raises); share/COW admission output BITWISE equal to the
uncached run (greedy and seeded sampling, vs ``generate_eager`` — the
house bar); copy-on-write triggering only on a matched partial tail
block while the originator's outputs stay intact; preempt-a-sharer
freeing only its private tail; deterministic eviction that never
evicts a referenced block; canary-cutover lanes never cross-matching
versions; ``prefix=`` resumes probing the index (warm migration
degrades to a table clone); zero steady-state XLA compiles with the
cache on; seeded kill/preempt/evict interleavings draining to zero
leaked and zero double-freed blocks (plus ``stress_faultinject``
quick_check section 8); the router's cache-aware affinity tiebreak;
and the ``dl4j_prefixcache_*`` schema pinning.
"""

import sys

import numpy as np
import pytest

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.faultinject import BurstKill
from deeplearning4j_tpu.models.zoo.transformer import gpt
from deeplearning4j_tpu.nn.generate import generate_eager
from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.serving.continuous import ContinuousDecodeScheduler
from deeplearning4j_tpu.serving.prefixcache import PrefixCache
from deeplearning4j_tpu.serving.registry import ModelRegistry
from deeplearning4j_tpu.serving.router import InferenceRouter

VOCAB = 11


def _tiny_gpt(seed=0, **kw):
    return gpt(vocab_size=VOCAB, d_model=16, n_layers=2, num_heads=2,
               max_len=32, compute_dtype="float32", learning_rate=0.01,
               seed=seed, **kw).init()


@pytest.fixture
def fresh_registry():
    prev = monitor.set_registry(monitor.MetricsRegistry())
    yield monitor.get_registry()
    monitor.set_registry(prev)


def _sched(net, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("burst_tokens", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("start", False)
    kw.setdefault("prefix_cache", True)
    return ContinuousDecodeScheduler(net=net, **kw)


def _drive(sched, futures, max_steps=400):
    for _ in range(max_steps):
        if all(f.done() for f in futures):
            return
        sched.step()
    raise AssertionError(
        f"schedule did not converge in {max_steps} steps; "
        f"events={list(sched.events)}")


def _assert_drained_clean(s):
    """Conservation after drain: free + cache-held == total, and
    clearing the cache returns the pool to fully free (zero leaked,
    zero double-freed — clear() raises on a double free)."""
    st = s.stats()
    cached = sum(c.cached_blocks() for c in s.prefix_caches())
    assert st["pool"]["blocks_free"] + cached == st["pool"]["blocks_total"], \
        (st["pool"], cached)
    for c in s.prefix_caches():
        c.clear()
    st = s.stats()
    assert st["pool"]["blocks_free"] == st["pool"]["blocks_total"]


# ------------------------------------------------- pool refcounts / COW

def test_pool_refcount_share_and_double_free():
    pool = PagedKVCachePool(9, 4, num_layers=1, num_heads=1, head_dim=2)
    a = pool.alloc(3)
    assert a == [1, 2, 3] and pool.free_count == 5
    pool.share_blocks(a[:2])            # a second holder on 1, 2
    assert pool.ref_count(1) == 2 and pool.ref_count(3) == 1
    # "preempt a sharer frees only its private tail": the seq's free
    # drops one ref everywhere — only block 3 returns to the free list
    pool.free_blocks(a)
    assert pool.free_count == 6
    assert pool.ref_count(1) == 1 and pool.ref_count(2) == 1
    assert pool.ref_count(3) == 0
    assert pool.shared_count() == 0
    # the cache's later release frees them for real
    pool.free_blocks([1, 2])
    assert pool.free_count == 8
    with pytest.raises(RuntimeError, match="double free"):
        pool.free_blocks([1])
    with pytest.raises(ValueError):
        pool.share_blocks([4])          # free block: nobody owns it
    with pytest.raises(ValueError):
        pool.share_blocks([0])          # the trash block, never


def test_pool_reclaimer_unifies_eviction_with_free_list():
    pool = PagedKVCachePool(5, 4, num_layers=1, num_heads=1, head_dim=2)
    cache = PrefixCache(pool)
    a = pool.alloc(4)
    cache.insert(("m", 1), list(range(16)), a)   # 4 full blocks cached
    pool.free_blocks(a)                          # seq gone; cache holds 4
    assert pool.free_count == 0
    # exhausted pool: alloc reclaims cached-but-unreferenced blocks —
    # LEAVES first (evicting a chain root would orphan its children),
    # so the deepest blocks (4, then 3) rejoin the sorted free list
    got = pool.alloc(2)
    assert got == [3, 4]
    assert cache.cached_blocks() == 2
    assert cache.stats()["evictions"] == 2
    # the surviving chain head still matches
    m, full, _ = cache.match(("m", 1), list(range(16)))
    assert m == 8 and full == [1, 2]
    pool.free_blocks(full)


# ----------------------------------------------------- bitwise parity

def test_shared_prefix_output_bitwise_vs_unshared(rng):
    """Cache-hit admissions (table clone + tail prefill) must produce
    BITWISE the tokens of the uncached run — greedy AND seeded
    sampling, pinned against generate_eager."""
    net = _tiny_gpt()
    pre = rng.integers(0, VOCAB, (1, 12))
    for sampler in ({}, {"temperature": 0.8, "top_k": 5, "seed": 7}):
        s = _sched(net)
        want = generate_eager(net, pre, 8, **sampler)
        f0 = s.submit(pre, 8, **sampler)
        _drive(s, [f0])
        assert np.array_equal(f0.result(0), want), ("cold", sampler)
        # warm: the same prompt matches its cached prefix
        f1 = s.submit(pre, 8, **sampler)
        _drive(s, [f1])
        assert np.array_equal(f1.result(0), want), ("warm", sampler)
        st = s.stats()
        assert st["prefix_cache"]["hits"] >= 1
        assert st["prefix_cache"]["saved_prefill_tokens"] > 0
        # the warm admission computed fewer prefill tokens
        assert st["prefill_tokens_computed"] < 2 * pre.shape[1]
        _assert_drained_clean(s)


def test_distinct_tails_share_one_preamble(rng):
    """The shared-system-prompt shape: N users, one preamble, distinct
    tails — every request after the first hits, all outputs bitwise."""
    net = _tiny_gpt()
    s = _sched(net)
    preamble = rng.integers(0, VOCAB, (1, 8))
    prompts = [np.concatenate(
        [preamble, rng.integers(0, VOCAB, (1, 4))], axis=1)
        for _ in range(4)]
    f0 = s.submit(prompts[0], 6)
    _drive(s, [f0])
    assert np.array_equal(f0.result(0), generate_eager(net, prompts[0], 6))
    futs = [s.submit(p, 6) for p in prompts[1:]]
    _drive(s, futs)
    for f, p in zip(futs, prompts[1:]):
        assert np.array_equal(f.result(0), generate_eager(net, p, 6))
    st = s.stats()["prefix_cache"]
    assert st["hits"] >= len(prompts) - 1
    _assert_drained_clean(s)


def test_cow_partial_tail_block(rng):
    """A match reaching INTO a cached partial tail block triggers
    copy-on-write (the only block a sharer ever writes), the sharer's
    output is bitwise-correct, and the originator's cached content
    survives untouched."""
    net = _tiny_gpt()
    s = _sched(net)
    # A: 10-token prompt, 2 generated -> 11 written positions =
    # 2 full blocks + a partial with fill 3
    pA = rng.integers(0, VOCAB, (1, 10))
    wantA = generate_eager(net, pA, 2)
    fA = s.submit(pA, 2)
    _drive(s, [fA])
    assert np.array_equal(fA.result(0), wantA)
    # B: A's prompt + its first generated token (11 tokens) — the match
    # covers both full blocks and 2 tokens of the partial
    pB = np.concatenate([pA, wantA[:, 10:11]], axis=1)
    wantB = generate_eager(net, pB, 6)
    fB = s.submit(pB, 6)
    _drive(s, [fB])
    assert np.array_equal(fB.result(0), wantB)
    st = s.stats()["prefix_cache"]
    assert st["cow_copies"] >= 1
    # the originator's cached prefix still serves bit-identically
    fA2 = s.submit(pA, 2)
    _drive(s, [fA2])
    assert np.array_equal(fA2.result(0), wantA)
    _assert_drained_clean(s)


# ------------------------------------------------ eviction / isolation

def test_eviction_never_evicts_referenced_block():
    pool = PagedKVCachePool(9, 4, num_layers=1, num_heads=1, head_dim=2)
    cache = PrefixCache(pool)
    a = pool.alloc(3)
    tokens = list(range(12))            # 3 full blocks
    cache.insert(("m", 1), tokens, a)
    pool.free_blocks(a)                 # only the cache holds them now
    m, full, part = cache.match(("m", 1), tokens)  # usable 11 -> 2 full
    assert m == 8 and len(full) == 2 and part is None
    # a "sequence" now references blocks 1,2 (refcount 2); block 3 is
    # cached-but-unreferenced — the ONLY legal eviction victim
    freed = cache.reclaim(10)
    assert freed == 1
    assert cache.cached_blocks() == 2
    assert pool.ref_count(full[0]) == 2 and pool.ref_count(full[1]) == 2
    pool.free_blocks(full)              # the sequence retires its hold
    assert cache.reclaim(10) == 2       # now they may go
    assert pool.free_count == pool.total_blocks


def test_deterministic_lru_eviction_order():
    pool = PagedKVCachePool(9, 4, num_layers=1, num_heads=1, head_dim=2)
    cache = PrefixCache(pool)
    a = pool.alloc(2)
    cache.insert(("m", 1), list(range(8)), a)          # older chain
    pool.free_blocks(a)
    b = pool.alloc(2)
    cache.insert(("m", 1), [9, 9, 9, 9, 8, 8, 8, 8], b)  # newer chain
    pool.free_blocks(b)
    # LRU (logical clock), leaves first: the OLDER chain's leaf goes
    # first, then its root; the newer chain survives a 2-block reclaim
    assert cache.reclaim(2) == 2
    m, full, _ = cache.match(("m", 1), [9, 9, 9, 9, 8, 8, 8, 8, 1])
    assert m == 8 and len(full) == 2
    pool.free_blocks(full)


def test_canary_lanes_never_cross_match_versions(rng, fresh_registry):
    """Two versions sharing one pool (same KV spec) must keep separate
    radix roots: the canary's probe never matches the stable's cached
    blocks — its K/V came from different params."""
    net1, net2 = _tiny_gpt(seed=1), _tiny_gpt(seed=9)
    reg = ModelRegistry()
    reg.register("lm", net=net1)
    eng = ParallelInference(registry=reg, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, prefix_cache=True)
    try:
        p = rng.integers(0, VOCAB, (1, 9))
        assert np.array_equal(
            eng.submit_generate(p, 8, model="lm", session="s1").result(30),
            generate_eager(net1, p, 8))
        reg.deploy("lm", net=net2)      # cutover: new sessions get v2
        sched = eng._continuous_scheduler()
        hits_before = sched.stats()["prefix_cache"]["hits"]
        # same prompt, new version: MUST miss (and be correct for v2)
        assert np.array_equal(
            eng.submit_generate(p, 8, model="lm", session="s2").result(30),
            generate_eager(net2, p, 8))
        st = sched.stats()
        assert st["prefix_cache"]["hits"] == hits_before
        assert st["lanes"] == 2 and len(st["pools"]) == 1
        # v1's cache still serves v1 (session pin) bit-identically
        assert np.array_equal(
            eng.submit_generate(p, 8, model="lm", session="s1").result(30),
            generate_eager(net1, p, 8))
        assert sched.stats()["prefix_cache"]["hits"] == hits_before + 1
    finally:
        eng.shutdown()


# ----------------------------------------------------- preempt / resume

def test_preempt_sharer_keeps_cache_and_stays_bitwise(rng):
    """A preempted sharer drops only its own references (the cache's
    interior pins survive — its resume re-matches them), and every
    output still equals the uninterrupted eager run."""
    net = _tiny_gpt()
    prompts = [rng.integers(0, VOCAB, (1, 5)) for _ in range(3)]

    def run():
        s = _sched(net, num_blocks=12)
        futs = [s.submit(p, 10) for p in prompts]
        _drive(s, futs)
        return s, futs

    s1, futs1 = run()
    assert s1.stats()["preemptions"] > 0
    for f, p in zip(futs1, prompts):
        assert np.array_equal(f.result(0), generate_eager(net, p, 10))
    # the whole schedule (admits, COWs, preempts, evictions) replays
    # bit-identically — cache clocks are logical, never wall time
    s2, futs2 = run()
    assert list(s1.events) == list(s2.events)
    for a, b in zip(futs1, futs2):
        assert np.array_equal(a.result(0), b.result(0))
    _assert_drained_clean(s1)
    _assert_drained_clean(s2)


def test_prefix_resume_probes_index_warm(rng):
    """The migration contract with a warm cache: a prefix= resume
    matches the cached run and re-prefills only the unmatched tail —
    the token-gap shrinks toward a table clone."""
    net = _tiny_gpt()
    s = _sched(net)
    p = rng.integers(0, VOCAB, (1, 8))
    want = generate_eager(net, p, 12)
    f0 = s.submit(p, 12)                 # seeds the cache on retire
    _drive(s, [f0])
    assert np.array_equal(f0.result(0), want)
    prefix = np.asarray([int(t) for t in want[0, 8:14]])
    f1 = s.submit(p, 12, prefix=prefix)
    _drive(s, [f1])
    assert np.array_equal(f1.result(0), want)
    st = s.stats()
    cold_cost = p.shape[1] + len(prefix)
    assert st["resume_reprefill_tokens"] < cold_cost, st
    assert st["prefix_cache"]["hits"] >= 1
    _assert_drained_clean(s)


# ------------------------------------------------- faults / accounting

@pytest.mark.faultinject
def test_kill_preempt_evict_interleaving_zero_leaks(rng, fresh_registry):
    """Seeded kill/preempt/evict interleavings (BurstKill mid-drill, a
    pool small enough to preempt and reclaim) drain to ZERO leaked and
    ZERO double-freed blocks, deterministically across replays."""
    net = _tiny_gpt()
    prompts = [rng.integers(0, VOCAB, (1, 5)) for _ in range(4)]

    def run():
        kill = BurstKill(after=2, failures=1)
        s = _sched(net, num_blocks=12, burst_hook=kill)
        futs = [s.submit(p, 10, seed=i) for i, p in enumerate(prompts)]
        for _ in range(400):
            if all(f.done() for f in futs):
                break
            s.step()
        outcomes = []
        for f in futs:
            try:
                outcomes.append(f.result(0).tolist())
            except BaseException as e:
                outcomes.append(type(e).__name__)
        return s, outcomes

    s1, out1 = run()
    assert any(isinstance(o, str) for o in out1), "kill never landed"
    assert any(not isinstance(o, str) for o in out1), "nothing survived"
    _assert_drained_clean(s1)
    s2, out2 = run()
    assert out1 == out2
    assert list(s1.events) == list(s2.events)
    _assert_drained_clean(s2)
    assert fresh_registry.family_total(monitor.FAULT_EVENTS_COUNTER) >= 1


def test_quick_check_section8_deterministic():
    """stress_faultinject quick_check carries the prefix-cache
    accounting drill (section 8) and stays deterministic."""
    sys.path.insert(0, "scripts")
    try:
        from stress_faultinject import _scenario_log, quick_check
    finally:
        sys.path.pop(0)
    log = _scenario_log(0)
    assert "pc " in log and "pc double-free caught" in log
    assert "leaked=0" in log
    assert quick_check(seeds=(0, 1), runs_per_seed=2) == []


# -------------------------------------------------- zero compiles / router

def test_zero_steady_state_compiles_with_cache(rng, fresh_registry):
    """Warmup covers the tail-prefill and COW-copy ladders too: cached
    admissions perform zero steady-state XLA compiles."""
    net = _tiny_gpt()
    eng = ParallelInference(net, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, prefix_cache=True)
    try:
        assert eng.warmup_generate([4, 8], 8) > 0
        miss0 = fresh_registry.family_total(monitor.JIT_CACHE_MISS_COUNTER)
        shared = rng.integers(0, VOCAB, (1, 5))
        # the first request RETIRES before the rest submit — insert is
        # on-retire, so concurrent same-batch admissions cannot hit
        p0 = np.concatenate([shared, rng.integers(0, VOCAB, (1, 3))],
                            axis=1)
        eng.submit_generate(p0, 6, seed=0).result(60)
        futs = []
        for i in range(1, 4):
            p = np.concatenate(
                [shared, rng.integers(0, VOCAB, (1, 3))], axis=1)
            futs.append(eng.submit_generate(p, 6, seed=i))
        for f in futs:
            f.result(60)
        assert fresh_registry.family_total(
            monitor.JIT_CACHE_MISS_COUNTER) == miss0
        assert eng.stats()["scheduler"]["prefix_cache"]["hits"] >= 1
    finally:
        eng.shutdown()


class _StubEp:
    """Minimal alive endpoint for router-admission unit tests."""

    def __init__(self, name):
        self.name = name
        self.last_seen = 0.0

    def alive(self):
        return True

    def stats(self):
        return {"queue_depth": 0}


def test_router_prefix_affinity_tiebreak(rng):
    """When admission estimates tie exactly, the endpoint that last
    served the prompt's prefix wins; otherwise name order — and
    health/deadline behavior is untouched."""
    router = InferenceRouter(endpoints=[_StubEp("b"), _StubEp("a")])
    prompt = rng.integers(0, VOCAB, (1, 6))
    key = router._prefix_key(prompt, None)
    assert key is not None
    # _admit returns (endpoint, est_wait_ms, est_total_ms) so the
    # admission span can record its estimate inputs (ISSUE 13)
    # cold tie: stable name order
    assert router._admit(None, "interactive", None, None,
                         key)[0].endpoint.name == "a"
    # b holds the prefix now: the tie breaks toward the warm cache
    router._note_prefix_owner(key, "b")
    assert router._admit(None, "interactive", None, None,
                         key)[0].endpoint.name == "b"
    # a different prompt: no owner, back to name order
    other = router._prefix_key(rng.integers(0, VOCAB, (1, 6)) + 100, None)
    assert router._admit(None, "interactive", None, None,
                         other)[0].endpoint.name == "a"
    router.close()


def test_endpoint_stats_and_snapshot_surface_cache(rng):
    """stats()/fleet_snapshot expose the prefix-cache summary (count +
    bytes + hit rate) — the heartbeat-carried affinity view."""
    from deeplearning4j_tpu.serving.endpoint import LocalEndpoint
    net = _tiny_gpt()
    eng = ParallelInference(net, replicas=1, continuous=True,
                            decode_slots=4, decode_burst=4,
                            kv_block_size=4, prefix_cache=True)
    router = InferenceRouter()
    try:
        router.add_endpoint(LocalEndpoint(eng, name="e0"))
        p = rng.integers(0, VOCAB, (1, 9))
        want = generate_eager(net, p, 6)
        assert np.array_equal(
            router.submit_generate(p, 6).result(30), want)
        assert np.array_equal(
            router.submit_generate(p, 6).result(30), want)
        pc = eng.stats()["scheduler"]["prefix_cache"]
        assert pc["hits"] >= 1 and pc["cached_bytes"] > 0
        assert 0.0 < pc["hit_rate"] <= 1.0
        snap = router.fleet_snapshot()
        ep = snap["endpoints"]["e0"]["prefix_cache"]
        assert ep is not None
        assert ep["cached_blocks"] > 0 and ep["cached_bytes"] > 0
    finally:
        router.close()
        eng.shutdown()


def test_metric_schema_pinned(rng, fresh_registry):
    """The dl4j_prefixcache_* family validates as Prometheus exposition
    and is pinned in KNOWN_DL4J_METRICS."""
    sys.path.insert(0, "scripts")
    try:
        from check_telemetry_schema import (KNOWN_DL4J_METRICS,
                                            validate_known_metrics,
                                            validate_prometheus_text)
    finally:
        sys.path.pop(0)
    for name in ("dl4j_prefixcache_hits_total",
                 "dl4j_prefixcache_misses_total",
                 "dl4j_prefixcache_evictions_total",
                 "dl4j_prefixcache_cow_copies_total",
                 "dl4j_prefixcache_cached_blocks",
                 "dl4j_prefixcache_shared_blocks",
                 "dl4j_prefixcache_saved_prefill_tokens_total"):
        assert name in KNOWN_DL4J_METRICS, name
    net = _tiny_gpt()
    s = _sched(net, num_blocks=12)
    p = rng.integers(0, VOCAB, (1, 10))
    futs = [s.submit(p, 8)]
    _drive(s, futs)
    futs = [s.submit(p, 8)]              # a hit
    _drive(s, futs)
    futs = [s.submit(rng.integers(0, VOCAB, (1, 12)), 10)
            for _ in range(3)]           # pressure: evictions
    _drive(s, futs)
    text = fresh_registry.prometheus_text()
    assert validate_prometheus_text(text) == []
    assert validate_known_metrics(text) == []
    for family in ("dl4j_prefixcache_hits_total",
                   "dl4j_prefixcache_misses_total",
                   "dl4j_prefixcache_cached_blocks",
                   "dl4j_prefixcache_shared_blocks",
                   "dl4j_prefixcache_saved_prefill_tokens_total"):
        assert f"# TYPE {family}" in text, family
    _assert_drained_clean(s)
