"""SP-tree / quad-tree + Barnes-Hut force tests.

Parity: ``clustering/sptree/SpTree.java`` (computeNonEdgeForces),
``clustering/quadtree/QuadTree.java``, ``plot/BarnesHutTsne.java:63``.
The theta→0 case is the correctness oracle: every cell gets opened to
its leaves, so Barnes-Hut must equal the exact O(n²) gradient.
"""

import time

import numpy as np
import pytest

from deeplearning4j_tpu.clustering.sptree import (
    QuadTree, SpTree, barnes_hut_tsne_gradient)


def _exact_tsne_gradient(y, p):
    """Dense reference gradient: 4 Σ_j (p_ij - q_ij) num_ij (y_i - y_j)."""
    n = y.shape[0]
    d = y[:, None, :] - y[None, :, :]
    num = 1.0 / (1.0 + np.einsum("ijk,ijk->ij", d, d))
    np.fill_diagonal(num, 0.0)
    q = num / num.sum()
    pq = (p - q) * num
    return 4.0 * np.einsum("ij,ijk->ik", pq, d)


def _dense_p(rng, n):
    p = rng.random((n, n))
    p = (p + p.T) / 2.0
    np.fill_diagonal(p, 0.0)
    return p / p.sum()


def _csr(p):
    n = p.shape[0]
    rows = [0]
    cols, vals = [], []
    for i in range(n):
        js = np.nonzero(p[i])[0]
        cols.extend(js.tolist())
        vals.extend(p[i, js].tolist())
        rows.append(len(cols))
    return np.array(rows), np.array(cols), np.array(vals)


def test_tree_invariants(rng):
    pts = rng.standard_normal((200, 3))
    tree = SpTree(pts)
    assert tree.n == 200 and tree.d == 3
    assert tree._count[0] == 200
    np.testing.assert_allclose(tree._com[0], pts.mean(0), atol=1e-12)
    assert tree.depth() >= 2
    # order array is a permutation: every point lands in exactly one leaf
    assert sorted(tree._order.tolist()) == list(range(200))


def test_duplicate_points_terminate():
    pts = np.ones((50, 2))
    pts[:25] = 0.0
    tree = SpTree(pts)  # must not recurse forever on duplicates
    assert tree._count[0] == 50
    force, sum_q = tree.compute_non_edge_forces(np.array([0.0, 0.0]), 0.5)
    # 24 coincident points are skipped (d2=0), 25 at distance sqrt(2)
    assert sum_q == pytest.approx(25 / 3.0)
    assert np.all(np.isfinite(force))


def test_quadtree_is_2d():
    with pytest.raises(ValueError):
        QuadTree(np.zeros((4, 3)))
    tree = QuadTree(np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0], [1.0, 1.0]]))
    assert tree._children[0].size == 4  # 2^2 children


def test_barnes_hut_theta0_equals_exact(rng):
    n = 120
    y = rng.standard_normal((n, 2))
    p = _dense_p(rng, n)
    grad_bh = barnes_hut_tsne_gradient(y, *_csr(p), theta=0.0)
    grad_exact = _exact_tsne_gradient(y, p)
    np.testing.assert_allclose(grad_bh, grad_exact, rtol=1e-8, atol=1e-12)


def test_barnes_hut_theta_small_error(rng):
    n = 300
    y = rng.standard_normal((n, 2)) * 3.0
    p = _dense_p(rng, n)
    grad_exact = _exact_tsne_gradient(y, p)
    grad_bh = barnes_hut_tsne_gradient(y, *_csr(p), theta=0.4)
    rel = (np.linalg.norm(grad_bh - grad_exact)
           / max(np.linalg.norm(grad_exact), 1e-300))
    assert rel < 0.03, f"theta=0.4 relative error {rel:.4f}"


def test_3d_embedding_forces(rng):
    """SpTree generalizes past 2-D (oct-tree case)."""
    n = 80
    y = rng.standard_normal((n, 3))
    p = _dense_p(rng, n)
    grad_bh = barnes_hut_tsne_gradient(y, *_csr(p), theta=0.0)
    np.testing.assert_allclose(grad_bh, _exact_tsne_gradient(y, p),
                               rtol=1e-8, atol=1e-12)


def test_exact_device_vs_bh_host_benchmark(rng):
    """Documents the design tradeoff (tsne.py docstring): at t-SNE scale
    the exact device path is competitive with the asymptotically-better
    host tree, which is why the TPU path stays exact. Informational —
    asserts only that both produce finite, agreeing-magnitude output."""
    import jax
    import jax.numpy as jnp

    n = 1000
    y = rng.standard_normal((n, 2)).astype(np.float32)
    p = _dense_p(rng, n).astype(np.float32)

    @jax.jit
    def exact(yj, pj):
        d = yj[:, None, :] - yj[None, :, :]
        num = 1.0 / (1.0 + jnp.einsum("ijk,ijk->ij", d, d))
        num = num * (1.0 - jnp.eye(n))
        q = num / jnp.sum(num)
        pq = (pj - q) * num
        return 4.0 * jnp.einsum("ij,ijk->ik", pq, d)

    g_dev = np.asarray(exact(y, p))  # compile
    t0 = time.perf_counter()
    g_dev = np.asarray(exact(y, p))
    t_dev = time.perf_counter() - t0

    rows, cols, vals = _csr(p)
    t0 = time.perf_counter()
    g_host = barnes_hut_tsne_gradient(y, rows, cols, vals, theta=0.5)
    t_host = time.perf_counter() - t0

    assert np.all(np.isfinite(g_dev)) and np.all(np.isfinite(g_host))
    rel = np.linalg.norm(g_host - g_dev) / np.linalg.norm(g_dev)
    assert rel < 0.05
    print(f"\nn={n}: exact-device {t_dev*1e3:.1f}ms vs BH-host {t_host*1e3:.1f}ms "
          f"(rel diff {rel:.4f})")
