"""Data plane tests: record readers, DataSet bridges, normalizers,
CIFAR/LFW loaders.

Parity: ``RecordReaderDataSetIterator.java:54``,
``SequenceRecordReaderDataSetIterator.java``,
``RecordReaderMultiDataSetIterator.java``, ``CifarDataSetIterator.java:17``,
ND4J normalizers.
"""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets.cifar import CifarDataSetIterator, load_cifar10
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator
from deeplearning4j_tpu.datasets.lfw import load_lfw
from deeplearning4j_tpu.datasets.normalizers import (
    ImagePreProcessingScaler, NormalizerMinMaxScaler, NormalizerStandardize)
from deeplearning4j_tpu.datavec import (
    CSVRecordReader, CSVSequenceRecordReader, ImageRecordReader,
    RecordReaderDataSetIterator, RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator)


CSV = ["1.0,2.0,0", "3.0,4.0,1", "5.0,6.0,2", "7.0,8.0,0", "9.0,10.0,1"]


def test_csv_reader_to_dataset():
    it = RecordReaderDataSetIterator(CSVRecordReader(CSV), batch_size=2,
                                     label_index=-1, num_classes=3)
    ds = it.next()
    np.testing.assert_allclose(ds.features, [[1, 2], [3, 4]])
    np.testing.assert_allclose(ds.labels, [[1, 0, 0], [0, 1, 0]])
    batches = list(it)
    assert [b.num_examples() for b in batches] == [2, 2, 1]


def test_csv_reader_regression_and_header():
    lines = ["a,b,target"] + CSV
    it = RecordReaderDataSetIterator(
        CSVRecordReader(lines, skip_lines=1), batch_size=5,
        label_index=-1, regression=True)
    ds = it.next()
    assert ds.labels.shape == (5, 1)
    np.testing.assert_allclose(ds.labels.ravel(), [0, 1, 2, 0, 1])


def test_sequence_reader_padding_and_masks(tmp_path):
    # two sequence files of different lengths -> padded + masked batch
    f1 = tmp_path / "s1.csv"
    f1.write_text("1,2\n3,4\n5,6\n")
    f2 = tmp_path / "s2.csv"
    f2.write_text("7,8\n9,10\n")
    l1 = tmp_path / "l1.csv"
    l1.write_text("0\n1\n0\n")
    l2 = tmp_path / "l2.csv"
    l2.write_text("1\n1\n")
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader([str(f1), str(f2)]),
        CSVSequenceRecordReader([str(l1), str(l2)]),
        batch_size=2, num_classes=2)
    ds = it.next()
    assert ds.features.shape == (2, 3, 2)
    np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [1, 1, 0]])
    np.testing.assert_allclose(ds.labels[0, 1], [0, 1])
    np.testing.assert_allclose(ds.features[1, 2], [0, 0])  # padded


def test_image_record_reader(tmp_path):
    from PIL import Image
    for label in ("cat", "dog"):
        d = tmp_path / label
        d.mkdir()
        for i in range(2):
            Image.new("RGB", (10, 8), color=(i * 50, 100, 150)).save(
                d / f"{i}.png")
    reader = ImageRecordReader(height=4, width=5, channels=3,
                               root_dir=str(tmp_path))
    assert reader.labels == ["cat", "dog"]
    it = RecordReaderDataSetIterator(reader, batch_size=4, num_classes=2)
    ds = it.next()
    assert ds.features.shape == (4, 4, 5, 3)
    assert ds.labels.sum() == 4


def test_multi_dataset_iterator():
    it = (RecordReaderMultiDataSetIterator(batch_size=2)
          .add_reader("r", CSVRecordReader(CSV))
          .add_input("r", 0, 2)
          .add_output_one_hot("r", 2, 3))
    mds = it.next()
    assert mds.features[0].shape == (2, 2)
    assert mds.labels[0].shape == (2, 3)


def test_normalizer_standardize_roundtrip(rng, tmp_path):
    x = rng.normal(5.0, 3.0, (64, 4)).astype(np.float32)
    ds = DataSet(x, np.zeros((64, 1), np.float32))
    norm = NormalizerStandardize().fit(ListDataSetIterator(ds, 16))
    t = norm.transform(ds)
    assert abs(t.features.mean()) < 1e-4
    assert abs(t.features.std() - 1.0) < 1e-2
    back = norm.revert(t)
    np.testing.assert_allclose(back.features, x, atol=1e-4)
    # persistence
    p = str(tmp_path / "norm.json")
    norm.save(p)
    norm2 = NormalizerStandardize.load(p)
    np.testing.assert_allclose(norm2.transform(ds).features, t.features)


def test_normalizer_minmax_and_image_scaler(rng):
    x = rng.uniform(-3, 7, (32, 5)).astype(np.float32)
    ds = DataSet(x, np.zeros((32, 1), np.float32))
    mm = NormalizerMinMaxScaler().fit(ds)
    t = mm.transform(ds)
    assert t.features.min() >= -1e-6 and t.features.max() <= 1 + 1e-6
    np.testing.assert_allclose(mm.revert(t).features, x, atol=1e-4)
    img = DataSet(np.full((2, 3, 3, 1), 255.0, np.float32),
                  np.zeros((2, 1), np.float32))
    np.testing.assert_allclose(
        ImagePreProcessingScaler().transform(img).features, 1.0)


def test_cifar_and_lfw_loaders():
    ds = load_cifar10(train=True, num_examples=32)
    assert ds.features.shape == (32, 32, 32, 3)
    assert ds.labels.shape == (32, 10)
    assert 0.0 <= ds.features.min() and ds.features.max() <= 1.0
    it = CifarDataSetIterator(batch=8, num_examples=16)
    assert sum(1 for _ in it) == 2
    lfw = load_lfw(num_examples=8, image_size=(16, 16))
    assert lfw.features.shape == (8, 16, 16, 3)


def test_train_from_record_reader_end_to_end(rng):
    """VERDICT r1 #4 'done' criterion: a network trains from a record
    reader through the async-prefetch fit path."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    lines = [f"{rng.normal()},{rng.normal()},{i % 3}" for i in range(48)]
    conf = (NeuralNetConfiguration.builder().seed(3).learning_rate(0.1)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=2, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    for _ in range(3):
        it = RecordReaderDataSetIterator(CSVRecordReader(lines), batch_size=16,
                                         label_index=-1, num_classes=3)
        net.fit(it)
    assert np.isfinite(net.score())


def test_string_labels_deterministic_order():
    """Label indices must come from the sorted label set, not encounter
    order, so independently built train/test iterators agree."""
    a = ["1,dog", "2,cat", "3,dog"]
    b = ["4,cat", "5,dog"]
    ita = RecordReaderDataSetIterator(CSVRecordReader(a), 8, num_classes=2)
    itb = RecordReaderDataSetIterator(CSVRecordReader(b), 8, num_classes=2)
    da, db = ita.next(), itb.next()
    # cat=0, dog=1 in both regardless of encounter order
    np.testing.assert_allclose(da.labels, [[0, 1], [1, 0], [0, 1]])
    np.testing.assert_allclose(db.labels, [[1, 0], [0, 1]])


def test_sequence_align_end(tmp_path):
    f1 = tmp_path / "a.csv"
    f1.write_text("1,1\n2,2\n3,3\n")
    f2 = tmp_path / "b.csv"
    f2.write_text("9,9\n")
    it = SequenceRecordReaderDataSetIterator(
        CSVSequenceRecordReader([str(f1), str(f2)]), None, 2, align="end")
    ds = it.next()
    np.testing.assert_allclose(ds.features_mask, [[1, 1, 1], [0, 0, 1]])
    np.testing.assert_allclose(ds.features[1, 2], [9, 9])  # last step aligned
    np.testing.assert_allclose(ds.features[1, 0], [0, 0])
