"""Character-level GravesLSTM language model + sampling.

The reference's GravesLSTMCharModellingExample role: LSTM stack over
one-hot characters, TBPTT-capable fit, stateful rnn_time_step sampling.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

_CORPUS = ("the quick brown fox jumps over the lazy dog. "
           "pack my box with five dozen liquor jugs. ") * 200


def main(smoke: bool = False):
    chars = sorted(set(_CORPUS))
    vocab = len(chars)
    idx = {c: i for i, c in enumerate(chars)}
    ids = np.array([idx[c] for c in _CORPUS], np.int64)

    seq, hidden, epochs = (32, 64, 2) if smoke else (64, 256, 20)
    n = (len(ids) - 1) // seq * seq
    x_ids = ids[:n].reshape(-1, seq)
    y_ids = ids[1:n + 1].reshape(-1, seq)
    eye = np.eye(vocab, dtype=np.float32)
    data = DataSet(eye[x_ids], eye[y_ids])

    net = MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(12).learning_rate(0.01).updater("adam").activation("tanh")
         .list()
         .layer(GravesLSTM(n_in=vocab, n_out=hidden))
         .layer(RnnOutputLayer(n_in=hidden, n_out=vocab,
                               activation="softmax", loss_function="mcxent"))
         .build())).init()

    batch = min(64, data.num_examples())
    staged = net.stage_scan(data, batch)
    scores = net.fit_scan(None, batch, epochs=epochs, staged=staged)
    print(f"final score {scores[-1]:.4f}")

    # stateful sampling via the compiled rnn_time_step path
    rng = np.random.default_rng(0)
    net.rnn_clear_previous_state()
    cur = idx["t"]
    out = ["t"]
    for _ in range(120 if not smoke else 20):
        probs = np.asarray(net.rnn_time_step(eye[[cur]])).ravel()
        cur = int(rng.choice(vocab, p=probs / probs.sum()))
        out.append(chars[cur])
    print("sample:", "".join(out))
    return float(scores[-1])


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    main(smoke=ap.parse_args().smoke)
