"""Training from a disk-staged dataset — the larger-than-RAM plane.

A data stream is spilled to uniform ``.npz`` batches
(``datasets/export.py``, the BatchAndExport role), then a net trains
straight from the files holding ONE batch in host RAM at a time, with a
resumable cursor demonstrating mid-epoch preemption recovery.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.export import (
    ExportedDataSetIterator,
    export_dataset,
)
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def main(smoke: bool = False):
    rng = np.random.default_rng(0)
    chunks, per, f, c = (4, 64, 8, 3) if smoke else (40, 2048, 32, 10)
    centers = rng.standard_normal((c, f)) * 2.0

    def stream():
        """Simulates a source that never fits in RAM at once."""
        for _ in range(chunks):
            ids = rng.integers(0, c, per)
            x = (centers[ids] + 0.5 * rng.standard_normal((per, f)))
            yield DataSet(x.astype(np.float32),
                          np.eye(c, dtype=np.float32)[ids])

    outdir = tempfile.mkdtemp(prefix="dl4j_export_")
    n_files = export_dataset(stream(), outdir, batch_size=per)
    print(f"spilled {chunks * per} examples to {n_files} files in {outdir}")

    conf = (NeuralNetConfiguration.builder().seed(7).learning_rate(0.05)
            .updater("adam").activation("tanh").list()
            .layer(DenseLayer(n_in=f, n_out=32))
            .layer(OutputLayer(n_in=32, n_out=c, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    it = ExportedDataSetIterator(outdir, shuffle=True, seed=1)
    epochs = 2 if smoke else 10
    for _ in range(epochs):
        net.fit(it)
        it.reset()
    score = net.score()

    # resumable cursor: a "preempted" run continues mid-epoch
    it2 = ExportedDataSetIterator(outdir, shuffle=True, seed=1)
    it2.next()
    cursor = it2.state()
    it3 = ExportedDataSetIterator(outdir, shuffle=True, seed=1).restore(cursor)
    remaining = 0
    while it3.has_next():
        it3.next()
        remaining += 1
    print(f"final score {score:.4f}; resume served {remaining} of "
          f"{n_files} batches after the cursor")
    return score


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    main(**vars(ap.parse_args()))
