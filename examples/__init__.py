"""Runnable end-to-end examples (the dl4j-examples role).

Each example is a `main(smoke=False)` driving the public API only;
`--smoke` shrinks shapes/epochs for CI. Run as
``python -m examples.<name>`` from the repo root.
"""
