"""Word2Vec over Japanese text through the dictionary lattice tokenizer.

The deeplearning4j-nlp-japanese role end to end: Kuromoji-style
Viterbi-lattice segmentation (TSV dictionary + POS connection costs +
unknown-word character classes, ``text/lattice.py``) feeding the
all-epochs-on-device SGNS engine; prints nearest neighbors for a few
query words. ``--korean`` runs the same pipeline on the Korean
dictionary.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

from deeplearning4j_tpu.models.word2vec.word2vec import Word2Vec
from deeplearning4j_tpu.text.tokenization import tokenizer_factory

_JA = [
    "私は日本語を勉強します",
    "先生は学校で日本語を話す",
    "学生は東京大学で勉強します",
    "私は明日学校へ行く",
    "今日は新しい仕事です",
    "東京は日本の世界です",
] * 40

_KO = [
    "저는 한국어를 공부합니다",
    "선생님은 학교에서 한국어를 합니다",
    "학생은 서울에서 공부합니다",
    "오늘은 회사에 있습니다",
] * 40


def main(smoke: bool = False, korean: bool = False):
    lang = "korean" if korean else "japanese"
    tf = tokenizer_factory(lang)
    corpus = _KO if korean else _JA
    sents = [tf.create(s).get_tokens() for s in corpus]
    w2v = Word2Vec(layer_size=16 if smoke else 64, window_size=3,
                   min_word_frequency=1, epochs=1 if smoke else 5,
                   negative_sample=3, seed=7,
                   batch_size=1024 if smoke else 8192)
    w2v.fit(sents)
    queries = ["한국어", "학교"] if korean else ["日本語", "学校"]
    for q in queries:
        print(f"nearest({q}):", w2v.words_nearest(q, 3))
    return w2v


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--korean", action="store_true")
    a = ap.parse_args()
    main(smoke=a.smoke, korean=a.korean)
