"""ResNet-50 training from an image directory (or synthetic data).

The reference's "ComputationGraph + conv helpers at ImageNet scale"
configuration (BASELINE config #3): ComputationGraph fit_scan, bf16
compute, image-record-reader input path when a directory is given.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

import numpy as np

from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.models.zoo.resnet import resnet, resnet50


def main(smoke: bool = False, data_dir: str = None, batch: int = 32,
         epochs: int = 1):
    if smoke:
        # 2-2-2-2 mini-resnet on tiny synthetic images: exercises the
        # exact graph/bench path in seconds
        net = resnet(stages=(1, 1, 1, 1), widths=(8, 16, 32, 64),
                     num_classes=10, compute_dtype="float32")
        size, n, batch = 32, 16, 8
    else:
        net = resnet50(num_classes=1000)
        size, n = 224, batch * 8
    net.init()

    if data_dir:
        from deeplearning4j_tpu.datavec.records import ImageRecordReader
        from deeplearning4j_tpu.datavec.iterator import RecordReaderDataSetIterator
        reader = ImageRecordReader(height=size, width=size, root_dir=data_dir)
        it = RecordReaderDataSetIterator(reader, batch_size=batch)
        for _ in range(epochs):
            net.fit(it)
            it.reset()
        print(f"trained {epochs} epochs from {data_dir}, score {net.score():.4f}")
        return net.score()

    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, size, size, 3)).astype(np.float32)
    classes = 10 if smoke else 1000
    y = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    mds = MultiDataSet([x], [y])
    staged = net.stage_scan(mds, batch)
    scores = net.fit_scan(None, batch, epochs=epochs, staged=staged)
    print(f"synthetic run: final score {scores[-1]:.4f}")
    return float(scores[-1])


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--data-dir")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--epochs", type=int, default=1)
    args = ap.parse_args()
    main(smoke=args.smoke, data_dir=args.data_dir, batch=args.batch,
         epochs=args.epochs)
