"""MNIST embeddings → t-SNE → live /tsne dashboard view.

The reference's t-SNE scatter dashboard
(``deeplearning4j-ui-resources/.../ui/tsne/``) end-to-end: embed MNIST
digit images with on-device t-SNE (``plot/tsne.py``, exact gradients on
the MXU) and serve the class-colored scatter at ``/tsne``. Run it and
open the printed URL; with real MNIST on disk the clusters are the ten
digit classes.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

import numpy as np

from deeplearning4j_tpu.datasets.mnist import load_mnist
from deeplearning4j_tpu.plot.tsne import TSNE
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer


def main(smoke: bool = False, port: int = 0, keep_serving: bool = False):
    n, iters = (60, 30) if smoke else (1000, 500)
    ds = load_mnist(train=True, num_examples=n)
    x = np.asarray(ds.features).reshape(n, -1).astype(np.float32)
    labels = [str(int(d)) for d in np.argmax(np.asarray(ds.labels), axis=1)]

    coords = TSNE(n_iter=iters, perplexity=min(30.0, n / 4)).fit_transform(x)

    srv = UiServer(InMemoryStatsStorage(), port=port,
                   tsne=(coords, labels)).start()
    print(f"t-SNE of {n} MNIST digits at {srv.url}/tsne")
    if keep_serving:  # pragma: no cover - interactive mode
        import time
        while True:
            time.sleep(3600)
    srv.stop()
    return coords


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--serve", action="store_true",
                    help="keep the dashboard running")
    a = ap.parse_args()
    main(smoke=a.smoke, port=a.port, keep_serving=a.serve)
