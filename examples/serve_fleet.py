"""Horizontal serving tier end-to-end: fleet, router, SLO admission.

Spin up a 3-endpoint LocalFleet (engine workers behind the broker wire
protocol), route traffic through the InferenceRouter, kill one engine
mid-load (the faultinject seam) and watch the fleet serve through it:
every request resolves via failover, the dead endpoint is ejected and
then reinstated after restart, and a deadline tighter than capacity is
shed with RetryAfter instead of queueing past the SLO. The UiServer
aggregates fleet health at /healthz (with the /healthz/live vs
/healthz/ready split) and the dl4j_router_* families at /metrics.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse
import time

import numpy as np

from deeplearning4j_tpu.faultinject import kill_endpoint
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.serving import (InferenceRouter, LocalFleet,
                                        RetryAfter, ScalePolicy)
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer

N_IN, N_OUT = 16, 4


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--endpoints", type=int, default=3)
    ap.add_argument("--requests", type=int, default=60)
    ap.add_argument("--serve-seconds", type=float, default=0.0,
                    help="keep the UiServer up afterwards (0 = exit)")
    args = ap.parse_args(argv)

    conf = (NeuralNetConfiguration.builder()
            .seed(7).learning_rate(0.05).updater("adam").activation("relu")
            .list()
            .layer(DenseLayer(n_in=N_IN, n_out=32))
            .layer(OutputLayer(n_in=32, n_out=N_OUT, activation="softmax",
                               loss_function="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()

    def engine_factory():
        eng = ParallelInference(net, max_batch_size=8, max_latency_ms=1.0,
                                replicas=1)
        eng.warmup([(N_IN,)])
        return eng

    router = InferenceRouter(per_try_timeout_s=1.0, eject_backoff_s=0.2,
                             max_attempts=4)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=1.0, heartbeat_timeout_s=0.4)
    for _ in range(args.endpoints):
        fleet.add_endpoint()
    fleet.wait_ready(30)
    server = UiServer(InMemoryStatsStorage(), router=router).start()
    print(f"fleet up: {fleet.names()}  healthz: {server.url}/healthz")

    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, N_IN)).astype(np.float32)

    futs = [router.submit(x) for _ in range(args.requests // 2)]
    victim = fleet.names()[0]
    kill_endpoint(fleet, victim)
    print(f"killed {victim} mid-load")
    futs += [router.submit(x) for _ in range(args.requests // 2)]
    for f in futs:
        f.result(timeout=30)
    snap = router.fleet_snapshot()
    print(f"all {len(futs)} requests served through the kill "
          f"(failovers={snap['failovers']}, healthy="
          f"{snap['healthy_endpoints']}/{snap['total_endpoints']})")

    fleet.restart(victim)
    router.probe_now()
    for _ in range(10):
        router.output(x, timeout=30)
    print(f"{victim} reinstated: "
          f"{router.fleet_snapshot()['endpoints'][victim]['in_pool']}")

    # SLO admission: an unmeetable deadline is shed, not queued
    try:
        router.submit(x, deadline_ms=1e-6, priority="best_effort")
        print("tight deadline admitted (cold estimate)")
    except RetryAfter as e:
        print(f"tight deadline shed: retry after {e.retry_after_s:.4f}s")

    # autoscaling: policy decisions from the live snapshot
    pol = ScalePolicy(min_endpoints=1, max_endpoints=args.endpoints + 1,
                      target_queue_per_endpoint=4.0, cooldown_s=0.0)
    print("autoscale:", fleet.autoscale(pol) or "steady")

    # capacity observatory: a fleet-wide window query — per-endpoint
    # summaries ride the heartbeats (engine fill ratio, jit-miss rate,
    # worker served delta) and merge here; the same view serves at
    # GET {server.url}/timeseries
    ts = fleet.timeseries_summary()
    print(f"fleet window ({ts.get('window_s') or 60.0:.0f}s):")
    for name, agg in sorted((ts.get("series") or {}).items()):
        print(f"  {name}: count={agg['count']} "
              f"rate={agg['rate']:.2f}/s mean={agg['mean']} "
              f"p99={agg['p99']}")

    if args.serve_seconds > 0:
        print(f"serving /healthz for {args.serve_seconds}s …")
        time.sleep(args.serve_seconds)
    server.stop()
    fleet.shutdown()
    return snap


if __name__ == "__main__":
    main()
