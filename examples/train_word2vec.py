"""Word2Vec skip-gram embeddings + nearest-words dashboard.

The reference's Word2VecRawTextExample role: sentence iterator →
tokenizer → vocab → SGNS training → wordsNearest, plus the live
UiServer nearest-words view.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

from deeplearning4j_tpu.models.word2vec.word2vec import Word2Vec
from deeplearning4j_tpu.text.sentenceiterator import CollectionSentenceIterator
from deeplearning4j_tpu.text.tokenization import (
    CommonPreprocessor, DefaultTokenizerFactory)
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer

_SENTENCES = [
    "the king rules the kingdom with the queen",
    "the queen rules beside the king",
    "a dog chases the cat around the garden",
    "the cat sleeps while the dog barks",
    "kings and queens live in castles",
    "dogs and cats are animals",
] * 50


def main(smoke: bool = False, serve: bool = False):
    fac = DefaultTokenizerFactory(CommonPreprocessor())
    w2v = Word2Vec(min_word_frequency=2, layer_size=16 if smoke else 64,
                   window_size=3, epochs=1 if smoke else 5, seed=7,
                   tokenizer_factory=fac)
    w2v.fit(CollectionSentenceIterator(_SENTENCES))
    print("nearest(king):", w2v.words_nearest("king", 4))
    if serve:
        srv = UiServer(InMemoryStatsStorage(), port=0,
                       word_vectors=w2v).start()
        print(f"nearest-words view: {srv.url}/words?word=king")
        input("enter to stop...")
        srv.stop()
    return w2v


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--serve", action="store_true")
    args = ap.parse_args()
    main(smoke=args.smoke, serve=args.serve)
