"""Puts the repo root on sys.path so example scripts run standalone
(``python examples/train_x.py`` from any cwd). When examples are
imported as a package (the smoke tests), the root is already there and
importing this module is a harmless no-op."""
import os
import sys

_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _root not in sys.path:
    sys.path.insert(0, _root)
