"""Training with the live dashboard: stats, activation images, model graph.

The observability stack end-to-end (``UiServer.java:25`` role): a
StatsListener streams score/norm/histogram reports into a storage the
UiServer serves at ``/train/<session>``, a ConvolutionalIterationListener
renders per-conv-layer activation montages at ``/activations``, and a
FlowIterationListener publishes the model graph at ``/flow``. The
monitor/ layer rides along: phase spans trace to JSONL + a Perfetto-
loadable Chrome trace (``--trace-dir``), a StepHealthWatchdog counts
NaN/slow steps, and Prometheus metrics serve at ``/metrics`` (+
``/healthz``). Run it and open the printed URLs.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse
import os

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer,
    DenseLayer,
    OutputLayer,
    SubsamplingLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UiServer
from deeplearning4j_tpu.ui.activations import (
    ConvolutionalIterationListener,
    FlowIterationListener,
)
from deeplearning4j_tpu.ui.stats import StatsListener


def main(smoke: bool = False, port: int = 0, keep_serving: bool = False,
         trace_dir: str = "/tmp/dl4j_tpu_trace"):
    os.makedirs(trace_dir, exist_ok=True)
    monitor.enable_tracing(os.path.join(trace_dir, "events.jsonl"))
    rng = np.random.default_rng(0)
    side, n, epochs = (10, 64, 2) if smoke else (28, 4096, 12)
    x = rng.standard_normal((n, side, side, 1)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]

    conf = (NeuralNetConfiguration.builder()
            .seed(1).learning_rate(0.01).updater("adam").activation("relu")
            .list()
            .layer(ConvolutionLayer(n_out=8, kernel_size=(3, 3)))
            .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=32))
            .layer(OutputLayer(n_out=10, activation="softmax",
                               loss_function="mcxent"))
            .set_input_type(InputType.convolutional(side, side, 1))
            .build())
    net = MultiLayerNetwork(conf).init()

    storage = InMemoryStatsStorage()
    conv = ConvolutionalIterationListener(x[:2], frequency=2)
    flow = FlowIterationListener(frequency=2)
    watchdog = monitor.StepHealthWatchdog()
    net.set_listeners(StatsListener(storage, frequency=1), conv, flow,
                      watchdog)

    srv = UiServer(storage, port=port, conv_listener=conv,
                   flow_listener=flow, model=net).start()
    print(f"dashboard: {srv.url}  (train view: {srv.url}/train/default, "
          f"activations: {srv.url}/activations, graph: {srv.url}/flow, "
          f"metrics: {srv.url}/metrics, health: {srv.url}/healthz)")

    ds = DataSet(x, y)
    for _ in range(epochs):
        net.fit(ds)
    tracer = monitor.disable_tracing()
    trace_path = tracer.export_chrome_trace(
        os.path.join(trace_dir, "trace.json"))
    print(f"final score {net.score():.4f}; "
          f"{len(storage.get_reports('default'))} reports, "
          f"{len(conv.latest)} activation images; "
          f"healthy={watchdog.healthy()}")
    print(f"phase breakdown: {monitor.phase_breakdown()}")
    print(f"Perfetto trace: {trace_path} (open at https://ui.perfetto.dev), "
          f"events: {os.path.join(trace_dir, 'events.jsonl')}")

    if keep_serving:
        print("serving until interrupted...")
        import time
        while True:
            time.sleep(60)
    srv.stop()
    return net.score()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--keep-serving", action="store_true")
    ap.add_argument("--trace-dir", default="/tmp/dl4j_tpu_trace")
    main(**vars(ap.parse_args()))
