"""LeNet on MNIST: conv stack, bf16 compute, telemetry + dashboard.

The reference's canonical first example (MnistDataSetIterator + conv
net). Real IDX files are used when present (datasets/mnist.py search
paths); otherwise a loud synthetic fallback keeps the example runnable.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.mnist import load_mnist
from deeplearning4j_tpu.eval.evaluation import Evaluation
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.ui import InMemoryStatsStorage, StatsListener, save_report


def build(compute_dtype="bfloat16"):
    return MultiLayerNetwork(
        (NeuralNetConfiguration.builder()
         .seed(12345).learning_rate(0.01).updater("adam")
         .activation("relu").weight_init("relu")
         .compute_dtype(compute_dtype)
         .list()
         .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
         .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
         .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
         .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
         .layer(DenseLayer(n_out=500))
         .layer(OutputLayer(n_out=10, activation="softmax",
                            loss_function="mcxent"))
         .set_input_type(InputType.convolutional(28, 28, 1))
         .build())).init()


def main(smoke: bool = False, report_path: str = "/tmp/lenet_report.html"):
    n, epochs, batch = (512, 1, 64) if smoke else (16384, 3, 512)
    train = load_mnist(train=True, num_examples=n)
    test = load_mnist(train=False, num_examples=max(256, n // 8))
    net = build()
    storage = InMemoryStatsStorage()
    net.set_listeners(StatsListener(storage, session_id="lenet", frequency=5))

    data = DataSet(train.features.reshape(-1, 28, 28, 1), train.labels)
    staged = net.stage_scan(data, batch)
    scores = net.fit_scan(None, batch, epochs=epochs, staged=staged)
    print(f"trained {epochs} epochs, final score {scores[-1]:.4f}")

    ev = Evaluation()
    ev.eval(test.labels, net.output(test.features.reshape(-1, 28, 28, 1)))
    print(f"test accuracy: {ev.accuracy():.4f}")
    save_report(storage, "lenet", report_path)
    print(f"dashboard: {report_path}")
    return ev.accuracy()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    main(smoke=ap.parse_args().smoke)
