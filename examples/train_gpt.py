"""Byte-level GPT training — single-chip, DP, SP (ring), or MoE.

The modern long-context flagship: one model config runs on one chip
(flash Pallas attention), data-parallel over a mesh, sequence-parallel
for long context (ring attention), or with Mixtral-style routed
experts — selected by flags, no model changes.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.zoo.transformer import gpt

_TEXT = ("the quick brown fox jumps over the lazy dog. "
         "she sells sea shells by the sea shore. ") * 400


def main(smoke: bool = False, num_experts: int = 0, seq_parallel: bool = False):
    data_ids = np.frombuffer(_TEXT.encode(), np.uint8).astype(np.int64)
    vocab = 256
    seq, d, layers, epochs = (32, 32, 2, 1) if smoke else (256, 256, 4, 8)
    n = (len(data_ids) - 1) // seq * seq
    x = data_ids[:n].reshape(-1, seq).astype(np.float32)
    # sparse int labels — no [n, seq, vocab] one-hot materialization
    y = data_ids[1:n + 1].reshape(-1, seq).astype(np.float32)
    ds = DataSet(x, y)

    net = gpt(vocab_size=vocab, d_model=d, n_layers=layers,
              num_heads=4, max_len=seq, num_experts=num_experts,
              compute_dtype="float32" if smoke else "bfloat16",
              learning_rate=1e-3).init()
    batch = min(32, ds.num_examples())

    if seq_parallel:
        import jax
        from deeplearning4j_tpu.parallel.mesh import make_mesh, sequence_mesh
        n_seq = min(4, len(jax.devices()))
        mesh = make_mesh({"seq": n_seq}, devices=jax.devices()[:n_seq])
        with sequence_mesh(mesh):
            scores = net.fit_scan(ds, batch, epochs=epochs)
    else:
        scores = net.fit_scan(ds, batch, epochs=epochs)
    print(f"final score {scores[-1]:.4f} "
          f"(experts={num_experts}, sp={seq_parallel})")

    # KV-cached greedy decoding: one jitted single-token program
    from deeplearning4j_tpu.models.zoo.transformer import generate
    prompt = np.frombuffer(b"the quick", np.uint8)[None].astype(np.int64)
    out = generate(net, prompt, max_new_tokens=30 if not smoke else 8)
    print("sample:", bytes(out[0].tolist()).decode(errors="replace"))
    return float(scores[-1])


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--experts", type=int, default=0)
    ap.add_argument("--seq-parallel", action="store_true")
    args = ap.parse_args()
    main(smoke=args.smoke, num_experts=args.experts,
         seq_parallel=args.seq_parallel)
