"""Pipeline-parallel GPT training — GPipe stages over the mesh.

The block stack of a ``gpt()`` zoo net is stage-stacked (one
TransformerBlock per device along the ``pp`` mesh axis) and trained
through the ppermute microbatch pipeline
(``parallel/pipeline.py`` + ``models/zoo/transformer.py`` pipelined
mode); embedding and LM head stay replicated. Gradients equal the
sequential container's (tests/test_pipeline.py), so the trained stages
round-trip back onto the plain model for serving.
"""

try:  # script mode: examples/ is sys.path[0]
    import _bootstrap  # noqa: F401
except ImportError:  # package mode: repo root already importable
    pass

import argparse

import numpy as np

from deeplearning4j_tpu.models.zoo.transformer import (
    gpt,
    gpt_pipelined_train_step,
    gpt_stack_blocks,
    gpt_unstack_blocks,
)

_TEXT = ("the quick brown fox jumps over the lazy dog. "
         "she sells sea shells by the sea shore. ") * 200


def main(smoke: bool = False, stages: int = 4):
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.parallel.mesh import make_mesh

    devs = jax.devices()
    stages = min(stages, len(devs))
    mesh = make_mesh({"pp": stages}, devices=devs[:stages])

    data_ids = np.frombuffer(_TEXT.encode(), np.uint8).astype(np.int64)
    vocab = 256
    seq, d, steps = (16, 32, 3) if smoke else (128, 128, 30)
    n = (len(data_ids) - 1) // seq * seq
    x = data_ids[:n].reshape(-1, seq).astype(np.float32)
    y = data_ids[1:n + 1].reshape(-1, seq).astype(np.float32)
    batch = 4 * stages  # divisible into the default microbatch count

    net = gpt(vocab_size=vocab, d_model=d, n_layers=stages, num_heads=4,
              max_len=seq, compute_dtype="float32").init()
    p_emb = net.params[net.impls[0].name]
    p_head = net.params[net.impls[-1].name]
    p_blocks = gpt_stack_blocks(net)
    step = gpt_pipelined_train_step(net, mesh, learning_rate=1e-2)

    losses = []
    ids = jnp.asarray(x[:batch])
    labels = jnp.asarray(y[:batch])
    for _ in range(steps):
        p_emb, p_blocks, p_head, loss = step(p_emb, p_blocks, p_head,
                                             ids, labels)
        losses.append(float(loss))
    print(f"pp={stages}: loss {losses[0]:.3f} -> {losses[-1]:.3f}")

    # collapse the trained stages back onto the sequential container
    gpt_unstack_blocks(net, p_blocks)
    net.params = {**net.params, net.impls[0].name: p_emb,
                  net.impls[-1].name: p_head}
    out = net.output(x[:2])
    assert np.isfinite(out).all()
    return losses[-1]


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--stages", type=int, default=4)
    main(**vars(ap.parse_args()))
