"""SP-tree (generalized quad/oct tree) + Barnes-Hut forces.

Parity: ``deeplearning4j-core/.../clustering/sptree/SpTree.java`` and
``clustering/quadtree/QuadTree.java`` (SURVEY.md §2.3) — the
space-partitioning tree Barnes-Hut t-SNE uses to approximate the
repulsive force sum in O(n log n): each cell stores its center of mass
and cumulative size; a traversal substitutes a whole far-away cell by
its center of mass when the cell is "small enough seen from the point"
(cell radius / distance < theta).

Role in the TPU build: ``plot/tsne.py`` keeps the exact O(n²)
formulation as the DEVICE path (pairwise matmuls are MXU-dense; a
pointer tree cannot run on the TPU at all) — see the equivalence
benchmark in ``tests/test_sptree.py``, which shows the exact device
path dominating at t-SNE scales. The SP-tree is the HOST-side analog
for (a) parity with the reference data structure, (b) n large enough
that O(n²) memory (an [n,n] device buffer) stops fitting, and (c)
nearest-cell queries on CPU-only processes (e.g. data workers).

``QuadTree`` is the fixed-2-D specialization the reference ships
separately; here it is literally the same structure with d=2.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


class SpTree:
    """Static SP-tree over an [n, d] point set.

    Vectorized construction: points are bucketed per level by child
    index (interleaved radix in d bits), no Python recursion per point.
    Nodes are stored in flat arrays (struct-of-arrays — the JVM
    reference chases one heap object per cell, SpTree.java:~node class;
    flat arrays keep traversal cache-friendly and numpy-sliceable).
    """

    def __init__(self, data: np.ndarray, leaf_size: int = 1,
                 max_depth: int = 32):
        data = np.asarray(data, np.float64)
        if data.ndim != 2:
            raise ValueError(f"expected [n, d] points, got {data.shape}")
        self.data = data
        n, d = data.shape
        self.n, self.d = n, d
        self.leaf_size = max(1, leaf_size)
        self.max_depth = max_depth

        # node arrays (grown geometrically)
        cap = max(16, 4 * n)
        self._center = np.zeros((cap, d))      # cell geometric center
        self._half = np.zeros(cap)             # cell half-width (max over dims)
        self._com = np.zeros((cap, d))         # center of mass
        self._count = np.zeros(cap, np.int64)  # points in cell
        self._children = -np.ones((cap, 2 ** d), np.int64)
        self._leaf_start = np.zeros(cap, np.int64)   # into self._order
        self._leaf_len = np.zeros(cap, np.int64)
        self._n_nodes = 0
        self._order = np.arange(n)

        if n:
            self._build()

    # -- construction --------------------------------------------------

    def _alloc(self) -> int:
        if self._n_nodes == len(self._half):
            grow = len(self._half)
            self._center = np.vstack([self._center, np.zeros((grow, self.d))])
            self._half = np.concatenate([self._half, np.zeros(grow)])
            self._com = np.vstack([self._com, np.zeros((grow, self.d))])
            self._count = np.concatenate([self._count, np.zeros(grow, np.int64)])
            self._children = np.vstack(
                [self._children, -np.ones((grow, 2 ** self.d), np.int64)])
            self._leaf_start = np.concatenate(
                [self._leaf_start, np.zeros(grow, np.int64)])
            self._leaf_len = np.concatenate(
                [self._leaf_len, np.zeros(grow, np.int64)])
        self._n_nodes += 1
        return self._n_nodes - 1

    def _build(self) -> None:
        lo, hi = self.data.min(0), self.data.max(0)
        center = (lo + hi) / 2.0
        half = float(np.max(hi - lo) / 2.0) + 1e-10
        root = self._alloc()
        self._center[root] = center
        self._half[root] = half
        # (node, start, end, depth) work stack over the point-order array
        stack = [(root, 0, self.n, 0)]
        while stack:
            node, s, e, depth = stack.pop()
            idx = self._order[s:e]
            pts = self.data[idx]
            self._count[node] = e - s
            self._com[node] = pts.mean(0)
            dup = bool(np.all(pts == pts[0]))  # duplicate guard (SpTree.java)
            if (e - s) <= self.leaf_size or depth >= self.max_depth or dup:
                self._leaf_start[node], self._leaf_len[node] = s, e - s
                continue
            center, half = self._center[node], self._half[node] / 2.0
            # child index = interleaved bits of (point >= center) per dim
            bits = (pts >= center[None, :]).astype(np.int64)
            child_of = bits @ (1 << np.arange(self.d, dtype=np.int64))
            sort = np.argsort(child_of, kind="stable")
            self._order[s:e] = idx[sort]
            child_of = child_of[sort]
            bounds = np.searchsorted(child_of, np.arange(2 ** self.d + 1))
            for ci in range(2 ** self.d):
                cs, ce = s + bounds[ci], s + bounds[ci + 1]
                if cs == ce:
                    continue
                child = self._alloc()
                offset = np.array([(half if (ci >> k) & 1 else -half)
                                   for k in range(self.d)])
                self._center[child] = center + offset
                self._half[child] = half
                self._children[node, ci] = child
                stack.append((child, cs, ce, depth + 1))

    # -- queries -------------------------------------------------------

    @property
    def n_nodes(self) -> int:
        return self._n_nodes

    def depth(self) -> int:
        best = 0
        stack = [(0, 1)] if self._n_nodes else []
        while stack:
            node, depth = stack.pop()
            best = max(best, depth)
            for c in self._children[node]:
                if c >= 0:
                    stack.append((c, depth + 1))
        return best

    def compute_non_edge_forces(self, point: np.ndarray, theta: float
                                ) -> Tuple[np.ndarray, float]:
        """Barnes-Hut repulsive term for one query point under the
        t-SNE Student-t kernel (``SpTree.java`` computeNonEdgeForces):
        returns (force_vector, sum_q) where
        force = Σ q_ij² * count * (point - com) and sum_q = Σ q_ij*count
        with q_ij = 1/(1+|point-com|²); exact whenever a cell is opened
        down to leaves, approximated by COM when half/dist < theta.
        Self-interaction (distance 0) is skipped, matching the
        reference's skip of the query point's own cell entry.
        """
        point = np.asarray(point, np.float64)
        force = np.zeros(self.d)
        sum_q = 0.0
        if not self._n_nodes:
            return force, sum_q
        stack = [0]
        while stack:
            node = stack.pop()
            diff = point - self._com[node]
            d2 = float(diff @ diff)
            count = int(self._count[node])
            is_leaf = self._leaf_len[node] > 0
            if is_leaf or self._half[node] * 2.0 < theta * np.sqrt(max(d2, 1e-300)):
                if is_leaf and (self._leaf_len[node] > 1 or d2 == 0.0):
                    # open the leaf exactly (skipping the query point)
                    s, ln = self._leaf_start[node], self._leaf_len[node]
                    pts = self.data[self._order[s:s + ln]]
                    dv = point[None, :] - pts
                    dd = np.einsum("ij,ij->i", dv, dv)
                    keep = dd > 0.0
                    q = 1.0 / (1.0 + dd[keep])
                    sum_q += float(q.sum())
                    force += (q * q) @ dv[keep]
                elif d2 > 0.0:
                    q = 1.0 / (1.0 + d2)
                    sum_q += q * count
                    force += (q * q * count) * diff
                continue
            for c in self._children[node]:
                if c >= 0:
                    stack.append(c)
        return force, sum_q


class QuadTree(SpTree):
    """2-D specialization (``clustering/quadtree/QuadTree.java``)."""

    def __init__(self, data: np.ndarray, leaf_size: int = 1,
                 max_depth: int = 32):
        data = np.asarray(data)
        if data.ndim != 2 or data.shape[1] != 2:
            raise ValueError(f"QuadTree is 2-D; got {data.shape}")
        super().__init__(data, leaf_size=leaf_size, max_depth=max_depth)


def barnes_hut_tsne_gradient(y: np.ndarray, p_rows: np.ndarray,
                             p_cols: np.ndarray, p_vals: np.ndarray,
                             theta: float = 0.5) -> np.ndarray:
    """Full Barnes-Hut t-SNE gradient on the host
    (``plot/BarnesHutTsne.java:63`` gradient role): attractive term from
    the sparse P (CSR triplets), repulsive term via :class:`SpTree`.

    grad_i = 4 * (Σ_j p_ij q_ij (y_i - y_j)  -  (Σ_j q_ij² (y_i-y_j)) / sum_Q)
    """
    y = np.asarray(y, np.float64)
    n, d = y.shape
    tree = SpTree(y)
    rep = np.zeros((n, d))
    sum_q = 0.0
    for i in range(n):
        f, sq = tree.compute_non_edge_forces(y[i], theta)
        rep[i] = f
        sum_q += sq
    attr = np.zeros((n, d))
    for i in range(n):
        js = p_cols[p_rows[i]:p_rows[i + 1]]
        ps = p_vals[p_rows[i]:p_rows[i + 1]]
        dv = y[i][None, :] - y[js]
        q = 1.0 / (1.0 + np.einsum("ij,ij->i", dv, dv))
        attr[i] = (ps * q) @ dv
    return 4.0 * (attr - rep / max(sum_q, 1e-300))
