"""Iterative clustering algorithm framework.

Parity: ``clustering/algorithm/`` (SURVEY.md §2.3, VERDICT r2 missing
item #1) — ``BaseClusteringAlgorithm.java`` driving a
``ClusteringStrategy`` (``strategy/ClusteringStrategy.java``,
``FixedClusterCountStrategy.java``, ``OptimisationStrategy.java``) to a
termination ``ClusteringAlgorithmCondition``
(``condition/ConvergenceCondition.java``,
``FixedIterationCountCondition.java``, ``VarianceVariationCondition.java``),
with per-iteration stats in an ``IterationHistory``
(``iteration/IterationHistory.java``) and cluster-splitting
optimizations (``optimisation/ClusteringOptimization.java``,
``ClusterUtils.applyOptimization`` :215).

TPU-first split: the O(n·k·d) work per iteration — point-to-center
distances, assignment, center means, the distance/variance statistics —
is ONE device program over the full point matrix (the reference loops
``List<Point>`` on the JVM heap across an ExecutorService); the O(k)
strategy control flow (dropping empty clusters, splitting spread-out
ones — which changes k, i.e. array shapes) stays host-side where
dynamic shapes belong.
"""

from __future__ import annotations

import enum
import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.kmeans import ClusterSet, KMeansClustering


# ------------------------------------------------------------- iteration info

class ClusterSetInfo:
    """Per-iteration statistics (``cluster/info/ClusterSetInfo.java``
    role), computed vectorized from the [n, k] distance matrix."""

    def __init__(self, points_count: int, cluster_point_counts: np.ndarray,
                 average_point_distance: np.ndarray,
                 max_point_distance: np.ndarray,
                 distance_variance: float, point_location_change: int):
        self.points_count = points_count
        self.cluster_point_counts = cluster_point_counts    # [k]
        self.average_point_distance = average_point_distance  # [k]
        self.max_point_distance = max_point_distance          # [k]
        #: variance of every point's distance to its cluster center
        #: (``getPointDistanceFromClusterVariance`` role)
        self.point_distance_from_cluster_variance = distance_variance
        #: how many points changed cluster since the previous iteration
        #: (``getPointLocationChange`` role)
        self.point_location_change = point_location_change

    @property
    def cluster_count(self) -> int:
        return len(self.cluster_point_counts)


class IterationInfo:
    """``iteration/IterationInfo.java``: one iteration's index + stats +
    whether a strategy (split/drop) mutated the cluster set."""

    def __init__(self, index: int, cluster_set_info: ClusterSetInfo):
        self.index = index
        self.cluster_set_info = cluster_set_info
        self.strategy_applied = False


class IterationHistory:
    """``iteration/IterationHistory.java``: iteration index → info."""

    def __init__(self):
        self.iterations: Dict[int, IterationInfo] = {}

    def add(self, info: IterationInfo) -> None:
        self.iterations[info.index] = info

    def get_iteration_count(self) -> int:
        return len(self.iterations)

    def get_iteration_info(self, index: int) -> Optional[IterationInfo]:
        return self.iterations.get(index)

    def get_most_recent_iteration_info(self) -> Optional[IterationInfo]:
        if not self.iterations:
            return None
        return self.iterations[max(self.iterations)]

    def get_most_recent_cluster_set_info(self) -> Optional[ClusterSetInfo]:
        info = self.get_most_recent_iteration_info()
        return info.cluster_set_info if info else None


# ----------------------------------------------------------------- conditions

class ClusteringAlgorithmCondition:
    """``condition/ClusteringAlgorithmCondition.java`` SPI. Conditions
    and strategies serialize to plain dicts (the reference marks the
    whole framework ``Serializable``) so a clustering setup rides the
    same JSON config plane as network configs."""

    def is_satisfied(self, history: IterationHistory) -> bool:
        raise NotImplementedError

    def to_dict(self) -> dict:
        d = {"type": type(self).__name__}
        d.update(self.__dict__)
        return d

    @staticmethod
    def from_dict(d: dict) -> "ClusteringAlgorithmCondition":
        kinds = {c.__name__: c for c in (
            FixedIterationCountCondition, ConvergenceCondition,
            VarianceVariationCondition)}
        d = dict(d)
        cls = kinds[d.pop("type")]
        return cls(**d)


class FixedIterationCountCondition(ClusteringAlgorithmCondition):
    """``condition/FixedIterationCountCondition.java``."""

    def __init__(self, count: int):
        self.count = count

    @staticmethod
    def iteration_count_greater_than(count: int) -> "FixedIterationCountCondition":
        return FixedIterationCountCondition(count)

    def is_satisfied(self, history: IterationHistory) -> bool:
        return history.get_iteration_count() >= self.count


class ConvergenceCondition(ClusteringAlgorithmCondition):
    """``condition/ConvergenceCondition.java``: the fraction of points
    that changed cluster last iteration drops below ``rate``."""

    def __init__(self, rate: float):
        self.rate = rate

    @staticmethod
    def distribution_variation_rate_less_than(rate: float) -> "ConvergenceCondition":
        return ConvergenceCondition(rate)

    def is_satisfied(self, history: IterationHistory) -> bool:
        if history.get_iteration_count() <= 1:
            return False
        info = history.get_most_recent_cluster_set_info()
        variation = info.point_location_change / max(info.points_count, 1)
        return variation < self.rate


class VarianceVariationCondition(ClusteringAlgorithmCondition):
    """``condition/VarianceVariationCondition.java``: the relative
    change of the point-distance variance stays below ``variation`` for
    each of the last ``period`` iterations."""

    def __init__(self, variation: float, period: int):
        self.variation = variation
        self.period = period

    @staticmethod
    def variance_variation_less_than(variation: float,
                                     period: int) -> "VarianceVariationCondition":
        return VarianceVariationCondition(variation, period)

    def is_satisfied(self, history: IterationHistory) -> bool:
        n = history.get_iteration_count()
        if n <= self.period:
            return False
        # iterations are recorded at indices 1..n (reference loop
        # ``getIterationInfo(j - i)`` with j = iterationCount)
        for i in range(self.period):
            cur = history.get_iteration_info(n - i)
            prev = history.get_iteration_info(n - i - 1)
            if cur is None or prev is None:
                return False
            pv = prev.cluster_set_info.point_distance_from_cluster_variance
            cv = cur.cluster_set_info.point_distance_from_cluster_variance
            if pv == 0:
                return False
            if abs((cv - pv) / pv) >= self.variation:
                return False
        return True


# --------------------------------------------------------------- optimization

class ClusteringOptimizationType(enum.Enum):
    """``optimisation/ClusteringOptimizationType.java`` (5 members; as
    in the reference, ``applyOptimization`` acts on the two
    point-to-center types — ``ClusterUtils.java:215-235`` silently
    no-ops the rest)."""

    MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE = "avg_center"
    MINIMIZE_MAXIMUM_POINT_TO_CENTER_DISTANCE = "max_center"
    MINIMIZE_AVERAGE_POINT_TO_POINT_DISTANCE = "avg_point"
    MINIMIZE_MAXIMUM_POINT_TO_POINT_DISTANCE = "max_point"
    MINIMIZE_PER_CLUSTER_POINT_COUNT = "point_count"


class ClusteringOptimization:
    """``optimisation/ClusteringOptimization.java``: (type, value)."""

    def __init__(self, type: ClusteringOptimizationType, value: float):
        self.type = type
        self.value = value


# ----------------------------------------------------------------- strategies

class ClusteringStrategyType(enum.Enum):
    FIXED_CLUSTER_COUNT = "fixed"
    OPTIMIZATION = "optimization"


class ClusteringStrategy:
    """``strategy/BaseClusteringStrategy.java``: declarative spec the
    algorithm runs — cluster count, distance, termination condition and
    (for ``OptimisationStrategy``) a split optimization + its
    application condition."""

    def __init__(self, type: ClusteringStrategyType, initial_cluster_count: int,
                 distance_function: str = "euclidean",
                 allow_empty_clusters: bool = False):
        self.type = type
        self.initial_cluster_count = initial_cluster_count
        self.distance_function = distance_function
        self.allow_empty_clusters = allow_empty_clusters
        self.termination_condition: Optional[ClusteringAlgorithmCondition] = None

    # builder verbs (``endWhen…`` in the reference)
    def end_when_iteration_count_equals(self, n: int) -> "ClusteringStrategy":
        self.termination_condition = \
            FixedIterationCountCondition.iteration_count_greater_than(n)
        return self

    def end_when_distribution_variation_rate_less_than(self, rate: float) -> "ClusteringStrategy":
        self.termination_condition = \
            ConvergenceCondition.distribution_variation_rate_less_than(rate)
        return self

    def is_strategy_of_type(self, t: ClusteringStrategyType) -> bool:
        return self.type is t

    def is_optimization_defined(self) -> bool:
        return False

    def is_optimization_applicable_now(self, history: IterationHistory) -> bool:
        return False

    def to_dict(self) -> dict:
        d = {"strategy": type(self).__name__,
             "initial_cluster_count": self.initial_cluster_count,
             "distance_function": self.distance_function,
             "allow_empty_clusters": self.allow_empty_clusters,
             "termination_condition":
                 self.termination_condition.to_dict()
                 if self.termination_condition else None}
        opt = getattr(self, "clustering_optimization", None)
        if opt is not None:
            d["optimization"] = {"type": opt.type.name, "value": opt.value}
        cond = getattr(self, "optimization_application_condition", None)
        if cond is not None:
            d["optimization_condition"] = cond.to_dict()
        return d

    @staticmethod
    def from_dict(d: dict) -> "ClusteringStrategy":
        kinds = {c.__name__: c for c in (FixedClusterCountStrategy,
                                         OptimisationStrategy)}
        cls = kinds[d["strategy"]]
        if cls is FixedClusterCountStrategy:
            st = cls(d["initial_cluster_count"], d["distance_function"],
                     d.get("allow_empty_clusters", False))
        else:
            st = cls(d["initial_cluster_count"], d["distance_function"])
        if d.get("termination_condition"):
            st.termination_condition = ClusteringAlgorithmCondition.from_dict(
                d["termination_condition"])
        if d.get("optimization") and isinstance(st, OptimisationStrategy):
            st.clustering_optimization = ClusteringOptimization(
                ClusteringOptimizationType[d["optimization"]["type"]],
                d["optimization"]["value"])
        if d.get("optimization_condition") and isinstance(st, OptimisationStrategy):
            st.optimization_application_condition = \
                ClusteringAlgorithmCondition.from_dict(
                    d["optimization_condition"])
        return st


class FixedClusterCountStrategy(ClusteringStrategy):
    """``strategy/FixedClusterCountStrategy.java``: keep exactly k
    clusters; empty ones are dropped and the most spread-out clusters
    split to restore the count."""

    DEFAULT_ITERATION_COUNT = 100

    def __init__(self, cluster_count: int, distance_function: str,
                 allow_empty_clusters: bool = False):
        super().__init__(ClusteringStrategyType.FIXED_CLUSTER_COUNT,
                         cluster_count, distance_function, allow_empty_clusters)

    @staticmethod
    def setup(cluster_count: int,
              distance_function: str = "euclidean") -> "FixedClusterCountStrategy":
        return FixedClusterCountStrategy(cluster_count, distance_function)


class OptimisationStrategy(ClusteringStrategy):
    """``strategy/OptimisationStrategy.java``: additionally split
    clusters violating a distance bound, when an application condition
    holds."""

    DEFAULT_ITERATION_COUNT = 100

    def __init__(self, initial_cluster_count: int, distance_function: str):
        super().__init__(ClusteringStrategyType.OPTIMIZATION,
                         initial_cluster_count, distance_function,
                         allow_empty_clusters=False)
        self.clustering_optimization: Optional[ClusteringOptimization] = None
        self.optimization_application_condition: \
            Optional[ClusteringAlgorithmCondition] = None

    @staticmethod
    def setup(initial_cluster_count: int,
              distance_function: str = "euclidean") -> "OptimisationStrategy":
        return OptimisationStrategy(initial_cluster_count, distance_function)

    def optimize(self, type: ClusteringOptimizationType,
                 value: float) -> "OptimisationStrategy":
        self.clustering_optimization = ClusteringOptimization(type, value)
        return self

    def optimize_when_iteration_count_multiple_of(self, n: int) -> "OptimisationStrategy":
        self.optimization_application_condition = \
            FixedIterationCountCondition.iteration_count_greater_than(n)
        return self

    def optimize_when_point_distribution_variation_rate_less_than(
            self, rate: float) -> "OptimisationStrategy":
        self.optimization_application_condition = \
            ConvergenceCondition.distribution_variation_rate_less_than(rate)
        return self

    def get_clustering_optimization_value(self) -> float:
        return self.clustering_optimization.value

    def is_clustering_optimization_type(self, t: ClusteringOptimizationType) -> bool:
        return (self.clustering_optimization is not None
                and self.clustering_optimization.type is t)

    def is_optimization_defined(self) -> bool:
        return self.clustering_optimization is not None

    def is_optimization_applicable_now(self, history: IterationHistory) -> bool:
        return (self.optimization_application_condition is not None
                and self.optimization_application_condition.is_satisfied(history))


# ------------------------------------------------------------------ algorithm

def _distances(x: jnp.ndarray, c: jnp.ndarray, distance: str) -> jnp.ndarray:
    """[n, k] TRUE distances (euclidean un-squared, unlike the k-means
    inner loop, because strategy thresholds are metric values)."""
    km = KMeansClustering(k=max(1, c.shape[0]), distance=distance)
    d = km._distances(x, c)
    if distance == "euclidean":
        d = jnp.sqrt(jnp.maximum(d, 0.0))
    return d


@functools.partial(jax.jit, static_argnames=("k",))
def _iteration_stats(d: jnp.ndarray, labels: jnp.ndarray,
                     prev_labels: jnp.ndarray, x: jnp.ndarray, k: int):
    """One device program: per-cluster counts/means/max distances, the
    distance variance, the location-change count, and the new centers."""
    n = d.shape[0]
    one_hot = jax.nn.one_hot(labels, k, dtype=x.dtype)             # [n, k]
    counts = jnp.sum(one_hot, axis=0)                              # [k]
    mine = jnp.take_along_axis(d, labels[:, None], axis=1)[:, 0]   # [n]
    sums = one_hot.T @ mine[:, None]                               # [k, 1]
    avg = jnp.where(counts > 0, sums[:, 0] / jnp.maximum(counts, 1.0), 0.0)
    mx = jnp.max(jnp.where(one_hot > 0, d, 0.0), axis=0)           # [k]
    var = jnp.var(mine)
    moved = jnp.sum((labels != prev_labels).astype(jnp.int32))
    centers = one_hot.T @ x / jnp.maximum(counts[:, None], 1.0)
    return counts, avg, mx, var, moved, centers


class BaseClusteringAlgorithm:
    """``BaseClusteringAlgorithm.java``: distance-weighted seeding →
    iterate (classify → refresh centers → record stats → apply
    strategy) until the termination condition holds with no strategy
    mutation in the final iteration (``iterations()`` :96-105)."""

    def __init__(self, strategy: ClusteringStrategy, seed: int = 123):
        if strategy.termination_condition is None:
            default = (FixedClusterCountStrategy.DEFAULT_ITERATION_COUNT
                       if isinstance(strategy, (FixedClusterCountStrategy,
                                                OptimisationStrategy))
                       else 100)
            strategy.end_when_iteration_count_equals(default)
        self.strategy = strategy
        self.seed = seed
        self.history = IterationHistory()
        self.centers: Optional[np.ndarray] = None

    @staticmethod
    def setup(strategy: ClusteringStrategy, seed: int = 123) -> "BaseClusteringAlgorithm":
        return BaseClusteringAlgorithm(strategy, seed)

    # ---- public entry (``applyTo`` :76) ----

    def apply_to(self, points: np.ndarray) -> ClusterSet:
        x = jnp.asarray(points, jnp.float32)
        n = x.shape[0]
        k = self.strategy.initial_cluster_count
        if n < k:
            raise ValueError(f"{n} points < cluster count {k}")
        self.history = IterationHistory()
        self._init_clusters(x)
        self._iterations(x)
        km = KMeansClustering(k=len(self.centers),
                              distance=self.strategy.distance_function,
                              seed=self.seed)
        km.centers = self.centers
        km.iterations_run = self.history.get_iteration_count()
        return ClusterSet(km, np.asarray(points, np.float32))

    # ---- seeding (``initClusters`` :107: distance-weighted pick) ----

    def _init_clusters(self, x: jnp.ndarray) -> None:
        rng = np.random.default_rng(self.seed)
        xn = np.asarray(x, np.float64)
        n = len(xn)
        chosen = [int(rng.integers(n))]
        while len(chosen) < self.strategy.initial_cluster_count:
            c = jnp.asarray(xn[chosen], jnp.float32)
            d = np.asarray(_distances(x, c, self.strategy.distance_function))
            dmin = d.min(axis=1) ** 2
            dmin[chosen] = 0.0
            r = rng.random() * dmin.max()
            idx = int(np.argmax(dmin >= r))
            if idx in chosen:  # degenerate duplicates: fall back to farthest
                idx = int(np.argmax(dmin))
            chosen.append(idx)
        self.centers = xn[chosen].astype(np.float32)

    # ---- iteration loop (``iterations`` :96) ----

    def _iterations(self, x: jnp.ndarray) -> None:
        cond = self.strategy.termination_condition
        prev_labels = np.full(x.shape[0], -1)
        it = 0
        while (not cond.is_satisfied(self.history)
               or self.history.get_most_recent_iteration_info().strategy_applied):
            it += 1
            prev_labels = self._classify_and_refresh(x, it, prev_labels)
            self._apply_strategy(x, it)
            if it > 10_000:  # safety net; the reference loops forever here
                break

    def _classify_and_refresh(self, x: jnp.ndarray, it: int,
                              prev_labels: np.ndarray) -> np.ndarray:
        k = len(self.centers)
        d = _distances(x, jnp.asarray(self.centers),
                       self.strategy.distance_function)
        labels = jnp.argmin(d, axis=1)
        counts, avg, mx, var, moved, centers = _iteration_stats(
            d, labels, jnp.asarray(prev_labels), x, k)
        counts = np.asarray(counts)
        # empty clusters keep their center (the strategy phase decides
        # whether to drop them)
        new_centers = np.array(centers)  # copy: device arrays are read-only
        keep = counts > 0
        new_centers[~keep] = self.centers[~keep]
        self.centers = new_centers
        info = ClusterSetInfo(
            points_count=x.shape[0], cluster_point_counts=counts,
            average_point_distance=np.asarray(avg),
            max_point_distance=np.asarray(mx),
            distance_variance=float(var), point_location_change=int(moved))
        self.history.add(IterationInfo(it, info))
        return np.asarray(labels)

    # ---- strategy application (``applyClusteringStrategy`` :141) ----

    def _apply_strategy(self, x: jnp.ndarray, it: int) -> None:
        info = self.history.get_most_recent_cluster_set_info()
        iteration = self.history.get_most_recent_iteration_info()
        strategy = self.strategy
        if not strategy.allow_empty_clusters:
            empty = info.cluster_point_counts == 0
            if empty.any():
                self.centers = self.centers[~empty]
                iteration.strategy_applied = True
                if (strategy.is_strategy_of_type(
                        ClusteringStrategyType.FIXED_CLUSTER_COUNT)
                        and len(self.centers) < strategy.initial_cluster_count):
                    self._split_most_spread_out(
                        x, strategy.initial_cluster_count - len(self.centers))
        if (strategy.is_optimization_defined() and it != 0
                and strategy.is_optimization_applicable_now(self.history)):
            if self._optimize(x):
                iteration.strategy_applied = True

    def _split_most_spread_out(self, x: jnp.ndarray, count: int) -> None:
        """``ClusterUtils.splitMostSpreadOutClusters`` role: the widest
        clusters donate their farthest member as a new center."""
        for _ in range(count):
            d = np.asarray(_distances(x, jnp.asarray(self.centers),
                                      self.strategy.distance_function))
            labels = d.argmin(axis=1)
            mine = d[np.arange(len(labels)), labels]
            spread = np.asarray([mine[labels == c].max() if (labels == c).any()
                                 else 0.0 for c in range(len(self.centers))])
            widest = int(spread.argmax())
            members = np.flatnonzero(labels == widest)
            far = members[mine[members].argmax()]
            self.centers = np.concatenate(
                [self.centers, np.asarray(x[far], np.float32)[None]])

    def _optimize(self, x: jnp.ndarray) -> bool:
        """``ClusterUtils.applyOptimization`` :215: split every cluster
        whose average/maximum point-to-center distance exceeds the
        optimization value. All statistics are recomputed against the
        CURRENT centers (an empty-cluster drop earlier in this same
        strategy pass renumbers clusters, so the history's per-cluster
        arrays may be stale-indexed)."""
        strategy: OptimisationStrategy = self.strategy  # type: ignore
        is_avg = strategy.is_clustering_optimization_type(
            ClusteringOptimizationType.MINIMIZE_AVERAGE_POINT_TO_CENTER_DISTANCE)
        is_max = strategy.is_clustering_optimization_type(
            ClusteringOptimizationType.MINIMIZE_MAXIMUM_POINT_TO_CENTER_DISTANCE)
        if not (is_avg or is_max):  # remaining types: reference no-ops
            return False
        d = np.asarray(_distances(x, jnp.asarray(self.centers),
                                  self.strategy.distance_function))
        labels = d.argmin(axis=1)
        mine = d[np.arange(len(labels)), labels]
        bound = strategy.get_clustering_optimization_value()
        new_centers = []
        for c in range(len(self.centers)):
            members = np.flatnonzero(labels == c)
            if len(members) < 2:
                continue
            stat = mine[members].mean() if is_avg else mine[members].max()
            if stat <= bound:
                continue
            far = members[mine[members].argmax()]
            new_centers.append(np.asarray(x[far], np.float32))
        if not new_centers:
            return False
        self.centers = np.concatenate([self.centers, np.asarray(new_centers)])
        return True
