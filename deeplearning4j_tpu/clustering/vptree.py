"""VP-tree nearest-neighbor index + brute-force TPU alternative.

Parity: ``deeplearning4j-core/.../clustering/vptree/VPTree.java`` — a
vantage-point tree for metric-space k-NN serving (the nearest-neighbors
backend of word2vec ``wordsNearest`` and the UI's t-SNE hover).

TPU-first note: a VP-tree is a pointer-chasing host structure — the
right tool when queries arrive one at a time on the host. For batched
queries the TPU answer is ``knn_brute``: ONE [q, n] distance matmul on
the MXU + top-k, which saturates the chip and beats tree traversal for
any batch big enough to matter (the same argument SURVEY §2.3 makes for
exact t-SNE over Barnes-Hut). Both are provided; they agree exactly.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _dist(metric: str, a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """a: [d] or [m, d]; b: [n, d] → [n] or [m, n]."""
    if metric == "euclidean":
        diff = np.atleast_2d(a)[:, None, :] - b[None, :, :]
        out = np.sqrt(np.maximum((diff * diff).sum(-1), 0.0))
    elif metric == "cosine":
        an = np.atleast_2d(a)
        an = an / np.maximum(np.linalg.norm(an, axis=-1, keepdims=True), 1e-12)
        bn = b / np.maximum(np.linalg.norm(b, axis=-1, keepdims=True), 1e-12)
        out = 1.0 - an @ bn.T
    else:
        raise ValueError(f"unknown metric {metric}")
    return out[0] if a.ndim == 1 else out


@dataclasses.dataclass
class _Node:
    index: int                      # vantage point row
    radius: float
    inside: Optional["_Node"]
    outside: Optional["_Node"]
    leaf_indices: Optional[np.ndarray] = None


class VPTree:
    """Vantage-point tree (``VPTree.java``) over row vectors."""

    def __init__(self, points: np.ndarray, metric: str = "euclidean",
                 leaf_size: int = 16, seed: int = 0):
        self.points = np.asarray(points, np.float64)
        self.metric = metric
        self.leaf_size = max(1, leaf_size)
        rng = np.random.default_rng(seed)
        self.root = self._build(np.arange(len(self.points)), rng)

    def _build(self, idx: np.ndarray, rng) -> Optional[_Node]:
        if len(idx) == 0:
            return None
        if len(idx) <= self.leaf_size:
            return _Node(int(idx[0]), 0.0, None, None, leaf_indices=idx)
        vp_pos = rng.integers(0, len(idx))
        vp = int(idx[vp_pos])
        rest = np.delete(idx, vp_pos)
        d = _dist(self.metric, self.points[vp], self.points[rest])
        radius = float(np.median(d))
        inside = rest[d <= radius]
        outside = rest[d > radius]
        return _Node(vp, radius, self._build(inside, rng), self._build(outside, rng))

    def search(self, query: np.ndarray, k: int = 1) -> Tuple[List[int], List[float]]:
        """k nearest neighbors of one query vector: (indices, distances)."""
        query = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap by negated distance

        def consider(indices):
            d = _dist(self.metric, query, self.points[indices])
            for i, di in zip(np.atleast_1d(indices), np.atleast_1d(d)):
                if len(heap) < k:
                    heap.append((float(di), int(i)))
                    heap.sort(reverse=True)
                elif di < heap[0][0]:
                    heap[0] = (float(di), int(i))
                    heap.sort(reverse=True)

        def tau():
            return heap[0][0] if len(heap) == k else np.inf

        def visit(node: Optional[_Node]):
            if node is None:
                return
            if node.leaf_indices is not None:
                consider(node.leaf_indices)
                return
            dv = float(_dist(self.metric, query, self.points[node.index:node.index + 1])[0])
            consider(np.asarray([node.index]))
            # standard VP pruning: only descend a side if it can contain
            # a point closer than the current kth distance
            if dv <= node.radius:
                visit(node.inside)
                if dv + tau() > node.radius:
                    visit(node.outside)
            else:
                visit(node.outside)
                if dv - tau() <= node.radius:
                    visit(node.inside)

        visit(self.root)
        heap.sort()
        return [i for _, i in heap], [d for d, _ in heap]


def knn_brute(points: np.ndarray, queries: np.ndarray, k: int,
              metric: str = "euclidean") -> Tuple[np.ndarray, np.ndarray]:
    """Batched exact k-NN on device: one distance matmul + top-k.
    Returns (indices [q, k], distances [q, k]) — matches VPTree.search
    exactly (same metric, full scan). This is the serving path for TPU
    deployments; the VP-tree is the host-side single-query path."""
    import jax
    import jax.numpy as jnp

    p = jnp.asarray(points, jnp.float32)
    q = jnp.asarray(np.atleast_2d(queries), jnp.float32)
    if metric == "euclidean":
        # |q-p|^2 = |q|^2 - 2 q·p + |p|^2 ; the q·p term is the matmul
        d2 = (jnp.sum(q * q, 1)[:, None] - 2.0 * q @ p.T + jnp.sum(p * p, 1)[None, :])
        d = jnp.sqrt(jnp.maximum(d2, 0.0))
    elif metric == "cosine":
        qn = q / jnp.maximum(jnp.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        pn = p / jnp.maximum(jnp.linalg.norm(p, axis=1, keepdims=True), 1e-12)
        d = 1.0 - qn @ pn.T
    else:
        raise ValueError(f"unknown metric {metric}")
    neg_d, idx = jax.lax.top_k(-d, k)
    return np.asarray(idx), np.asarray(-neg_d)
