from deeplearning4j_tpu.clustering.kmeans import Cluster, ClusterSet, KMeansClustering  # noqa: F401
from deeplearning4j_tpu.clustering.algorithm import (  # noqa: F401
    BaseClusteringAlgorithm,
    ClusteringOptimizationType,
    ClusteringStrategy,
    ConvergenceCondition,
    FixedClusterCountStrategy,
    FixedIterationCountCondition,
    OptimisationStrategy,
    VarianceVariationCondition,
)
from deeplearning4j_tpu.clustering.kdtree import KDTree  # noqa: F401
from deeplearning4j_tpu.clustering.vptree import VPTree  # noqa: F401
from deeplearning4j_tpu.clustering.sptree import (  # noqa: F401
    QuadTree,
    SpTree,
    barnes_hut_tsne_gradient,
)
