from deeplearning4j_tpu.clustering.kmeans import KMeansClustering  # noqa: F401
from deeplearning4j_tpu.clustering.kdtree import KDTree  # noqa: F401
