"""K-means clustering.

Parity: ``clustering/kmeans/KMeansClustering.java`` + the cluster
framework (``ClusterSet``/``Point``) it sits on (SURVEY.md §2.3).

TPU formulation: the assign step is one [n,d]x[d,k] distance matmul +
argmin and the update step a segment-sum — both inside a single jitted
``lax.while_loop`` with a convergence predicate, so the whole clustering
runs on-device (the reference iterated point-lists on the JVM heap).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-6,
                 distance: str = "euclidean", seed: int = 123):
        if distance not in ("euclidean", "cosine", "manhattan"):
            raise ValueError(distance)
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.distance = distance
        self.seed = seed
        self.centers: Optional[np.ndarray] = None
        self.iterations_run: int = 0

    def _distances(self, x: jnp.ndarray, c: jnp.ndarray) -> jnp.ndarray:
        if self.distance == "euclidean":
            return (jnp.sum(x * x, 1)[:, None] - 2.0 * x @ c.T
                    + jnp.sum(c * c, 1)[None, :])
        if self.distance == "cosine":
            xn = x / jnp.maximum(jnp.linalg.norm(x, axis=1, keepdims=True), 1e-12)
            cn = c / jnp.maximum(jnp.linalg.norm(c, axis=1, keepdims=True), 1e-12)
            return 1.0 - xn @ cn.T
        return jnp.sum(jnp.abs(x[:, None, :] - c[None, :, :]), axis=-1)

    def fit(self, data: np.ndarray) -> "KMeansClustering":
        x = jnp.asarray(data, jnp.float32)
        n = x.shape[0]
        if n < self.k:
            raise ValueError(f"{n} points < k={self.k}")
        rng = np.random.default_rng(self.seed)
        # k-means++ seeding (host-side): robust to the bad random-init
        # local optima the plain reference seeding falls into
        xn = np.asarray(x, np.float64)
        centers = [xn[rng.integers(n)]]
        for _ in range(self.k - 1):
            d2 = np.min(((xn[:, None, :] - np.asarray(centers)[None]) ** 2).sum(-1), axis=1)
            probs = d2 / max(d2.sum(), 1e-12)
            centers.append(xn[rng.choice(n, p=probs)])
        init = jnp.asarray(np.asarray(centers), x.dtype)

        def assign(c):
            return jnp.argmin(self._distances(x, c), axis=1)

        def update(labels):
            one_hot = jax.nn.one_hot(labels, self.k, dtype=x.dtype)  # [n,k]
            sums = one_hot.T @ x
            counts = jnp.sum(one_hot, axis=0)[:, None]
            return jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0), 0.0)

        def cond(carry):
            c, prev_c, i = carry
            return (i < self.max_iterations) & (jnp.max(jnp.abs(c - prev_c)) > self.tol)

        def body(carry):
            c, _, i = carry
            c_new = update(assign(c))
            # keep empty clusters at their previous center
            c_new = jnp.where(jnp.all(c_new == 0.0, axis=1, keepdims=True), c, c_new)
            return c_new, c, i + 1

        final_c, _, iters = jax.lax.while_loop(
            cond, body, (init, init + 2 * self.tol, jnp.asarray(0)))
        self.centers = np.asarray(final_c)
        self.iterations_run = int(iters)
        return self

    def predict(self, data: np.ndarray) -> np.ndarray:
        x = jnp.asarray(data, jnp.float32)
        return np.asarray(jnp.argmin(self._distances(x, jnp.asarray(self.centers)), axis=1))

    def inertia(self, data: np.ndarray) -> float:
        x = jnp.asarray(data, jnp.float32)
        d = self._distances(x, jnp.asarray(self.centers))
        return float(jnp.sum(jnp.min(d, axis=1)))


class Cluster:
    """One cluster of a ClusterSet (``clustering/cluster/Cluster.java``
    role): center + member point indices with distances-to-center."""

    def __init__(self, cluster_id: int, center: np.ndarray):
        self.id = cluster_id
        self.center = center
        self.point_indices: list = []
        self.distances: list = []

    def add_point(self, index: int, distance: float) -> None:
        self.point_indices.append(int(index))
        self.distances.append(float(distance))

    def average_distance(self) -> float:
        return float(np.mean(self.distances)) if self.distances else 0.0

    def max_distance(self) -> float:
        return float(np.max(self.distances)) if self.distances else 0.0

    def __len__(self) -> int:
        return len(self.point_indices)


class ClusterSet:
    """``clustering/cluster/ClusterSet.java`` role: the queryable result
    of a clustering run — per-cluster membership with distances and
    nearest-cluster lookup for new points."""

    def __init__(self, model: "KMeansClustering", data: np.ndarray):
        self.model = model
        # one distance matmul serves both assignment and the stats
        d = np.asarray(model._distances(jnp.asarray(data, jnp.float32),
                                        jnp.asarray(model.centers)))
        if model.distance == "euclidean":
            # _distances returns squared euclidean (cancellation can dip
            # epsilon-negative); report TRUE distances like the other
            # metrics so Cluster stats are metric-consistent
            d = np.sqrt(np.maximum(d, 0.0))
        labels = d.argmin(axis=1)
        self.clusters = []
        for i in range(model.k):
            c = Cluster(i, model.centers[i])
            members = np.flatnonzero(labels == i)
            c.point_indices = members.tolist()
            c.distances = d[members, i].tolist()
            self.clusters.append(c)

    def cluster_of(self, point: np.ndarray) -> Cluster:
        lab = int(self.model.predict(np.asarray(point, np.float32)[None])[0])
        return self.clusters[lab]

    def total_average_distance(self) -> float:
        ds = [dist for c in self.clusters for dist in c.distances]
        return float(np.mean(ds)) if ds else 0.0

    def __iter__(self):
        return iter(self.clusters)

    def __len__(self) -> int:
        return len(self.clusters)
