"""KD-tree for exact nearest-neighbor queries.

Parity: ``clustering/kdtree/KDTree.java`` (SURVEY.md §2.3; also
``vptree/`` fills the same role for metric spaces — the batched
brute-force path in ``WordVectors.words_nearest`` is the TPU-preferred
alternative for bulk queries, this host structure serves single-point
lookups).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("point", "index", "axis", "left", "right")

    def __init__(self, point, index, axis):
        self.point = point
        self.index = index
        self.axis = axis
        self.left: Optional["_Node"] = None
        self.right: Optional["_Node"] = None


class KDTree:
    def __init__(self, points: np.ndarray):
        self.points = np.asarray(points, np.float64)
        idx = np.arange(len(self.points))
        self.root = self._build(idx, 0)

    def _build(self, idx: np.ndarray, depth: int) -> Optional[_Node]:
        if len(idx) == 0:
            return None
        axis = depth % self.points.shape[1]
        order = idx[np.argsort(self.points[idx, axis])]
        mid = len(order) // 2
        node = _Node(self.points[order[mid]], int(order[mid]), axis)
        node.left = self._build(order[:mid], depth + 1)
        node.right = self._build(order[mid + 1:], depth + 1)
        return node

    def nn(self, query: np.ndarray) -> Tuple[int, float]:
        """Nearest neighbor (index, distance)."""
        q = np.asarray(query, np.float64)
        best = [None, np.inf]

        def visit(node):
            if node is None:
                return
            d = float(np.linalg.norm(node.point - q))
            if d < best[1]:
                best[0], best[1] = node.index, d
            diff = q[node.axis] - node.point[node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if abs(diff) < best[1]:
                visit(far)

        visit(self.root)
        return best[0], best[1]

    def knn(self, query: np.ndarray, k: int) -> List[Tuple[int, float]]:
        q = np.asarray(query, np.float64)
        heap: List[Tuple[float, int]] = []  # max-heap via negation

        import heapq

        def visit(node):
            if node is None:
                return
            d = float(np.linalg.norm(node.point - q))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index))
            diff = q[node.axis] - node.point[node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])
