"""RecordReader → DataSet/MultiDataSet iterator bridges.

Parity: ``datasets/datavec/RecordReaderDataSetIterator.java:54``
(single reader → DataSet, classification label-index one-hot or
regression passthrough), ``SequenceRecordReaderDataSetIterator.java``
(aligned feature/label sequence readers with padding + masks), and
``RecordReaderMultiDataSetIterator.java`` (named readers composed into
multi-input/multi-output MultiDataSets for ComputationGraph).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator, MultiDataSetIterator
from deeplearning4j_tpu.datavec.records import ImageRecordReader, RecordReader


def _one_hot(idx: int, n: int) -> np.ndarray:
    v = np.zeros((n,), np.float32)
    v[int(idx)] = 1.0
    return v


class RecordReaderDataSetIterator(DataSetIterator):
    """Single record reader → DataSet minibatches.

    ``label_index`` marks the label column (classification with
    ``num_classes``, or regression when ``regression=True``);
    ``label_index=None`` yields unlabeled features (labels == features,
    the reference's unsupervised convention for pretrain feeds).
    For ``ImageRecordReader`` records ([array, label]) the array is the
    feature block.
    """

    def __init__(self, reader: RecordReader, batch_size: int,
                 label_index: Optional[int] = -1,
                 num_classes: Optional[int] = None,
                 regression: bool = False,
                 labels: Optional[Sequence[str]] = None):
        self.reader = reader
        self._batch = batch_size
        self.label_index = label_index
        self.num_classes = num_classes
        self.regression = regression
        self._label_map: Dict[str, int] = {}
        if labels is not None:
            # explicit canonical label list (cross-split contract)
            self._label_map = {s: i for i, s in enumerate(labels)}
        elif (not regression and label_index is not None
                and not isinstance(reader, ImageRecordReader)):
            # canonical (sorted) string-label map, like the reference's
            # label list: first-encounter order would make the class
            # indices depend on record order and differ across splits
            strings = set()
            self.reader.reset()
            for rec in self.reader:
                vals = list(rec)
                li = label_index if label_index >= 0 else len(vals) + label_index
                if isinstance(vals[li], str):
                    strings.add(vals[li])
            self._label_map = {s: i for i, s in enumerate(sorted(strings))}
            if (strings and num_classes is not None
                    and len(self._label_map) != num_classes):
                raise ValueError(
                    f"this split contains {len(self._label_map)} distinct "
                    f"string labels ({sorted(strings)}) but num_classes="
                    f"{num_classes}; indices would disagree across splits — "
                    f"pass labels=<canonical list> to pin the mapping")
        self.reader.reset()

    def reset(self):
        self.reader.reset()

    def has_next(self):
        return self.reader.has_next()

    def batch(self):
        return self._batch

    def _split(self, rec) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        if isinstance(self.reader, ImageRecordReader) or (
                len(rec) == 2 and isinstance(rec[0], np.ndarray) and rec[0].ndim >= 2):
            arr, label = rec
            x = np.asarray(arr, np.float32)
            if self.label_index is None:
                return x, None
            if self.regression:
                return x, np.asarray([label], np.float32)
            return x, _one_hot(label, self.num_classes or len(self.reader.labels))
        vals = list(rec)
        if self.label_index is None:
            return np.asarray(vals, np.float32), None
        li = self.label_index if self.label_index >= 0 else len(vals) + self.label_index
        label = vals.pop(li)
        x = np.asarray(vals, np.float32)
        if self.regression:
            return x, np.asarray([float(label)], np.float32)
        if self.num_classes is None:
            raise ValueError("classification needs num_classes")
        return x, _one_hot(float(label) if not isinstance(label, str) else
                           self._label_to_index(label), self.num_classes)

    def _label_to_index(self, label: str) -> int:
        if label not in self._label_map:
            raise ValueError(f"unseen string label {label!r}; known: "
                             f"{sorted(self._label_map)}")
        return self._label_map[label]

    def _next_impl(self) -> DataSet:
        xs, ys = [], []
        while self.reader.has_next() and len(xs) < self._batch:
            x, y = self._split(self.reader.next_record())
            xs.append(x)
            ys.append(y)
        feats = np.stack(xs)
        labels = feats if ys[0] is None else np.stack(ys)
        return DataSet(feats, labels)


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """Aligned feature + label sequence readers → padded, masked
    sequence DataSets (``SequenceRecordReaderDataSetIterator.java``).

    ``align="start"`` (default) left-aligns sequences, zero-padding and
    masking the tail (DL4J ALIGN_START); ``align="end"`` right-aligns so
    every sequence's last real timestep sits at index T-1 (DL4J
    ALIGN_END — the sequence-to-last-step convention)."""

    def __init__(self, features_reader: RecordReader,
                 labels_reader: Optional[RecordReader], batch_size: int,
                 num_classes: Optional[int] = None, regression: bool = False,
                 align: str = "start"):
        if align not in ("start", "end"):
            raise ValueError(f"align must be 'start' or 'end', got {align!r}")
        self.fr = features_reader
        self.lr = labels_reader
        self._batch = batch_size
        self.num_classes = num_classes
        self.regression = regression
        self.align = align

    def reset(self):
        self.fr.reset()
        if self.lr is not None:
            self.lr.reset()

    def has_next(self):
        return self.fr.has_next()

    def batch(self):
        return self._batch

    def _next_impl(self) -> DataSet:
        fseqs, lseqs = [], []
        while self.fr.has_next() and len(fseqs) < self._batch:
            f = np.asarray(self.fr.next_record(), np.float32)
            fseqs.append(f)
            if self.lr is not None:
                l = np.asarray(self.lr.next_record(), np.float32)
                if not self.regression:
                    if self.num_classes is None:
                        raise ValueError("classification needs num_classes")
                    l = np.eye(self.num_classes, dtype=np.float32)[
                        l.astype(int).ravel()]
                lseqs.append(l)
        T = max(s.shape[0] for s in fseqs)
        b = len(fseqs)

        def pack(seqs, width):
            arr = np.zeros((b, T, width), np.float32)
            mask = np.zeros((b, T), np.float32)
            for i, s in enumerate(seqs):
                if self.align == "end":
                    arr[i, T - s.shape[0]:] = s
                    mask[i, T - s.shape[0]:] = 1.0
                else:
                    arr[i, :s.shape[0]] = s
                    mask[i, :s.shape[0]] = 1.0
            return arr, mask

        x, mask = pack(fseqs, fseqs[0].shape[-1])
        if self.lr is None:
            return DataSet(x, x, features_mask=mask, labels_mask=mask)
        y, lmask = pack(lseqs, lseqs[0].shape[-1])
        return DataSet(x, y, features_mask=mask, labels_mask=lmask)


class RecordReaderMultiDataSetIterator(MultiDataSetIterator):
    """Named readers → MultiDataSet (``RecordReaderMultiDataSetIterator``
    builder semantics): each input/output selects a reader and either a
    column range ("all features") or a one-hot label column."""

    def __init__(self, batch_size: int):
        self._batch = batch_size
        self._readers: Dict[str, RecordReader] = {}
        self._inputs: List[Tuple[str, Optional[int], Optional[int]]] = []
        self._outputs: List[Tuple[str, int, int]] = []

    def add_reader(self, name: str, reader: RecordReader):
        self._readers[name] = reader
        return self

    def add_input(self, reader_name: str, col_from: Optional[int] = None,
                  col_to: Optional[int] = None):
        self._inputs.append((reader_name, col_from, col_to))
        return self

    def add_output_one_hot(self, reader_name: str, column: int, num_classes: int):
        self._outputs.append((reader_name, column, num_classes))
        return self

    def reset(self):
        for r in self._readers.values():
            r.reset()

    def has_next(self):
        return all(r.has_next() for r in self._readers.values())

    def batch(self):
        return self._batch

    def _next_impl(self) -> MultiDataSet:
        rows: Dict[str, List[List[float]]] = {n: [] for n in self._readers}
        count = 0
        while self.has_next() and count < self._batch:
            for n, r in self._readers.items():
                rows[n].append(list(r.next_record()))
            count += 1
        feats = []
        for name, c0, c1 in self._inputs:
            arr = np.asarray([[float(v) for v in row] for row in rows[name]],
                             np.float32)
            feats.append(arr[:, c0:c1] if c0 is not None or c1 is not None else arr)
        labels = []
        for name, col, ncls in self._outputs:
            idx = np.asarray([float(row[col]) for row in rows[name]]).astype(int)
            labels.append(np.eye(ncls, dtype=np.float32)[idx])
        return MultiDataSet(features=feats, labels=labels)
