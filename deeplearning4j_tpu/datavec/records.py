"""RecordReader SPI + CSV / sequence / image / line readers.

Parity: the DataVec record-reader layer the reference consumes through
``RecordReaderDataSetIterator.java:54`` — CSVRecordReader,
CSVSequenceRecordReader, ImageRecordReader (directory-per-label),
LineRecordReader. A "record" is a list of writable values; here that is
a list of python/NumPy scalars (or a [t, f] array for sequence
readers), which keeps the bridge to DataSet trivially vectorizable.

TPU note: readers run on the host feed path (they sit behind the async
prefetch iterator), so they stay pure-Python/NumPy — the device never
waits on parsing if the queue is deep enough.
"""

from __future__ import annotations

import csv
import io
import os
from typing import Iterator, List, Optional, Sequence

import numpy as np


class RecordReader:
    """``RecordReader`` contract: initialize(source) → iterate records."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next_record(self):
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next_record()


class CSVRecordReader(RecordReader):
    """``CSVRecordReader`` — one record per CSV row; values parsed to
    float when possible, else kept as strings (label columns)."""

    def __init__(self, path_or_lines, skip_lines: int = 0, delimiter: str = ","):
        if isinstance(path_or_lines, (list, tuple)):
            self._lines = [l for l in path_or_lines]
        else:
            with open(path_or_lines, newline="") as f:
                self._lines = f.read().splitlines()
        self._skip = skip_lines
        self._delim = delimiter
        self._rows: List[List[object]] = []
        for line in self._lines[skip_lines:]:
            if not line.strip():
                continue
            row = next(csv.reader(io.StringIO(line), delimiter=delimiter))
            self._rows.append([self._parse(v) for v in row])
        self._pos = 0

    @staticmethod
    def _parse(v: str):
        try:
            return float(v)
        except ValueError:
            return v.strip()

    def has_next(self):
        return self._pos < len(self._rows)

    def next_record(self):
        r = self._rows[self._pos]
        self._pos += 1
        return list(r)

    def reset(self):
        self._pos = 0

    def num_records(self) -> int:
        return len(self._rows)


class LineRecordReader(RecordReader):
    """``LineRecordReader`` — one record per raw text line."""

    def __init__(self, path_or_lines):
        if isinstance(path_or_lines, (list, tuple)):
            self._lines = list(path_or_lines)
        else:
            with open(path_or_lines) as f:
                self._lines = f.read().splitlines()
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._lines)

    def next_record(self):
        line = self._lines[self._pos]
        self._pos += 1
        return [line]

    def reset(self):
        self._pos = 0


class CSVSequenceRecordReader(RecordReader):
    """``CSVSequenceRecordReader`` — one sequence per CSV FILE (the
    reference's convention): each file's rows are the timesteps."""

    def __init__(self, paths: Sequence[str], skip_lines: int = 0,
                 delimiter: str = ","):
        self._paths = list(paths)
        self._skip = skip_lines
        self._delim = delimiter
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._paths)

    def next_record(self) -> np.ndarray:
        """Returns the [t, f] float array for one sequence. Numeric CSVs
        are parsed by the native multithreaded reader when available
        (comma-delimited only; other delimiters take the python path)."""
        path = self._paths[self._pos]
        self._pos += 1
        if self._delim == ",":
            from deeplearning4j_tpu.native import csv_read_floats
            try:
                # strict: a mis-pointed or string-labelled file must fail
                # loudly, not train on silently-zeroed features
                return csv_read_floats(path, skip_rows=self._skip, strict=True)
            except IOError:
                pass
        reader = CSVRecordReader(path, self._skip, self._delim)
        rows = [r for r in reader]
        return np.asarray(rows, np.float32)

    def reset(self):
        self._pos = 0

    def num_records(self) -> int:
        return len(self._paths)


class ImageRecordReader(RecordReader):
    """``ImageRecordReader`` — images from a directory-per-label tree
    (``parent/<label>/<file>``), decoded to [h, w, c] float NHWC in
    [0, 255] like the reference's native image loader; resized to
    (height, width)."""

    EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")

    def __init__(self, height: int, width: int, channels: int = 3,
                 root_dir: Optional[str] = None,
                 paths_and_labels: Optional[Sequence] = None):
        self.h, self.w, self.c = height, width, channels
        items: List = []
        if root_dir is not None:
            for label in sorted(os.listdir(root_dir)):
                d = os.path.join(root_dir, label)
                if not os.path.isdir(d):
                    continue
                for fn in sorted(os.listdir(d)):
                    if fn.lower().endswith(self.EXTS):
                        items.append((os.path.join(d, fn), label))
        if paths_and_labels:
            items.extend(paths_and_labels)
        self._items = items
        self.labels = sorted({lab for _, lab in items})
        self._pos = 0

    def has_next(self):
        return self._pos < len(self._items)

    def next_record(self):
        """Returns [image_array, label_index]."""
        path, label = self._items[self._pos]
        self._pos += 1
        from PIL import Image
        img = Image.open(path)
        img = img.convert("L" if self.c == 1 else "RGB")
        img = img.resize((self.w, self.h))
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return [arr, self.labels.index(label)]

    def reset(self):
        self._pos = 0

    def num_records(self) -> int:
        return len(self._items)
