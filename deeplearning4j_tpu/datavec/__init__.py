from deeplearning4j_tpu.datavec.iterator import (  # noqa: F401
    RecordReaderDataSetIterator,
    RecordReaderMultiDataSetIterator,
    SequenceRecordReaderDataSetIterator,
)
from deeplearning4j_tpu.datavec.records import (  # noqa: F401
    CSVRecordReader,
    CSVSequenceRecordReader,
    ImageRecordReader,
    LineRecordReader,
    RecordReader,
)
