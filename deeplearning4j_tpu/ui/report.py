"""HTML training dashboard export.

Parity: the ``deeplearning4j-ui`` Dropwizard dashboard
(``ui/UiServer.java:25-32``, weights/score views) and the Spark stats
HTML export (``stats/StatsUtils.java``). A zero-egress TPU pod can't
assume a live web server, so the dashboard is a self-contained static
HTML file (inline SVG charts, no external assets) rendered from a
StatsStorage session — open it in any browser, attach it to CI.
"""

from __future__ import annotations

import html
import math
from typing import Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.ui.storage import StatsStorage

_W, _H, _PAD = 640, 220, 36
_COLORS = ("#3366cc", "#dc3912", "#ff9900", "#109618", "#990099",
           "#0099c6", "#dd4477", "#66aa00", "#b82e2e", "#316395")


def _finite(points: Sequence[Tuple[float, float]]) -> List[Tuple[float, float]]:
    return [(x, y) for x, y in points if math.isfinite(x) and math.isfinite(y)]


def _svg_line_chart(title: str, series: Dict[str, List[Tuple[float, float]]],
                    log_y: bool = False, point_marks: bool = False) -> str:
    """Multi-series chart as a standalone <svg>; ``point_marks=True``
    draws circles per point instead of connecting lines (scatter)."""
    all_pts = _finite([p for pts in series.values() for p in pts])
    if not all_pts:
        return f"<h3>{html.escape(title)}</h3><p>(no data)</p>"
    xs = [p[0] for p in all_pts]
    ys = [p[1] for p in all_pts]
    if log_y:
        ys = [y for y in ys if y > 0]
        if not ys:
            log_y = False
            ys = [p[1] for p in all_pts]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    if log_y:
        y0, y1 = math.log10(y0), math.log10(y1)
    if x1 == x0:
        x1 = x0 + 1
    if y1 == y0:
        y1 = y0 + 1

    def sx(x):
        return _PAD + (x - x0) / (x1 - x0) * (_W - 2 * _PAD)

    def sy(y):
        if log_y:
            y = math.log10(max(y, 10 ** y0))
        return _H - _PAD - (y - y0) / (y1 - y0) * (_H - 2 * _PAD)

    parts = [f'<svg width="{_W}" height="{_H}" xmlns="http://www.w3.org/2000/svg" '
             f'style="background:#fff;border:1px solid #ddd">']
    # axes + labels
    parts.append(f'<line x1="{_PAD}" y1="{_H-_PAD}" x2="{_W-_PAD}" y2="{_H-_PAD}" stroke="#999"/>')
    parts.append(f'<line x1="{_PAD}" y1="{_PAD}" x2="{_PAD}" y2="{_H-_PAD}" stroke="#999"/>')
    fmt = (lambda v: f"1e{v:.1f}") if log_y else (lambda v: f"{v:.3g}")
    parts.append(f'<text x="{_PAD}" y="{_H-_PAD+14}" font-size="10">{x0:.0f}</text>')
    parts.append(f'<text x="{_W-_PAD-20}" y="{_H-_PAD+14}" font-size="10">{x1:.0f}</text>')
    parts.append(f'<text x="2" y="{_H-_PAD}" font-size="10">{fmt(y0)}</text>')
    parts.append(f'<text x="2" y="{_PAD+8}" font-size="10">{fmt(y1)}</text>')
    for i, (name, pts) in enumerate(sorted(series.items())):
        pts = _finite(pts)
        if not pts:
            continue
        color = _COLORS[i % len(_COLORS)]
        if point_marks:
            parts.extend(f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="2.5" '
                         f'fill="{color}"/>' for x, y in pts)
        else:
            d = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in pts)
            parts.append(f'<polyline points="{d}" fill="none" stroke="{color}" stroke-width="1.5"/>')
        ly = _PAD + 12 * (i + 1)
        parts.append(f'<rect x="{_W-_PAD-130}" y="{ly-8}" width="8" height="8" fill="{color}"/>')
        parts.append(f'<text x="{_W-_PAD-118}" y="{ly}" font-size="10">{html.escape(name[:24])}</text>')
    parts.append("</svg>")
    return f"<h3>{html.escape(title)}</h3>" + "".join(parts)


def render_html(storage: StatsStorage, session_id: str,
                worker_id: Optional[str] = None) -> str:
    """Render one session's training telemetry to a standalone HTML page."""
    reports = storage.get_reports(session_id, worker_id)
    score = {"score": [(r.iteration, r.score) for r in reports]}
    timing = {"ms/iteration": [(r.iteration, r.duration_ms) for r in reports]}
    pnorms: Dict[str, List[Tuple[float, float]]] = {}
    unorms: Dict[str, List[Tuple[float, float]]] = {}
    mem: Dict[str, List[Tuple[float, float]]] = {}
    for r in reports:
        for k, v in r.param_norms.items():
            pnorms.setdefault(k, []).append((r.iteration, v))
        for k, v in r.update_norms.items():
            unorms.setdefault(k, []).append((r.iteration, v))
        for k, v in r.memory.items():
            mem.setdefault(k, []).append((r.iteration, v / 2**20))
    sections = [
        _svg_line_chart("Score vs iteration", score),
        _svg_line_chart("Parameter L2 norms (log)", pnorms, log_y=True),
        _svg_line_chart("Update magnitudes |Δ‖p‖| (log)", unorms, log_y=True),
        _svg_line_chart("Iteration time (ms)", timing),
    ]
    if mem:
        sections.append(_svg_line_chart("Device memory (MiB)", mem))
    head = (f"<h1>deeplearning4j_tpu training report</h1>"
            f"<p>session <b>{html.escape(session_id)}</b>, "
            f"{len(reports)} reports, workers: "
            f"{html.escape(', '.join(storage.list_workers(session_id)) or '-')}</p>")
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>training report</title></head>"
            f"<body style='font-family:sans-serif'>{head}"
            + "".join(sections) + "</body></html>")


def save_report(storage: StatsStorage, session_id: str, path: str,
                worker_id: Optional[str] = None) -> str:
    with open(path, "w") as f:
        f.write(render_html(storage, session_id, worker_id))
    return path
