"""Minimal PNG encoding + activation-grid rasterization (stdlib only).

Support code for the ``ConvolutionalIterationListener`` role
(``deeplearning4j-ui/.../weights/ConvolutionalIterationListener.java:39``)
— the reference rasterizes per-layer activation maps into images for
the UI; this is the zero-dependency equivalent (PNG = zlib-deflated
filter-0 scanlines + CRC'd chunks).
"""

from __future__ import annotations

import struct
import zlib

import numpy as np


def encode_png_gray(img: np.ndarray) -> bytes:
    """8-bit grayscale PNG from a [h, w] uint8 (or castable) array."""
    img = np.ascontiguousarray(np.asarray(img, np.uint8))
    if img.ndim != 2:
        raise ValueError(f"need [h, w] grayscale, got shape {img.shape}")
    h, w = img.shape

    def chunk(tag: bytes, payload: bytes) -> bytes:
        return (struct.pack(">I", len(payload)) + tag + payload
                + struct.pack(">I", zlib.crc32(tag + payload) & 0xFFFFFFFF))

    ihdr = struct.pack(">IIBBBBB", w, h, 8, 0, 0, 0, 0)  # gray, no interlace
    raw = b"".join(b"\x00" + img[r].tobytes() for r in range(h))
    return (b"\x89PNG\r\n\x1a\n"
            + chunk(b"IHDR", ihdr)
            + chunk(b"IDAT", zlib.compress(raw, 6))
            + chunk(b"IEND", b""))


def activation_grid(acts: np.ndarray, pad: int = 1,
                    max_channels: int = 64) -> np.ndarray:
    """[h, w, c] (or [b, h, w, c]: first example) activation maps tiled
    into one [H, W] uint8 grid, each channel min-max normalized —
    the reference's per-layer activation montage."""
    a = np.asarray(acts, np.float32)
    if a.ndim == 4:
        a = a[0]
    if a.ndim != 3:
        raise ValueError(f"need [h, w, c] activations, got shape {a.shape}")
    h, w, c = a.shape
    c = min(c, max_channels)
    cols = int(np.ceil(np.sqrt(c)))
    rows = int(np.ceil(c / cols))
    grid = np.zeros((rows * (h + pad) + pad, cols * (w + pad) + pad), np.uint8)
    for i in range(c):
        ch = a[:, :, i]
        lo, hi = float(ch.min()), float(ch.max())
        norm = (ch - lo) / (hi - lo) if hi > lo else np.zeros_like(ch)
        r, col = divmod(i, cols)
        y = pad + r * (h + pad)
        x = pad + col * (w + pad)
        grid[y:y + h, x:x + w] = (norm * 255).astype(np.uint8)
    return grid
