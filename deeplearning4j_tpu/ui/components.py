"""Declarative UI component DSL: charts/tables/text as JSON.

Parity: ``deeplearning4j-ui-components/.../ui/components/{chart,table,
text,decorator}`` (~2.1k LoC) — a tree of declarative components, each
JSON-serializable with a polymorphic ``componentType`` tag, used to
build custom dashboards. The reference renders them with bundled JS
(dygraphs etc.); here every component renders to self-contained
HTML/SVG (same zero-asset doctrine as ``report.py``), and the JSON
round-trip is the stable interchange format.

Usage::

    page = ComponentDiv(
        ComponentText("LeNet run 7", style=StyleText(size=18, bold=True)),
        ChartLine("score", x=[its], y=[scores], series_names=["score"]),
        ComponentTable(header=["layer", "‖p‖"], content=rows),
    )
    open("dash.html", "w").write(page.render_html())
    ComponentDiv.from_dict(json.loads(json.dumps(page.to_dict())))
"""

from __future__ import annotations

import html as _html
from typing import Any, Dict, List, Optional, Sequence

from deeplearning4j_tpu.ui.report import _svg_line_chart

_REGISTRY: Dict[str, type] = {}


def _register(cls):
    _REGISTRY[cls.component_type] = cls
    return cls


class Component:
    """Base component (``ui/api/Component.java`` role): a JSON-taggable
    node; subclasses implement ``_body_dict``/``_from_body``/
    ``render_html``."""

    component_type = "Component"

    def to_dict(self) -> Dict[str, Any]:
        d = {"componentType": self.component_type}
        d.update(self._body_dict())
        return d

    def _body_dict(self) -> Dict[str, Any]:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Component":
        ctype = d.get("componentType")
        cls = _REGISTRY.get(ctype)
        if cls is None:
            raise ValueError(f"unknown componentType {ctype!r}; "
                             f"known: {sorted(_REGISTRY)}")
        body = {k: v for k, v in d.items() if k != "componentType"}
        return cls._from_body(body)

    @classmethod
    def _from_body(cls, body: Dict[str, Any]) -> "Component":
        return cls(**body)

    def render_html(self) -> str:
        raise NotImplementedError


@_register
class ComponentText(Component):
    """``components/text/ComponentText.java``."""

    component_type = "ComponentText"

    def __init__(self, text: str, size: int = 12, bold: bool = False,
                 color: str = "#000"):
        self.text, self.size, self.bold, self.color = text, size, bold, color

    def _body_dict(self):
        return {"text": self.text, "size": self.size, "bold": self.bold,
                "color": self.color}

    def render_html(self) -> str:
        weight = "bold" if self.bold else "normal"
        return (f"<div style='font-size:{int(self.size)}px;"
                f"font-weight:{weight};color:{_html.escape(self.color)}'>"
                f"{_html.escape(self.text)}</div>")


@_register
class ComponentTable(Component):
    """``components/table/ComponentTable.java``."""

    component_type = "ComponentTable"

    def __init__(self, header: Sequence[str], content: Sequence[Sequence[Any]],
                 title: str = ""):
        self.header = list(header)
        self.content = [list(row) for row in content]
        self.title = title

    def _body_dict(self):
        return {"header": self.header, "content": self.content,
                "title": self.title}

    def render_html(self) -> str:
        head = "".join(f"<th>{_html.escape(str(h))}</th>" for h in self.header)
        rows = "".join(
            "<tr>" + "".join(f"<td>{_html.escape(str(c))}</td>" for c in row) + "</tr>"
            for row in self.content)
        title = f"<h3>{_html.escape(self.title)}</h3>" if self.title else ""
        return (f"{title}<table border='1' cellpadding='4' "
                f"style='border-collapse:collapse'><tr>{head}</tr>{rows}</table>")


class _Chart(Component):
    """Shared chart fields (``components/chart/Chart.java``)."""

    def __init__(self, title: str = ""):
        self.title = title


@_register
class ChartLine(_Chart):
    """``chart/ChartLine.java``: one or more (x, y) line series."""

    component_type = "ChartLine"

    def __init__(self, title: str = "", x: Sequence[Sequence[float]] = (),
                 y: Sequence[Sequence[float]] = (),
                 series_names: Optional[Sequence[str]] = None,
                 log_y: bool = False):
        super().__init__(title)
        self.x = [list(map(float, s)) for s in x]
        self.y = [list(map(float, s)) for s in y]
        if len(self.x) != len(self.y):
            raise ValueError(f"{len(self.x)} x-series vs {len(self.y)} y-series")
        self.series_names = list(series_names) if series_names else [
            f"series{i}" for i in range(len(self.x))]
        self.log_y = log_y

    def _body_dict(self):
        return {"title": self.title, "x": self.x, "y": self.y,
                "series_names": self.series_names, "log_y": self.log_y}

    def render_html(self) -> str:
        series = {name: list(zip(xs, ys)) for name, xs, ys
                  in zip(self.series_names, self.x, self.y)}
        return _svg_line_chart(self.title, series, log_y=self.log_y)


@_register
class ChartScatter(ChartLine):
    """``chart/ChartScatter.java`` — same payload, point marks."""

    component_type = "ChartScatter"

    def render_html(self) -> str:
        series = {name: list(zip(xs, ys)) for name, xs, ys
                  in zip(self.series_names, self.x, self.y)}
        return _svg_line_chart(self.title, series, log_y=self.log_y,
                               point_marks=True)


@_register
class ChartHistogram(_Chart):
    """``chart/ChartHistogram.java``: bins as [low, high, count]."""

    component_type = "ChartHistogram"

    def __init__(self, title: str = "", lower: Sequence[float] = (),
                 upper: Sequence[float] = (), counts: Sequence[float] = ()):
        super().__init__(title)
        self.lower = list(map(float, lower))
        self.upper = list(map(float, upper))
        self.counts = list(map(float, counts))
        if not (len(self.lower) == len(self.upper) == len(self.counts)):
            raise ValueError("lower/upper/counts lengths differ")

    def _body_dict(self):
        return {"title": self.title, "lower": self.lower,
                "upper": self.upper, "counts": self.counts}

    def render_html(self) -> str:
        if not self.counts:
            return f"<h3>{_html.escape(self.title)}</h3><p>(no data)</p>"
        w, h, pad = 640, 220, 36
        x0, x1 = min(self.lower), max(self.upper)
        cmax = max(self.counts) or 1.0
        span = (x1 - x0) or 1.0
        bars = []
        for lo, hi, c in zip(self.lower, self.upper, self.counts):
            bx = pad + (lo - x0) / span * (w - 2 * pad)
            bw = max(1.0, (hi - lo) / span * (w - 2 * pad) - 1)
            bh = c / cmax * (h - 2 * pad)
            bars.append(f'<rect x="{bx:.1f}" y="{h - pad - bh:.1f}" '
                        f'width="{bw:.1f}" height="{bh:.1f}" fill="#3366cc"/>')
        return (f"<h3>{_html.escape(self.title)}</h3>"
                f'<svg width="{w}" height="{h}" xmlns="http://www.w3.org/2000/svg" '
                f'style="background:#fff;border:1px solid #ddd">'
                f'<line x1="{pad}" y1="{h-pad}" x2="{w-pad}" y2="{h-pad}" stroke="#999"/>'
                f'<text x="{pad}" y="{h-pad+14}" font-size="10">{x0:.3g}</text>'
                f'<text x="{w-pad-30}" y="{h-pad+14}" font-size="10">{x1:.3g}</text>'
                f'<text x="2" y="{pad+8}" font-size="10">{cmax:.3g}</text>'
                + "".join(bars) + "</svg>")


@_register
class ChartHorizontalBar(_Chart):
    """``chart/ChartHorizontalBar.java``: labeled horizontal bars."""

    component_type = "ChartHorizontalBar"

    def __init__(self, title: str = "", labels: Sequence[str] = (),
                 values: Sequence[float] = ()):
        super().__init__(title)
        self.labels = list(labels)
        self.values = list(map(float, values))
        if len(self.labels) != len(self.values):
            raise ValueError("labels/values lengths differ")

    def _body_dict(self):
        return {"title": self.title, "labels": self.labels,
                "values": self.values}

    def render_html(self) -> str:
        if not self.values:
            return f"<h3>{_html.escape(self.title)}</h3><p>(no data)</p>"
        w, row_h, pad = 640, 18, 140
        vmax = max(abs(v) for v in self.values) or 1.0
        h = len(self.values) * row_h + 10
        rows = []
        for i, (lab, v) in enumerate(zip(self.labels, self.values)):
            y = 5 + i * row_h
            bw = abs(v) / vmax * (w - pad - 10)
            rows.append(
                f'<text x="2" y="{y + 12}" font-size="10">'
                f'{_html.escape(str(lab)[:20])}</text>'
                f'<rect x="{pad}" y="{y}" width="{bw:.1f}" height="{row_h - 4}" '
                f'fill="#3366cc"/>'
                f'<text x="{pad + bw + 3:.1f}" y="{y + 12}" font-size="10">{v:.4g}</text>')
        return (f"<h3>{_html.escape(self.title)}</h3>"
                f'<svg width="{w}" height="{h}" xmlns="http://www.w3.org/2000/svg" '
                f'style="background:#fff;border:1px solid #ddd">'
                + "".join(rows) + "</svg>")


@_register
class ComponentDiv(Component):
    """``components/component/ComponentDiv.java``: child container."""

    component_type = "ComponentDiv"

    def __init__(self, *children: Component, style: str = ""):
        # from_dict path passes a prebuilt list of dicts via `children=`
        if len(children) == 1 and isinstance(children[0], (list, tuple)):
            children = tuple(children[0])
        self.children: List[Component] = [
            c if isinstance(c, Component) else Component.from_dict(c)
            for c in children]
        self.style = style

    def _body_dict(self):
        return {"children": [c.to_dict() for c in self.children],
                "style": self.style}

    @classmethod
    def _from_body(cls, body):
        return cls(body.get("children", []), style=body.get("style", ""))

    def render_html(self) -> str:
        inner = "".join(c.render_html() for c in self.children)
        style = f" style='{_html.escape(self.style)}'" if self.style else ""
        return f"<div{style}>{inner}</div>"

    def render_page(self, title: str = "deeplearning4j_tpu dashboard") -> str:
        """Standalone HTML page wrapper."""
        return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
                f"<title>{_html.escape(title)}</title></head>"
                f"<body style='font-family:sans-serif'>{self.render_html()}"
                "</body></html>")
