"""Training telemetry: StatsListener + structured reports.

Parity: ``deeplearning4j-ui-model/.../stats/StatsListener.java:46-187``
(iterationDone :117 — score, per-layer parameter/gradient/update
histograms & norms, memory, timing, hardware info) and
``stats/api/StatsReport.java``. The reference encodes reports with
generated SBE codecs and posts them over HTTP; here a report is a plain
dataclass → dict (JSON-ready) routed to a ``StatsStorage`` —
the wire format problem SBE solved doesn't exist in-process, and the
storage SPI (storage.py) is the extension seam a transport would plug
into.

TPU note: param/update statistics force a device→host transfer, so the
listener computes them every ``frequency`` iterations only, in ONE jitted
reduction per call (not one per layer) to keep host round-trips flat.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener


@dataclasses.dataclass
class StatsReport:
    """One iteration's telemetry (``StatsReport.java`` role)."""

    session_id: str
    worker_id: str
    iteration: int
    timestamp: float
    score: float
    duration_ms: float = float("nan")
    # per-layer-parameter statistics, keyed "layer/param"
    param_norms: Dict[str, float] = dataclasses.field(default_factory=dict)
    update_norms: Dict[str, float] = dataclasses.field(default_factory=dict)
    param_histograms: Dict[str, Any] = dataclasses.field(default_factory=dict)
    memory: Dict[str, float] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        # non-finite floats are not valid strict JSON — ship null so
        # jq/JS can parse report lines even from diverged runs
        def clean(v):
            if isinstance(v, float) and not np.isfinite(v):
                return None
            if isinstance(v, dict):
                return {k: clean(x) for k, x in v.items()}
            if isinstance(v, list):
                return [clean(x) for x in v]
            return v
        return clean(dataclasses.asdict(self))

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "StatsReport":
        d = dict(d)
        for k in ("duration_ms", "score"):
            if d.get(k) is None:
                d[k] = float("nan")
        for k in ("param_norms", "update_norms", "memory"):
            if d.get(k):
                d[k] = {kk: (float("nan") if v is None else v)
                        for kk, v in d[k].items()}
        if d.get("param_histograms"):
            # undo to_dict's non-finite scrubbing here too (a diverged
            # run's histogram min/max serialize as null): round-trip must
            # restore the same NaNs param_norms/update_norms/memory get
            def unscrub(v):
                if v is None:
                    return float("nan")
                if isinstance(v, dict):
                    return {k: unscrub(x) for k, x in v.items()}
                if isinstance(v, list):
                    return [unscrub(x) for x in v]
                return v
            d["param_histograms"] = {k: unscrub(v)
                                     for k, v in d["param_histograms"].items()}
        return StatsReport(**d)


def _flat_names(params) -> List[str]:
    names = []
    for lname in sorted(params):
        for pname in sorted(params[lname]):
            names.append(f"{lname}/{pname}")
    return names


@jax.jit
def _norms(params):
    """All per-parameter L2 norms in one device program."""
    return {ln: {pn: jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
                 for pn, v in ps.items()}
            for ln, ps in params.items()}


class StatsListener(IterationListener):
    """Collects StatsReports into a storage
    (``StatsListener.java:46`` — iterationDone :117).

    ``histograms=True`` additionally ships 20-bin parameter histograms
    (HistogramIterationListener role) — a full device→host pull of the
    parameters, so keep the frequency low when using it.
    """

    def __init__(self, storage, frequency: int = 1, session_id: str = "default",
                 worker_id: str = "worker0", histograms: bool = False,
                 histogram_bins: int = 20, registry=None):
        """``registry``: a :class:`~deeplearning4j_tpu.monitor.MetricsRegistry`
        to publish score/duration samples into (default: the process-wide
        one) — the listener is a registry consumer, not a private clock."""
        self.storage = storage
        self.frequency = max(1, frequency)
        self.session_id = session_id
        self.worker_id = worker_id
        self.histograms = histograms
        self.histogram_bins = histogram_bins
        self._registry = registry
        # (time, iteration) of the previous *report*, so duration_ms is
        # the windowed per-iteration mean, not the last single gap
        self._last_report: Optional[tuple] = None
        self._last_norms: Optional[Dict[str, float]] = None

    def _publish_metrics(self, score: float, duration_ms: float) -> None:
        """Publish into the process registry (monitor/) so /metrics serves
        the same samples the storage gets — one clock, many consumers."""
        from deeplearning4j_tpu.monitor import get_registry
        reg = self._registry if self._registry is not None else get_registry()
        labels = dict(session=self.session_id, worker=self.worker_id)
        if np.isfinite(score):
            reg.gauge("dl4j_score", "Latest training score", **labels).set(score)
        else:
            reg.counter("dl4j_nan_scores_total",
                        "Iterations with a non-finite score", **labels).inc()
        if np.isfinite(duration_ms):
            reg.histogram("dl4j_step_duration_ms",
                          "Per-iteration host step duration",
                          **labels).observe(duration_ms)

    def _device_memory(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        try:
            stats = jax.local_devices()[0].memory_stats() or {}
            out = {k: float(v) for k, v in stats.items()
                   if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")}
        except Exception:
            pass
        try:
            # host RSS — the role the reference's JVM-memory/GC MXBean
            # telemetry plays (StatsListener.java:165-190)
            import os
            page = os.sysconf("SC_PAGE_SIZE")
            with open("/proc/self/statm") as f:
                out["host_rss_bytes"] = float(
                    int(f.read().split()[1]) * page)
        except Exception:
            pass
        return out

    def iteration_done(self, model, iteration, score):
        if iteration % self.frequency != 0:
            return
        now = time.perf_counter()
        duration = float("nan")
        if self._last_report is not None:
            # mean per-iteration duration over the whole reporting window
            # (with frequency > 1 the previous behavior reported only the
            # last single iteration's gap)
            t0, it0 = self._last_report
            span_iters = max(1, iteration - it0)
            duration = (now - t0) * 1000.0 / span_iters
        self._last_report = (now, iteration)
        self._publish_metrics(float(score), duration)
        report = StatsReport(
            session_id=self.session_id, worker_id=self.worker_id,
            iteration=iteration, timestamp=time.time(), score=float(score),
            duration_ms=duration, memory=self._device_memory())
        if model.params is not None:
            norm_tree = jax.device_get(_norms(model.params))
            norms = {f"{ln}/{pn}": float(v)
                     for ln, ps in norm_tree.items() for pn, v in ps.items()}
            report.param_norms = norms
            if self._last_norms is not None:
                # |Δ‖p‖| as the cheap update-magnitude proxy; exact update
                # norms would need a param snapshot (2x HBM) per report
                report.update_norms = {
                    k: abs(norms[k] - self._last_norms[k])
                    for k in norms if k in self._last_norms}
            self._last_norms = norms
            if self.histograms:
                host = jax.device_get(model.params)
                for ln in sorted(host):
                    for pn, v in sorted(host[ln].items()):
                        counts, edges = np.histogram(
                            np.asarray(v, np.float32).ravel(), bins=self.histogram_bins)
                        report.param_histograms[f"{ln}/{pn}"] = {
                            "counts": counts.tolist(),
                            "min": float(edges[0]), "max": float(edges[-1])}
        self.storage.put_report(report)
