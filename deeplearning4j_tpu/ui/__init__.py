from deeplearning4j_tpu.ui.components import (  # noqa: F401
    ChartHistogram,
    ChartHorizontalBar,
    ChartLine,
    ChartScatter,
    Component,
    ComponentDiv,
    ComponentTable,
    ComponentText,
)
from deeplearning4j_tpu.ui.report import render_html, save_report  # noqa: F401
from deeplearning4j_tpu.ui.server import RemoteStatsStorageRouter, UiServer  # noqa: F401
from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport  # noqa: F401
from deeplearning4j_tpu.ui.storage import (  # noqa: F401
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsStorage,
)
