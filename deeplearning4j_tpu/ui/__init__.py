from deeplearning4j_tpu.ui.report import render_html, save_report  # noqa: F401
from deeplearning4j_tpu.ui.stats import StatsListener, StatsReport  # noqa: F401
from deeplearning4j_tpu.ui.storage import (  # noqa: F401
    FileStatsStorage,
    InMemoryStatsStorage,
    StatsStorage,
)
