"""Activation-image + model-graph training listeners.

Parity (VERDICT r2 missing #2):

- ``ConvolutionalIterationListener``
  (``deeplearning4j-ui/.../weights/ConvolutionalIterationListener.java:39``)
  — every N iterations, rasterize each convolutional layer's activation
  maps on a probe batch into a PNG montage for the UI.
- ``FlowIterationListener``
  (``deeplearning4j-ui/.../flow/FlowIterationListener.java``) — publish
  the live model-graph structure (layers/vertices, shapes, score) that
  the ``/flow`` UiServer view renders as SVG.

TPU note: activations are fetched from ONE extra jitted forward on a
small probe batch at the listener ``frequency`` — never from inside the
train step (which stays fused and donation-friendly).
"""

from __future__ import annotations

import base64
import os
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.optimize.listeners import IterationListener
from deeplearning4j_tpu.ui.images import activation_grid, encode_png_gray


class ConvolutionalIterationListener(IterationListener):
    """Renders per-conv-layer activation grids as PNGs.

    probe: a small input batch ([b, h, w, c]) run through the model at
    each firing. Images land in ``output_dir`` (when set) as
    ``iter{N}_{layer}.png`` and are always kept in ``self.latest``
    (layer name → PNG bytes) for the UiServer ``/activations`` page.
    """

    def __init__(self, probe: np.ndarray, frequency: int = 10,
                 output_dir: Optional[str] = None, max_channels: int = 64):
        self.probe = np.asarray(probe, np.float32)
        self.frequency = max(1, frequency)
        self.output_dir = output_dir
        self.max_channels = max_channels
        self.latest: Dict[str, bytes] = {}
        self.last_iteration: Optional[int] = None
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)

    def iteration_done(self, model, iteration: int, score: float):
        if iteration % self.frequency:
            return
        acts = model.feed_forward(self.probe)
        latest = {}  # built locally, assigned once: the UiServer thread
        for impl, a in zip(model.impls, acts):  # iterates self.latest
            if a.ndim != 4:  # only spatial feature maps render
                continue
            png = encode_png_gray(activation_grid(a, max_channels=self.max_channels))
            latest[impl.name] = png
            if self.output_dir:
                path = os.path.join(self.output_dir,
                                    f"iter{iteration}_{impl.name}.png")
                with open(path, "wb") as f:
                    f.write(png)
        self.latest = latest
        self.last_iteration = iteration


def _mln_flow(model, score: Optional[float]) -> Dict:
    layers = []
    for i, (impl, lc) in enumerate(zip(model.impls, model.conf.layers)):
        layers.append({
            "name": impl.name,
            "type": type(lc).__name__,
            "n_in": getattr(lc, "n_in", None),
            "n_out": getattr(lc, "n_out", None),
            "inputs": [model.impls[i - 1].name] if i > 0 else [],
        })
    return {"kind": "MultiLayerNetwork", "layers": layers, "score": score}


def _cg_flow(model, score: Optional[float]) -> Dict:
    layers = []
    for v in model.conf.vertices:
        layers.append({
            "name": v.name,
            "type": (type(v.layer).__name__ if v.kind == "layer" and v.layer
                     else v.kind),
            "n_in": getattr(v.layer, "n_in", None) if v.kind == "layer" else None,
            "n_out": getattr(v.layer, "n_out", None) if v.kind == "layer" else None,
            "inputs": list(v.inputs or []),
        })
    return {"kind": "ComputationGraph", "layers": layers, "score": score}


def model_flow_info(model, score: Optional[float] = None) -> Dict:
    """Model-graph structure dict (the FlowIterationListener payload)."""
    if hasattr(model, "order"):  # ComputationGraph (topological order attr)
        return _cg_flow(model, score)
    return _mln_flow(model, score)


class FlowIterationListener(IterationListener):
    """Publishes the model-graph view every N iterations; attach the
    listener (or just the model) to a ``UiServer`` to serve ``/flow``."""

    def __init__(self, frequency: int = 10):
        self.frequency = max(1, frequency)
        self.latest: Optional[Dict] = None

    def iteration_done(self, model, iteration: int, score: float):
        if iteration % self.frequency:
            return
        info = model_flow_info(model, score)
        info["iteration"] = iteration
        self.latest = info


def render_flow_svg(info: Dict) -> str:
    """Self-contained SVG of the model graph: one box per layer/vertex,
    edges following declared inputs (vertical topological layout)."""
    from html import escape

    layers: List[Dict] = info["layers"]
    w_box, h_box, gap, pad = 220, 46, 26, 20
    pos = {l["name"]: i for i, l in enumerate(layers)}
    height = pad * 2 + len(layers) * (h_box + gap)
    parts = [f"<svg xmlns='http://www.w3.org/2000/svg' width='{w_box + 2 * pad + 200}' "
             f"height='{height}' font-family='sans-serif' font-size='12'>"]
    title = f"{info.get('kind', 'model')}"
    if info.get("score") is not None:
        title += f" — score {info['score']:.4f}"
    parts.append(f"<text x='{pad}' y='{pad - 5}' font-size='14'>"
                 f"{escape(title)}</text>")
    for i, l in enumerate(layers):
        y = pad + i * (h_box + gap)
        shape = ""
        if l.get("n_in") is not None or l.get("n_out") is not None:
            shape = f"{l.get('n_in', '?')} → {l.get('n_out', '?')}"
        parts.append(
            f"<rect x='{pad}' y='{y}' width='{w_box}' height='{h_box}' "
            f"rx='6' fill='#eef4ff' stroke='#446'/>"
            f"<text x='{pad + 10}' y='{y + 18}' font-weight='bold'>"
            f"{escape(str(l['name']))}</text>"
            f"<text x='{pad + 10}' y='{y + 36}'>{escape(str(l['type']))} "
            f"{shape}</text>")
        for src in l.get("inputs", []):
            if src in pos:
                y0 = pad + pos[src] * (h_box + gap) + h_box
                parts.append(
                    f"<line x1='{pad + w_box / 2}' y1='{y0}' "
                    f"x2='{pad + w_box / 2}' y2='{y}' stroke='#446' "
                    f"marker-end='url(#arr)'/>")
    parts.insert(1, "<defs><marker id='arr' markerWidth='8' markerHeight='8' "
                    "refX='6' refY='3' orient='auto'>"
                    "<path d='M0,0 L6,3 L0,6 z' fill='#446'/></marker></defs>")
    parts.append("</svg>")
    return "".join(parts)


def render_activations_html(listener: ConvolutionalIterationListener) -> str:
    """Self-contained activation-montage page (base64-inlined PNGs)."""
    import html as _html

    if not listener.latest:
        body = "<p>(no activations rendered yet)</p>"
    else:
        imgs = []
        for name, png in listener.latest.items():
            b64 = base64.b64encode(png).decode()
            imgs.append(f"<figure style='display:inline-block;margin:8px'>"
                        f"<img src='data:image/png;base64,{b64}' "
                        f"style='image-rendering:pixelated;border:1px solid #888'/>"
                        f"<figcaption>{_html.escape(name)}</figcaption></figure>")
        it = listener.last_iteration
        body = f"<p>iteration {it}</p>" + "".join(imgs)
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>activations</title></head>"
            "<body style='font-family:sans-serif'><h1>Layer activations</h1>"
            + body + "</body></html>")
