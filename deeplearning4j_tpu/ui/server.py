"""Live training dashboard server + remote stats transport.

Parity: ``deeplearning4j-ui/.../ui/UiServer.java:25-32`` (embedded
Dropwizard/Jetty app serving dashboards, port auto-config) and the
remote listener transport (``weights/HistogramIterationListener.java:33``
posts telemetry to the server via a Jersey HTTP client;
``deeplearning4j-ui-remote-iterationlisteners``).

TPU-first re-design: the server is a stdlib ``ThreadingHTTPServer``
daemon around a :class:`~deeplearning4j_tpu.ui.storage.StatsStorage` —
no web framework, no servlet container, zero dependencies, so it runs on
a zero-egress pod host. Dashboards are the same self-contained SVG pages
``report.py`` renders offline; the JSON API exposes the storage SPI 1:1
so external tooling (curl/jq, notebooks) can stream telemetry. A
:class:`RemoteStatsStorageRouter` is the client half: a ``StatsStorage``
whose ``put_report`` POSTs to a server, so a ``StatsListener`` on worker
hosts ships reports to one dashboard process exactly like the
reference's remote listeners.

Routes:
  GET  /                                  session index (HTML)
  GET  /metrics                           Prometheus text exposition
  GET  /healthz                           combined health (JSON; 503 degraded)
  GET  /healthz/live                      liveness — process up, always 200
  GET  /healthz/ready                     readiness — warmed + not degraded,
                                          503 otherwise (k8s probe split)
  GET  /timeseries[?name=s&window=secs]   windowed telemetry (JSON): full
                                          snapshot, or one series × window
  GET  /train/<session>[?worker=w]        dashboard (HTML, report.py)
  GET  /api/sessions                      ["s1", ...]
  GET  /api/sessions/<s>/workers          ["w0", ...]
  GET  /api/sessions/<s>/reports[?worker] [report dicts...]
  POST /api/reports                       accept one report dict
  GET  /words[?word=w&n=k]                nearest-words view (HTML)
  GET  /api/words/nearest?word=w[&n=k]    {"word": w, "nearest": [...]}
  GET  /tsne                              2-D embedding scatter (HTML/SVG)
  GET  /api/tsne                          {"points": [[x,y]..], "labels": [..]}
  POST /api/tsne                          accept {"points", "labels"} push
"""

from __future__ import annotations

import html
import json
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, quote, unquote, urlencode, urlparse

from deeplearning4j_tpu.monitor import get_registry
from deeplearning4j_tpu.monitor.step_health import NAN_COUNTER, SLOW_COUNTER
from deeplearning4j_tpu.ui.report import render_html
from deeplearning4j_tpu.ui.stats import StatsReport
from deeplearning4j_tpu.ui.storage import StatsStorage


def _top_consumers(attr, k: int = 5):
    """Rank owners (model[@vN] lanes + the untagged bucket) by KV
    byte-seconds, then by total tokens — the ``/healthz`` answer to
    "who is eating this serving plane". ``attr`` is a scheduler
    ``attribution()`` block: per-model token/queue accumulators plus
    per-pool owner-tagged byte-second meters."""
    owners = {}
    for owner, d in (attr.get("models") or {}).items():
        o = owners.setdefault(owner, {"owner": owner, "kv_byte_seconds": 0.0,
                                      "prefill_tokens": 0, "decode_tokens": 0,
                                      "queue_ms": 0.0})
        o["prefill_tokens"] = int(d.get("prefill_tokens", 0))
        o["decode_tokens"] = int(d.get("decode_tokens", 0))
        o["queue_ms"] = round(float(d.get("queue_ms", 0.0)), 3)
    for pool in attr.get("kv_pools") or []:
        for owner, bs in (pool.get("byte_seconds") or {}).items():
            o = owners.setdefault(
                owner, {"owner": owner, "kv_byte_seconds": 0.0,
                        "prefill_tokens": 0, "decode_tokens": 0,
                        "queue_ms": 0.0})
            o["kv_byte_seconds"] = round(
                o["kv_byte_seconds"] + float(bs), 3)
    ranked = sorted(
        owners.values(),
        key=lambda o: (-o["kv_byte_seconds"],
                       -(o["prefill_tokens"] + o["decode_tokens"]),
                       o["owner"]))
    return ranked[:max(1, int(k))]


class _Handler(BaseHTTPRequestHandler):
    server_version = "dl4j-tpu-ui/1.0"

    # the owning UiServer injects `storage` onto the server object
    @property
    def storage(self) -> StatsStorage:
        return self.server._storage  # type: ignore[attr-defined]

    def log_message(self, fmt, *args):  # quiet by default
        if self.server._verbose:  # type: ignore[attr-defined]
            super().log_message(fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, obj, code: int = 200) -> None:
        self._send(code, json.dumps(obj).encode(), "application/json")

    def _html(self, text: str, code: int = 200) -> None:
        self._send(code, text.encode(), "text/html; charset=utf-8")

    def do_GET(self):  # noqa: N802 (http.server API)
        url = urlparse(self.path)
        parts = [unquote(p) for p in url.path.split("/") if p]
        query = parse_qs(url.query)
        worker = query.get("worker", [None])[0]
        try:
            if not parts:
                return self._html(self._index())
            if parts == ["metrics"]:
                return self._metrics()
            if parts == ["healthz"]:
                return self._healthz()
            if parts == ["healthz", "live"]:
                return self._healthz_live()
            if parts == ["healthz", "ready"]:
                return self._healthz_ready()
            if parts == ["debug", "traces"]:
                return self._debug_traces()
            if parts == ["timeseries"]:
                return self._timeseries(query)
            if parts[0] == "train" and len(parts) == 2:
                return self._html(render_html(self.storage, parts[1], worker))
            if parts[0] == "api":
                if parts[1:] == ["sessions"]:
                    return self._json(self.storage.list_sessions())
                if len(parts) == 4 and parts[1] == "sessions" and parts[3] == "workers":
                    return self._json(self.storage.list_workers(parts[2]))
                if len(parts) == 4 and parts[1] == "sessions" and parts[3] == "reports":
                    reports = self.storage.get_reports(parts[2], worker)
                    return self._json([r.to_dict() for r in reports])
                if parts[1:] == ["words", "nearest"]:
                    return self._words_nearest(query)
            if parts == ["words"]:
                return self._html(self._words_page(query))
            if parts == ["flow"]:
                return self._flow_page()
            if parts == ["api", "flow"]:
                return self._flow_json()
            if parts == ["activations"]:
                return self._activations_page()
            if parts == ["tsne"]:
                return self._tsne_page()
            if parts == ["api", "tsne"]:
                return self._tsne_json()
            return self._json({"error": "not found"}, 404)
        except Exception as e:  # surface handler bugs to the client, not the log
            return self._json({"error": f"{type(e).__name__}: {e}"}, 500)

    def do_POST(self):  # noqa: N802
        url = urlparse(self.path)
        parts = [p for p in url.path.split("/") if p]
        if parts == ["api", "tsne"]:
            return self._tsne_post()
        if parts != ["api", "reports"]:
            return self._json({"error": "not found"}, 404)
        try:
            length = int(self.headers.get("Content-Length", "0"))
            report = StatsReport.from_dict(json.loads(self.rfile.read(length)))
            self.storage.put_report(report)
            return self._json({"ok": True})
        except Exception as e:
            return self._json({"error": f"{type(e).__name__}: {e}"}, 400)

    # ------------------------------------------- /metrics + /healthz
    # (the monitor/ registry exposition: Prometheus scrape target + the
    # k8s-style liveness probe the reference's Dropwizard admin port
    # provided via its healthcheck servlet)

    @property
    def registry(self):
        reg = self.server._registry  # type: ignore[attr-defined]
        return reg if reg is not None else get_registry()

    def _metrics(self):
        body = self.registry.prometheus_text().encode()
        return self._send(200, body,
                          "text/plain; version=0.0.4; charset=utf-8")

    def _health_body(self):
        """(body, degraded, unwarmed) shared by the health routes.

        Liveness vs readiness split (the k8s probe discipline):
        ``/healthz/live`` answers "is the process up" — ALWAYS 200
        while the server can answer at all, so orchestrators never
        restart a pod for being degraded-but-serving; ``/healthz/ready``
        answers "should this pod take traffic" — 503 while any engine
        is un-warmed (first request would eat an XLA compile) or the
        serving plane is degraded (replica quarantined, fleet endpoint
        out). ``/healthz`` keeps its historical combined semantics
        (503 on degraded; warmup does NOT gate it) for existing
        monitors, and carries ``live`` + ``ready`` fields."""
        reg = self.registry
        nan = reg.family_total(NAN_COUNTER)
        slow = reg.family_total(SLOW_COUNTER)
        body = {
            "nan_scores": int(nan),
            "slow_steps": int(slow),
            "sessions": len(self.storage.list_sessions()),
            "uptime_s": round(time.monotonic()
                              - self.server._started_at, 3),  # type: ignore
        }
        degraded = nan > 0
        unwarmed = False
        engine = getattr(self.server, "_infer_engine", None)
        if engine is not None:
            # serving-plane snapshot (the dl4j_infer_* metric families
            # on /metrics carry the full histograms); a quarantined
            # replica means reduced capacity — degraded, still serving
            body["inference"] = engine.stats()
            degraded = degraded or bool(body["inference"].get("degraded"))
            unwarmed = unwarmed or not body["inference"].get("warmed", True)
            models = body["inference"].get("models")
            if models:
                # per-model readiness (multi-model engine): a model is
                # ready when it has a warmed serving version and a
                # closed breaker; /healthz/ready 503s until EVERY model
                # is — an orchestrator must not route traffic at a pod
                # whose newest deploy is still compiling
                body["models_ready"] = {
                    name: bool(m.get("ready") and m.get("warmed"))
                    for name, m in models.items()}
                unwarmed = unwarmed or not all(
                    m.get("warmed") for m in models.values())
                degraded = degraded or any(
                    m.get("breaker_open") for m in models.values())
            sched = body["inference"].get("scheduler")
            if sched is not None:
                # continuous-decode readiness (mirrors models_ready):
                # an un-warmed scheduler means the first admitted
                # sequence would eat the prefill/burst XLA compiles
                body["scheduler_ready"] = bool(sched.get("warmed"))
                unwarmed = unwarmed or not sched.get("warmed", True)
                attr = sched.get("attribution")
                if isinstance(attr, dict):
                    # capacity attribution: who is eating the serving
                    # plane, ranked — KV byte-seconds first (the scarce
                    # resource), then tokens
                    body["top_consumers"] = _top_consumers(attr)
        router = getattr(self.server, "_router", None)
        if router is not None:
            # fleet aggregation: every endpoint's health/stats as the
            # router sees them (heartbeats + ejection state)
            body["fleet"] = router.fleet_snapshot()
            degraded = degraded or bool(body["fleet"].get("degraded"))
        # mesh topology: the active MeshPlane (named axes + device ids)
        # — an operator reading /healthz sees at a glance what topology
        # this process is actually training/serving on (and a restore
        # onto a shrunken mesh shows up as the changed axis sizes)
        from deeplearning4j_tpu.parallel.mesh import active_plane
        plane = active_plane()
        if plane is not None:
            body["mesh"] = plane.topology()
        body["live"] = True
        body["ready"] = not degraded and not unwarmed
        return body, degraded, unwarmed

    def _timeseries(self, query):
        """Windowed telemetry as JSON (the capacity observatory's read
        path): ``?name=&window=`` answers one series × one window;
        without ``name`` the full snapshot of every series × the
        requested (or default) windows. Series live in two stores: the
        process-global registry store (scheduler/router samples) and
        the attached engine's private store (fill ratio, jit-miss,
        worker served) — both are searched/served."""
        store = self.registry.timeseries
        engine = getattr(self.server, "_infer_engine", None)
        estore = getattr(engine, "timeseries", None)
        name = query.get("name", [None])[0]
        try:
            windows = [float(w) for w in query.get("window", [])]
        except ValueError:
            return self._json({"error": "?window= must be a number"}, 400)
        if name is not None:
            window = windows[0] if windows else 60.0
            q = store.query(name, window)
            if q is None and estore is not None:
                q = estore.query(name, window)
            if q is None:
                return self._json(
                    {"error": f"no series named {name!r}"}, 404)
            return self._json({"name": name, **q})
        kw = {"windows": tuple(windows)} if windows else {}
        body = {"process": store.snapshot(**kw)}
        if estore is not None:
            body["engine"] = estore.snapshot(**kw)
        return self._json(body)

    def _debug_traces(self):
        """The flight recorder's rings as JSONL (one record per line:
        flight events first, then recent completed request traces) —
        the live seam behind the on-ejection dump files, served so an
        operator can pull the evidence WITHOUT shelling into the box.
        ``scripts/check_telemetry_schema.py`` validates the format."""
        from deeplearning4j_tpu.monitor.reqtrace import flight_recorder
        lines = [json.dumps(rec) for rec in flight_recorder().records()]
        return self._send(200, ("\n".join(lines) + "\n").encode(),
                          "application/x-ndjson")

    def _healthz(self):
        body, degraded, _ = self._health_body()
        body["status"] = "degraded" if degraded else "ok"
        return self._json(body, 503 if degraded else 200)

    def _healthz_live(self):
        body, degraded, _ = self._health_body()
        body["status"] = "degraded" if degraded else "ok"
        return self._json(body, 200)  # process up == live, always 200

    def _healthz_ready(self):
        body, degraded, unwarmed = self._health_body()
        ready = not degraded and not unwarmed
        body["status"] = ("ok" if ready else
                          "unwarmed" if unwarmed and not degraded
                          else "degraded")
        return self._json(body, 200 if ready else 503)

    # ------------------------------------------------------ /tsne view
    # (``deeplearning4j-ui-resources/.../ui/tsne/`` dashboard role: the
    # reference served a d3 scatter over word coordinates; here the page
    # is one self-contained SVG, data via plot/tsne.py or a POST push)

    def _tsne_post(self):
        try:
            length = int(self.headers.get("Content-Length", "0"))
            data = json.loads(self.rfile.read(length))
            pts = [[float(a), float(b)] for a, b in data["points"]]
            labels = [str(l) for l in data.get("labels") or
                      [str(i) for i in range(len(pts))]]
            if len(labels) != len(pts):
                raise ValueError(
                    f"{len(labels)} labels for {len(pts)} points")
            with self.server._tsne_lock:  # type: ignore[attr-defined]
                self.server._tsne_data = (pts, labels)  # type: ignore
            return self._json({"ok": True, "n": len(pts)})
        except Exception as e:
            return self._json({"error": f"{type(e).__name__}: {e}"}, 400)

    def _tsne_data(self):
        with self.server._tsne_lock:  # type: ignore[attr-defined]
            return self.server._tsne_data  # type: ignore[attr-defined]

    def _tsne_json(self):
        data = self._tsne_data()
        if data is None:
            return self._json({"error": "no t-SNE data attached"}, 404)
        pts, labels = data
        return self._json({"points": pts, "labels": labels})

    def _tsne_page(self):
        data = self._tsne_data()
        if data is None:
            return self._html(
                "<p>(no t-SNE data — pass tsne=(coords, labels) to "
                "UiServer or POST /api/tsne)</p>")
        pts, labels = data
        return self._html(
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>t-SNE</title></head>"
            "<body style='font-family:sans-serif'><h1>t-SNE embedding</h1>"
            f"<p>{len(pts)} points</p>"
            + render_tsne_svg(pts, labels) + "</body></html>")

    def _flow_info(self):
        """Model-graph info: from an attached FlowIterationListener's
        latest snapshot, else built live from an attached model
        (``ui/flow/FlowIterationListener.java`` view role)."""
        from deeplearning4j_tpu.ui.activations import model_flow_info

        fl = self.server._flow_listener  # type: ignore[attr-defined]
        if fl is not None and fl.latest is not None:
            return fl.latest
        model = self.server._flow_model  # type: ignore[attr-defined]
        if model is not None:
            score = getattr(model, "_score", None)
            if score is not None:
                # may be a deferred device scalar (optimize/deferred.py)
                score = float(score)
            return model_flow_info(model, score)
        return None

    def _flow_json(self):
        info = self._flow_info()
        if info is None:
            return self._json({"error": "no model attached"}, 404)
        return self._json(info)

    def _flow_page(self):
        from deeplearning4j_tpu.ui.activations import render_flow_svg

        info = self._flow_info()
        if info is None:
            return self._html("<p>(no model attached; pass model= or "
                              "flow_listener= to UiServer)</p>")
        return self._html(
            "<!DOCTYPE html><html><head><meta charset='utf-8'>"
            "<title>model flow</title></head>"
            "<body style='font-family:sans-serif'><h1>Model graph</h1>"
            + render_flow_svg(info) + "</body></html>")

    def _activations_page(self):
        from deeplearning4j_tpu.ui.activations import render_activations_html

        conv = self.server._conv_listener  # type: ignore[attr-defined]
        if conv is None:
            return self._html("<p>(no ConvolutionalIterationListener "
                              "attached)</p>")
        return self._html(render_activations_html(conv))

    def _words_nearest(self, query):
        """Nearest-neighbor serving for attached word vectors — the
        ``deeplearning4j-scaleout/deeplearning4j-nlp`` Dropwizard
        nearest-neighbors resource role."""
        wv = self.server._word_vectors  # type: ignore[attr-defined]
        if wv is None:
            return self._json({"error": "no word vectors attached"}, 404)
        word = query.get("word", [None])[0]
        if not word:
            return self._json({"error": "missing ?word="}, 400)
        try:
            n = int(query.get("n", ["10"])[0])
        except ValueError:
            return self._json({"error": "?n= must be an integer"}, 400)
        try:
            pairs = wv.words_nearest(word, n=n)
        except KeyError:
            return self._json({"error": f"unknown word {word!r}"}, 404)
        pairs = [list(p) if isinstance(p, (list, tuple)) else [p, None]
                 for p in pairs]
        return self._json({"word": word, "nearest": pairs})

    def _words_page(self, query) -> str:
        word = query.get("word", [""])[0]
        rows = ""
        wv = self.server._word_vectors  # type: ignore[attr-defined]
        if wv is not None and word:
            try:
                n = int(query.get("n", ["10"])[0])
            except ValueError:
                n = 10
            try:
                pairs = wv.words_nearest(word, n=n)
                rows = "".join(
                    f"<tr><td>{html.escape(str(w))}</td>"
                    f"<td>{'' if s is None else f'{float(s):.4f}'}</td></tr>"
                    for w, s in (p if isinstance(p, (list, tuple)) else (p, None)
                                 for p in pairs))
                rows = ("<table border='1' cellpadding='4'>"
                        "<tr><th>word</th><th>similarity</th></tr>"
                        + rows + "</table>")
            except KeyError:
                rows = f"<p>unknown word: {html.escape(word)}</p>"
        elif wv is None:
            rows = "<p>(no word vectors attached)</p>"
        return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
                "<title>nearest words</title></head>"
                "<body style='font-family:sans-serif'><h1>Nearest words</h1>"
                "<form method='get'><input name='word' "
                f"value='{html.escape(word)}'/>"
                "<button>search</button></form>" + rows + "</body></html>")

    def _index(self) -> str:
        rows = []
        for s in self.storage.list_sessions():
            workers = ", ".join(self.storage.list_workers(s)) or "-"
            n = len(self.storage.get_reports(s))
            link = f"/train/{html.escape(quote(s, safe=''))}"
            rows.append(f"<tr><td><a href='{link}'>{html.escape(s)}</a></td>"
                        f"<td>{n}</td><td>{html.escape(workers)}</td></tr>")
        body = ("<table border='1' cellpadding='4'>"
                "<tr><th>session</th><th>reports</th><th>workers</th></tr>"
                + "".join(rows) + "</table>") if rows else "<p>(no sessions yet)</p>"
        return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
                "<title>deeplearning4j_tpu UI</title></head>"
                "<body style='font-family:sans-serif'>"
                "<h1>deeplearning4j_tpu training UI</h1>" + body + "</body></html>")


def render_tsne_svg(points, labels, width: int = 760, height: int = 560,
                    max_text_labels: int = 200) -> str:
    """Self-contained SVG scatter of a 2-D embedding: one dot + hover
    tooltip per point, text labels while the plot stays readable
    (≤``max_text_labels``), color by label group when labels repeat
    (class-colored MNIST digits) and per-point otherwise (unique word
    labels). The ``ui/tsne`` dashboard view, sans d3/node_modules."""
    if not points:
        return "<p>(empty embedding)</p>"
    xs = [p[0] for p in points]
    ys = [p[1] for p in points]
    x0, x1 = min(xs), max(xs)
    y0, y1 = min(ys), max(ys)
    sx = (width - 40) / ((x1 - x0) or 1.0)
    sy = (height - 40) / ((y1 - y0) or 1.0)
    groups = sorted(set(labels))
    grouped = len(groups) < len(labels)  # repeated labels = classes
    palette = ["#1f77b4", "#ff7f0e", "#2ca02c", "#d62728", "#9467bd",
               "#8c564b", "#e377c2", "#7f7f7f", "#bcbd22", "#17becf"]
    color = {g: palette[i % len(palette)] for i, g in enumerate(groups)}
    show_text = not grouped and len(points) <= max_text_labels
    dots = []
    for (x, y), lab in zip(points, labels):
        px = 20 + (x - x0) * sx
        py = height - 20 - (y - y0) * sy  # SVG y grows downward
        c = color[lab] if grouped else "#1f77b4"
        dots.append(
            f"<circle cx='{px:.1f}' cy='{py:.1f}' r='3' fill='{c}' "
            f"fill-opacity='0.75'><title>{html.escape(str(lab))}"
            f"</title></circle>")
        if show_text:
            dots.append(f"<text x='{px + 4:.1f}' y='{py - 3:.1f}' "
                        f"font-size='9'>{html.escape(str(lab))}</text>")
    legend = ""
    if grouped:
        items = "".join(
            f"<tspan x='10' dy='14' fill='{color[g]}'>&#9679; "
            f"{html.escape(str(g))}</tspan>" for g in groups[:20])
        if len(groups) > 20:  # truncation must be visible, not silent
            items += (f"<tspan x='10' dy='14' fill='#555'>… "
                      f"+{len(groups) - 20} more</tspan>")
        legend = f"<text y='10' font-size='11'>{items}</text>"
    return (f"<svg xmlns='http://www.w3.org/2000/svg' width='{width}' "
            f"height='{height}' style='border:1px solid #ccc'>"
            + "".join(dots) + legend + "</svg>")


class UiServer:
    """Embedded dashboard server (``UiServer.java:25``).

    ``port=0`` picks a free port (the reference's port auto-config).
    The server runs on a daemon thread; ``attach`` more storages is not
    needed — pass the storage the training listeners write to.
    """

    def __init__(self, storage: StatsStorage, port: int = 0,
                 host: str = "127.0.0.1", verbose: bool = False,
                 word_vectors=None, model=None, conv_listener=None,
                 flow_listener=None, tsne=None, registry=None,
                 inference_engine=None, router=None):
        """``word_vectors``: any object with ``words_nearest(word, n)``
        (Word2Vec/WordVectors) — enables the /words nearest-neighbor
        view (legacy dl4j-scaleout/deeplearning4j-nlp render role).
        ``model``: a MultiLayerNetwork/ComputationGraph for the /flow
        model-graph view (live snapshot); ``flow_listener`` /
        ``conv_listener``: FlowIterationListener /
        ConvolutionalIterationListener instances backing /flow and
        /activations with training-time snapshots. ``tsne``: a
        ``(coords [N,2], labels [N])`` pair for the /tsne scatter view
        (``plot/tsne.py`` output; also settable later via
        ``set_tsne`` or POST /api/tsne). ``registry``: MetricsRegistry
        served at /metrics + /healthz (default: the process-wide one the
        monitor spans/listeners/watchdogs publish into).
        ``inference_engine``: a ``ParallelInference`` whose ``stats()``
        snapshot rides along on /healthz (its dl4j_infer_* metric
        families land on /metrics regardless). ``router``: an
        ``InferenceRouter`` whose ``fleet_snapshot()`` is aggregated
        into /healthz (per-endpoint health, ejections, shed/hedge/
        failover totals) and gates /healthz/ready."""
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd._storage = storage  # type: ignore[attr-defined]
        self._httpd._verbose = verbose  # type: ignore[attr-defined]
        self._httpd._registry = registry  # type: ignore[attr-defined]
        self._httpd._infer_engine = inference_engine  # type: ignore[attr-defined]
        self._httpd._router = router  # type: ignore[attr-defined]
        self._httpd._started_at = time.monotonic()  # type: ignore[attr-defined]
        self._httpd._word_vectors = word_vectors  # type: ignore[attr-defined]
        self._httpd._flow_model = model  # type: ignore[attr-defined]
        self._httpd._conv_listener = conv_listener  # type: ignore[attr-defined]
        self._httpd._flow_listener = flow_listener  # type: ignore[attr-defined]
        self._httpd._tsne_lock = threading.Lock()  # type: ignore[attr-defined]
        self._httpd._tsne_data = None  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None
        if tsne is not None:
            self.set_tsne(*tsne)

    def set_tsne(self, coords, labels=None) -> None:
        """Attach/replace the /tsne embedding: ``coords`` [N,2]-like,
        ``labels`` length-N (defaults to indices)."""
        pts = [[float(a), float(b)] for a, b in coords]
        labels = ([str(l) for l in labels] if labels is not None
                  else [str(i) for i in range(len(pts))])
        if len(labels) != len(pts):
            raise ValueError(f"{len(labels)} labels for {len(pts)} points")
        with self._httpd._tsne_lock:  # type: ignore[attr-defined]
            self._httpd._tsne_data = (pts, labels)  # type: ignore

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "UiServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="dl4j-tpu-ui", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class RemoteStatsStorageRouter(StatsStorage):
    """Client-side storage that ships reports to a :class:`UiServer`
    over HTTP — the remote-listener transport
    (``HistogramIterationListener.java:35-52`` Jersey POST role). Give
    this to a ``StatsListener`` on a worker host and reports land in the
    dashboard process's storage.

    Reads (list/get) also proxy through the JSON API, so the router is a
    full ``StatsStorage`` — a worker can read back global state too.
    """

    def __init__(self, url: str, timeout: float = 10.0):
        self.base = url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str):
        with urllib.request.urlopen(self.base + path, timeout=self.timeout) as r:
            return json.loads(r.read())

    def put_report(self, report: StatsReport) -> None:
        data = json.dumps(report.to_dict()).encode()
        req = urllib.request.Request(
            self.base + "/api/reports", data=data, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as r:
            resp = json.loads(r.read())
        if not resp.get("ok"):
            raise RuntimeError(f"report rejected: {resp}")
        self._notify(report)

    def list_sessions(self):
        return self._get("/api/sessions")

    def list_workers(self, session_id: str):
        return self._get(f"/api/sessions/{quote(session_id, safe='')}/workers")

    def get_reports(self, session_id: str, worker_id: Optional[str] = None):
        suffix = "?" + urlencode({"worker": worker_id}) if worker_id else ""
        dicts = self._get(
            f"/api/sessions/{quote(session_id, safe='')}/reports{suffix}")
        return [StatsReport.from_dict(d) for d in dicts]
