"""StatsStorage SPI + in-memory and file-backed implementations.

Parity: ``deeplearning4j-ui-model/.../storage/StatsStorage.java``
(sessions/workers keyed report store + change listeners) and
``mapdb/MapDBStatsStorage.java:21`` (persistent impl). The file backend
here is append-only JSONL per session — crash-safe, greppable, and
streamable, which is what MapDB bought the reference.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.ui.stats import StatsReport


class StatsStorage:
    """Storage SPI (``StatsStorage.java``)."""

    def put_report(self, report: StatsReport) -> None:
        raise NotImplementedError

    def list_sessions(self) -> List[str]:
        raise NotImplementedError

    def list_workers(self, session_id: str) -> List[str]:
        raise NotImplementedError

    def get_reports(self, session_id: str,
                    worker_id: Optional[str] = None) -> List[StatsReport]:
        raise NotImplementedError

    def latest_report(self, session_id: str) -> Optional[StatsReport]:
        reports = self.get_reports(session_id)
        return reports[-1] if reports else None

    # change-stream (StatsStorageListener role)

    def add_listener(self, cb: Callable[[StatsReport], None]) -> None:
        if not hasattr(self, "_listeners"):
            self._listeners: List[Callable] = []
        self._listeners.append(cb)

    def _notify(self, report: StatsReport) -> None:
        for cb in getattr(self, "_listeners", []):
            cb(report)


class InMemoryStatsStorage(StatsStorage):
    """``InMemoryStatsStorage`` — dict-backed, test/dev use."""

    def __init__(self):
        self._data: Dict[Tuple[str, str], List[StatsReport]] = {}
        self._lock = threading.Lock()

    def put_report(self, report: StatsReport) -> None:
        with self._lock:
            self._data.setdefault((report.session_id, report.worker_id), []).append(report)
        self._notify(report)

    def list_sessions(self) -> List[str]:
        with self._lock:
            return sorted({s for s, _ in self._data})

    def list_workers(self, session_id: str) -> List[str]:
        with self._lock:
            return sorted({w for s, w in self._data if s == session_id})

    def get_reports(self, session_id, worker_id=None) -> List[StatsReport]:
        out = []
        with self._lock:
            items = list(self._data.items())
        for (s, w), reports in items:
            if s == session_id and (worker_id is None or w == worker_id):
                out.extend(reports)
        return sorted(out, key=lambda r: (r.iteration, r.timestamp))


class FileStatsStorage(StatsStorage):
    """``MapDBStatsStorage`` role: persistent storage as append-only
    JSONL, one file per session under ``root_dir``."""

    def __init__(self, root_dir: str):
        self.root = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, session_id: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_" for c in session_id)
        return os.path.join(self.root, f"{safe}.jsonl")

    def put_report(self, report: StatsReport) -> None:
        line = json.dumps(report.to_dict())
        with self._lock:
            with open(self._path(report.session_id), "a") as f:
                f.write(line + "\n")
        self._notify(report)

    def list_sessions(self) -> List[str]:
        return sorted(os.path.splitext(f)[0] for f in os.listdir(self.root)
                      if f.endswith(".jsonl"))

    def list_workers(self, session_id: str) -> List[str]:
        return sorted({r.worker_id for r in self.get_reports(session_id)})

    def get_reports(self, session_id, worker_id=None) -> List[StatsReport]:
        path = self._path(session_id)
        if not os.path.exists(path):
            return []
        out = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                r = StatsReport.from_dict(json.loads(line))
                if worker_id is None or r.worker_id == worker_id:
                    out.append(r)
        return sorted(out, key=lambda r: (r.iteration, r.timestamp))
