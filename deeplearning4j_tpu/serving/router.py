"""InferenceRouter — the SLO-aware front end of the serving fleet.

The engine (PR 3/4/5) made ONE process serve well; this tier makes a
FLEET survivable, the Clipper (NSDI '17) layered-serving shape: a
front-end router dispatches classify / generate requests over N engine
endpoints (in-process or broker-reached) and owns the robustness plane
the engine cannot see from inside one process:

- **Health**: per-endpoint state from heartbeats + ``engine.stats()``.
  An endpoint is in the dispatch pool only while alive and not
  ejected; ``dl4j_router_endpoint_healthy{endpoint=...}`` mirrors it.
- **Outlier ejection** with backoff-probed reinstatement: repeated
  failures eject the endpoint for ``eject_backoff_s * 2**n``; after
  the backoff it turns *half-open* — the next request is routed to it
  as the probe, success reinstates, failure re-ejects with a doubled
  backoff. ``probe_now()`` collapses the wait for deterministic tests.
- **Failover + hedging**: a failed or timed-out dispatch retries on a
  different endpoint (bounded attempts, the request's Future never
  strands); a request still unresolved after ``hedge_after_ms`` sends
  ONE duplicate to a second endpoint and the first reply wins — the
  tail-latency discipline. Hedges are skipped for session-pinned
  requests (their KV state lives on one endpoint).
- **Deadline-aware admission control** (the Orca lesson: admission
  must be deadline-aware, not FIFO): each request carries a priority
  class and optional deadline; the router estimates completion time
  from live endpoint telemetry (queue depth / healthy replicas ×
  an EWMA of observed service time) and **sheds with**
  :class:`RetryAfter` any request that cannot meet its deadline —
  rejecting beats queueing past the SLO. Lower priority classes shed
  earlier (their estimate must fit a smaller fraction of the
  deadline).
- **Session affinity**: ``session=`` pins a multi-burst decode stream
  to the endpoint holding its KV state; the pin survives until that
  endpoint leaves the pool, then the session re-pins on first use.
- **Cache-aware affinity tiebreak**: endpoints running the prefix
  cache expose its summary (cached-prefix count + bytes) through the
  ``stats()`` snapshots riding their heartbeats, and the router
  remembers which endpoint last served each prompt-prefix key
  (the first ``prefix_affinity_tokens`` ids). When two endpoints tie
  on the admission estimate, the one already holding the prompt's
  prefix wins — a warm cache beats a cold one at zero health cost.
  Health, deadline shedding and session re-pin-after-death keep their
  existing behavior; the tiebreak only orders EXACT estimate ties.
- **Durable decode streams**: ``submit_generate(on_tokens=...)``
  streams incremental token deltas (wire-v2 chunks) while the router
  journals every received token per stream. When the serving endpoint
  dies mid-generation — reply timeout, heartbeat loss, a typed
  ``DecodeBurstError``, a wedge — the stream MIGRATES: re-pin,
  re-submit prompt + journaled prefix as a resume request, and the
  surviving engine continues the stream's PRNG clock. Delivered
  tokens are append-only (dedupe by offset: no gap, no repeat) and
  token-for-token equal to an uninterrupted run; the cost is a prefix
  re-prefill (``dl4j_router_resume_prefix_tokens_total``), not a
  re-generation.
- **Wedge watchdog** (``wedge_timeout_s``): heartbeats prove liveness,
  not progress. An endpoint with router-dispatched work in flight
  whose monotonic progress counters (engine ``resolved``, worker
  ``served``, scheduler ``bursts``) stay flat for the window is
  ejected like a crash and its streams migrate off it.
- **Autoscale signals**: ``fleet_snapshot()`` feeds
  :class:`~deeplearning4j_tpu.serving.policy.ScalePolicy` (queue-depth
  and p99 driven add/remove-endpoint decisions).
"""

from __future__ import annotations

import heapq
import logging
import queue
import threading
import time
from collections import OrderedDict
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.monitor import (
    KVTIER_RESTORE_COUNTER,
    REQ_PHASE_HISTOGRAM,
    REQ_SLO_BURN_COUNTER,
    REQ_TPOT_HISTOGRAM,
    REQ_TTFT_HISTOGRAM,
    ROUTER_ENDPOINT_HEALTHY_GAUGE,
    ROUTER_FAILOVERS_COUNTER,
    ROUTER_HEDGES_COUNTER,
    ROUTER_LATENCY_HISTOGRAM,
    ROUTER_LOOP_LAG_HISTOGRAM,
    ROUTER_QUEUE_WAIT_HISTOGRAM,
    ROUTER_REQUESTS_COUNTER,
    ROUTER_RESUME_PREFIX_COUNTER,
    ROUTER_SHED_COUNTER,
    SESSION_JOURNAL_BYTES_GAUGE,
    SESSION_MIGRATIONS_COUNTER,
    TS_ROUTER_ADMIT_ERROR,
    TS_ROUTER_QUEUE_DEPTH,
    TS_ROUTER_SHED,
    TS_SLO_BURN,
    get_registry,
    mark,
    merge_summaries,
    phase_breakdown,
    record_fault,
    reqtrace,
    ts_record,
)
from deeplearning4j_tpu.monitor.tracing import to_origin_us
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.serving.endpoint import EndpointError, EngineEndpoint

logger = logging.getLogger("deeplearning4j_tpu")

#: priority class → fraction of the deadline the completion estimate
#: may consume before the request is shed. Interactive requests use
#: the whole deadline; batch and best-effort shed earlier, so under
#: pressure the low classes drain first and the SLO class keeps its
#: headroom (the admission half of priority scheduling — no
#: in-router reordering needed when rejection is this cheap).
PRIORITY_HEADROOM: Dict[str, float] = {
    "interactive": 1.0,
    "batch": 0.7,
    "best_effort": 0.4,
}


class RetryAfter(RuntimeError):
    """Admission control rejected the request: it cannot meet its
    deadline (or no endpoint is available). ``retry_after_s`` is the
    router's estimate of when capacity frees up — the HTTP
    Retry-After discipline, surfaced as data so any transport can
    relay it."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class _EndpointState:
    """Router-side bookkeeping for one endpoint."""

    __slots__ = ("endpoint", "consecutive_failures", "ejections",
                 "ejected_until", "in_trial", "ewma_ms", "inflight",
                 "requests", "failures", "model_ewma_ms",
                 "progress_sig", "progress_at", "wedged", "role")

    def __init__(self, endpoint: EngineEndpoint, role: str = "mixed"):
        self.endpoint = endpoint
        # disaggregated serving role: "prefill" endpoints serve ONLY
        # prefill handoff hops (they never enter the classify/decode
        # pool); "decode"/"mixed" serve everything else
        self.role = role
        self.consecutive_failures = 0
        self.ejections = 0
        self.ejected_until = 0.0  # monotonic; 0 = not ejected
        self.in_trial = False     # half-open probe outstanding
        self.ewma_ms: Optional[float] = None
        # per-model dispatch-latency EWMAs: different models on one
        # endpoint can be orders of magnitude apart, so admission
        # estimates completion with the MODEL's observed service time
        # when it has one (overall EWMA as the cold fallback)
        self.model_ewma_ms: Dict[str, float] = {}
        self.inflight = 0         # router-dispatched, unresolved
        self.requests = 0
        self.failures = 0
        # wedge watchdog: heartbeats prove liveness, these prove
        # PROGRESS — the last observed (resolved/served/bursts)
        # signature and when it last moved while work was in flight
        self.progress_sig: Optional[Tuple] = None
        self.progress_at: Optional[float] = None
        self.wedged = False


class _Routed:
    """One router request across its (possibly several) dispatches.

    For a STREAMING decode request (``on_tokens`` set) this is also the
    stream's journal: ``received`` is the append-only token log (the
    dedupe-by-offset ledger AND the resume prefix a migration
    re-submits), ``epoch`` stamps the active dispatch so a late chunk
    from a dispatch the stream already migrated off can never corrupt
    the log, and ``dups``/``gaps``/``late`` account every chunk that
    was dropped rather than delivered (the no-gap/no-repeat audit)."""

    __slots__ = ("future", "kind", "x", "gen", "deadline", "t0", "tried",
                 "attempts", "outstanding", "lock", "hedged", "session",
                 "priority", "timer", "per_try_timeout", "model", "version",
                 "on_tokens", "received", "epoch", "dups", "gaps", "late",
                 "journal_dropped", "migrations", "prefix_key", "kv_state",
                 "troot", "tctx", "deadline_ms", "t_first_chunk",
                 "t_last_activity", "est_wait_ms")

    def __init__(self, kind: str, x, gen, deadline: Optional[float],
                 priority: str, session: Optional[str],
                 per_try_timeout: Optional[float],
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 on_tokens=None):
        self.future: "Future[np.ndarray]" = Future()
        self.kind = kind
        self.x = x
        self.gen = gen
        self.deadline = deadline    # monotonic, None = no deadline
        self.t0 = time.perf_counter()
        self.tried: set = set()
        self.attempts = 0
        self.outstanding = 0
        self.lock = threading.Lock()
        self.hedged = False
        self.session = session
        self.priority = priority
        self.timer: Optional["_TimerHandle"] = None  # armed hedge
        self.per_try_timeout = per_try_timeout
        self.model = model
        self.version = version
        self.on_tokens = on_tokens
        self.received: List[int] = []   # the session journal (tokens)
        self.epoch = 0                  # active-dispatch stamp
        self.dups = 0
        self.gaps = 0
        self.late = 0
        self.journal_dropped = False    # over budget: restart, not resume
        self.migrations = 0
        self.prefix_key: Optional[Tuple] = None
        # disaggregated prefill: the shipped {"kv","logits","t_in"}
        # handoff state (rides every dispatch until a journaled prefix
        # supersedes it — both paths yield exact tokens)
        self.kv_state = None
        # request trace: the root span minted at admission (this router
        # owns its lifecycle) + per-stream progress timestamps for the
        # TTFT/TPOT and silence-gap attribution
        self.troot = None
        self.tctx = None
        self.deadline_ms: Optional[float] = None  # set by _route
        self.t_first_chunk: Optional[float] = None
        self.t_last_activity: Optional[float] = None
        # admission estimate (queue-wait half): graded against observed
        # TTFT at finish — the estimator's report card series
        self.est_wait_ms: Optional[float] = None


class _TimerHandle:
    """A cancellable deferred call on the router loop (the surface the
    old per-request ``threading.Timer`` exposed: ``cancel()``)."""

    __slots__ = ("when", "fn", "args", "interval", "cancelled")

    def __init__(self, when: float, fn, args: tuple,
                 interval: Optional[float] = None):
        self.when = when
        self.fn = fn
        self.args = args
        self.interval = interval    # recurring period (None = one-shot)
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True


class _RouterLoop:
    """The router's event loop: ONE timer thread (heap + condition)
    runs every deferred router action — hedge timers, the wedge /
    journal-gauge tick — instead of one ``threading.Timer`` thread per
    request. Callbacks execute OUTSIDE the condition (the loop's lock
    orders before nothing — the PR-15 ``lock-order`` rule pins the
    graph acyclic as the per-timer threads collapse into this clock),
    and each executed callback's lag behind its deadline is reported
    through ``on_lag`` — the loop-health signal
    (``dl4j_router_loop_lag_ms``) a saturated dispatch plane shows
    first. The thread starts lazily on the first scheduled call."""

    def __init__(self, name: str = "dl4j-tpu-router-loop", on_lag=None):
        self._cond = threading.Condition()
        self._heap: List[Tuple[float, int, _TimerHandle]] = []
        self._seq = 0
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._name = name
        self._on_lag = on_lag

    def call_later(self, delay: float, fn, *args) -> _TimerHandle:
        return self._schedule(_TimerHandle(
            time.monotonic() + max(0.0, float(delay)), fn, args))

    def call_every(self, interval: float, fn, *args) -> _TimerHandle:
        """Recurring fixed-delay call: re-armed AFTER each run, so a
        slow callback never stacks overlapping invocations."""
        interval = max(1e-3, float(interval))
        return self._schedule(_TimerHandle(
            time.monotonic() + interval, fn, args, interval=interval))

    def _schedule(self, h: _TimerHandle) -> _TimerHandle:
        with self._cond:
            if self._closed:
                h.cancelled = True
                return h
            self._seq += 1
            heapq.heappush(self._heap, (h.when, self._seq, h))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name=self._name)
                self._thread.start()
            self._cond.notify()
        return h

    def _run(self) -> None:
        while True:
            fire: List[_TimerHandle] = []
            with self._cond:
                if self._closed:
                    return
                now = time.monotonic()
                while self._heap and self._heap[0][0] <= now:
                    h = heapq.heappop(self._heap)[2]
                    if not h.cancelled:
                        fire.append(h)
                if not fire:
                    timeout = None if not self._heap \
                        else max(0.0, self._heap[0][0] - now)
                    self._cond.wait(timeout)
                    continue
            # callbacks run OUTSIDE the condition: they may take the
            # router/registry locks freely without creating an edge
            # under the loop's own lock
            for h in fire:
                lag_ms = (time.monotonic() - h.when) * 1e3
                if self._on_lag is not None:
                    try:
                        self._on_lag(lag_ms)
                    except BaseException:
                        pass
                try:
                    h.fn(*h.args)
                except BaseException as e:
                    logger.warning("router loop: timer callback failed "
                                   "(%s: %s)", type(e).__name__, e)
                if h.interval is not None and not h.cancelled:
                    h.when = time.monotonic() + h.interval
                    self._schedule(h)

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2)


class InferenceRouter:
    """Dispatch classify/generate requests over a fleet of endpoints.

    Knobs: ``max_attempts`` bounds dispatches per request (first try +
    failovers + the hedge); ``eject_threshold`` consecutive failures
    eject an endpoint for ``eject_backoff_s`` (doubling per ejection,
    capped at ``eject_backoff_max_s``); ``hedge_after_ms`` arms the
    tail-latency duplicate (0 disables); ``heartbeat_timeout_s`` is
    how stale an endpoint's proof-of-life may grow before it leaves
    the pool; ``default_deadline_ms`` applies per priority class when
    a request names none (None = no deadline)."""

    def __init__(self, endpoints: Optional[List[EngineEndpoint]] = None,
                 max_attempts: int = 3,
                 eject_threshold: int = 2,
                 eject_backoff_s: float = 0.5,
                 eject_backoff_max_s: float = 30.0,
                 hedge_after_ms: float = 0.0,
                 per_try_timeout_s: Optional[float] = None,
                 default_deadline_ms: Optional[Dict[str, float]] = None,
                 ewma_alpha: float = 0.2,
                 wedge_timeout_s: Optional[float] = None,
                 journal_limit_tokens: int = 4096,
                 prefix_affinity_tokens: int = 32):
        self._eps: Dict[str, _EndpointState] = {}
        self._lock = threading.Lock()
        self._affinity: Dict[str, str] = {}
        self.max_attempts = max(1, int(max_attempts))
        self.eject_threshold = max(1, int(eject_threshold))
        self.eject_backoff = float(eject_backoff_s)
        self.eject_backoff_max = float(eject_backoff_max_s)
        self.hedge_after = max(0.0, float(hedge_after_ms)) / 1e3
        self.per_try_timeout = per_try_timeout_s
        self.default_deadline_ms = dict(default_deadline_ms or {})
        self.ewma_alpha = float(ewma_alpha)
        # wedge watchdog: an endpoint with router-dispatched work in
        # flight whose progress counters stay flat this long is treated
        # as FAILED (ejected; its streams migrate) even while its
        # heartbeats keep arriving. None = heartbeat-only health.
        self.wedge_timeout = (None if wedge_timeout_s is None
                              else float(wedge_timeout_s))
        # a stream whose journal outgrows this many tokens migrates by
        # RESTART instead of prefix-resume (the journal stays usable as
        # the dedupe ledger; it just stops being shipped as a prefix)
        self.journal_limit = max(1, int(journal_limit_tokens))
        # cache-aware affinity: prompt-prefix key (the first N token
        # ids) -> the endpoint that last served it. Consulted only to
        # break EXACT estimate ties — a warm prefix cache beats a cold
        # one, but never outranks health or deadline. 0 disables.
        self.prefix_affinity_tokens = max(0, int(prefix_affinity_tokens))
        self._prefix_owners: "OrderedDict[Tuple, str]" = OrderedDict()
        self._prefix_owners_cap = 4096
        # durable session handles: session -> {"prompt", "output",
        # "payload"?}. The prompt + full output journal make the
        # last-resort re-prefill rung; the shipped host-tier payload
        # (when the worker delivered one) makes the cross-endpoint
        # swap-in rung — either way the session survives its endpoint
        self._hibernated: Dict[str, Dict[str, Any]] = {}
        self._streams: set = set()      # in-flight streaming _Routed
        self._closed = False
        # the router's ONE clock: hedge timers and the wedge/journal
        # tick share a single loop thread instead of spawning a
        # threading.Timer per request; its lag histogram is the
        # dispatch plane's saturation signal
        self._loop = _RouterLoop(on_lag=self._note_loop_lag)
        self._loop_lag_last_ms = 0.0
        self._loop_lag_max_ms = 0.0
        if self.wedge_timeout is not None:
            # the watchdog also runs on the clock (not only on submit /
            # observation): a wedged endpoint is ejected and the
            # journal gauge stays fresh even while the caller is idle
            self._loop.call_every(
                min(0.25, self.wedge_timeout / 2.0), self._wedge_tick)
        for ep in endpoints or []:
            self.add_endpoint(ep)

    # -------------------------------------------------------- membership

    def add_endpoint(self, endpoint: EngineEndpoint,
                     role: str = "mixed") -> None:
        """``role="prefill"`` registers a PREFILL-specialized endpoint
        (the DistServe/Splitwise split): it never serves classify or
        decode traffic — the router routes generate admissions' prompt
        prefill to it and hands the session to a decode endpoint with
        the shipped KV (``dl4j_disagg_kv_handoffs_total``), which
        removes prefill head-of-line blocking from decode bursts."""
        if role not in ("mixed", "decode", "prefill"):
            raise ValueError(f"role must be mixed|decode|prefill, "
                             f"got {role!r}")
        with self._lock:
            if endpoint.name in self._eps:
                raise ValueError(f"duplicate endpoint {endpoint.name!r}")
            self._eps[endpoint.name] = _EndpointState(endpoint, role)
        self._health_gauge(endpoint.name).set(1.0)
        mark("router_endpoint_added", endpoint=endpoint.name, role=role)

    def remove_endpoint(self, name: str) -> Optional[EngineEndpoint]:
        with self._lock:
            st = self._eps.pop(name, None)
            self._affinity = {s: pin for s, pin in self._affinity.items()
                              if pin[0] != name}
        if st is None:
            return None
        self._health_gauge(name).set(0.0)
        mark("router_endpoint_removed", endpoint=name)
        return st.endpoint

    def endpoints(self) -> List[str]:
        with self._lock:
            return sorted(self._eps)

    # ----------------------------------------------------------- metrics

    def _reg(self):
        return get_registry()

    def _health_gauge(self, name: str):
        return self._reg().gauge(
            ROUTER_ENDPOINT_HEALTHY_GAUGE,
            "Endpoint in the router dispatch pool (1) or ejected/dead (0)",
            endpoint=name)

    def _note_loop_lag(self, lag_ms: float) -> None:
        """Executed-callback lag behind its scheduled deadline — the
        router loop's health signal (a saturated or blocked loop shows
        here before anything times out)."""
        self._loop_lag_last_ms = lag_ms
        if lag_ms > self._loop_lag_max_ms:
            self._loop_lag_max_ms = lag_ms
        self._reg().histogram(
            ROUTER_LOOP_LAG_HISTOGRAM,
            "Router event-loop timer lag: how late each executed "
            "deferred action (hedge, wedge/journal tick) ran behind "
            "its scheduled time").observe(lag_ms)

    def _wedge_tick(self) -> None:
        """Recurring loop tick: run the progress watchdog over every
        endpoint and refresh the journal gauge on the shared clock."""
        if self._closed or self.wedge_timeout is None:
            return
        now = time.monotonic()
        with self._lock:
            items = list(self._eps.values())
        for st in items:
            self._check_wedge(st, now)
        self._journal_gauge()

    # ------------------------------------------------------------ health

    def _pool(self, now: float, role: str = "serve"
              ) -> List[_EndpointState]:
        """Dispatchable endpoints: alive, not draining/stopped, slice
        not degraded, and either not ejected or half-open (backoff
        elapsed, no trial outstanding yet). The wedge watchdog runs
        here — liveness alone does not keep a non-progressing endpoint
        in the pool. ``role="serve"`` (classify/decode traffic)
        excludes prefill-specialized endpoints; ``role="prefill"``
        selects ONLY them (the disaggregation hop)."""
        out = []
        for st in self._eps.values():
            if role == "prefill":
                if st.role != "prefill":
                    continue
            elif st.role == "prefill":
                continue
            if not st.endpoint.alive():
                continue
            if self._endpoint_state(st) in (wire.STATE_DRAINING,
                                            wire.STATE_STOPPED):
                continue  # scale-down hand-off: finish there, pin here
            if self._slice_degraded(st):
                continue  # the slice positively declared itself dead
            if self.wedge_timeout is not None:
                self._check_wedge(st, now)
            if st.ejected_until > now and st.consecutive_failures:
                continue  # still serving out its ejection backoff
            out.append(st)
        return out

    @staticmethod
    def _slice_degraded(st: _EndpointState) -> bool:
        """A slice endpoint whose heartbeats carry ``slice.degraded``
        declared itself DEAD (a chip inside the slice failed): no
        timeout inference needed — it leaves the pool immediately and
        its pinned streams migrate."""
        try:
            sl = st.endpoint.stats().get("slice")
        except BaseException:
            return False
        return bool(isinstance(sl, dict) and sl.get("degraded"))

    @staticmethod
    def _endpoint_state(st: _EndpointState) -> Optional[str]:
        state = getattr(st.endpoint, "state", None)
        return state() if callable(state) else None

    def _check_wedge(self, st: _EndpointState, now: float) -> None:
        """Progress watchdog: with router-dispatched work in flight,
        the endpoint's monotonic counters (engine ``resolved``, worker
        ``served``, scheduler ``bursts``/``retired_rows`` — riding in
        every heartbeat) must keep moving. A configurable window of
        zero progress is a FAILURE: the endpoint is ejected exactly
        like a crash, and its pinned streams migrate on their next
        failover. Heartbeats prove liveness; this proves work."""
        with self._lock:
            inflight = st.inflight
        if inflight <= 0:
            with self._lock:
                st.progress_sig = None
                st.progress_at = None
            return
        stats = st.endpoint.stats()
        sched = stats.get("scheduler") or {}
        sig = (stats.get("resolved"), stats.get("served"),
               sched.get("bursts"), sched.get("retired_rows"))
        wedged = False
        with self._lock:
            if sig != st.progress_sig or st.progress_at is None:
                st.progress_sig = sig
                st.progress_at = now
                return
            if now - st.progress_at < self.wedge_timeout:
                return
            if st.ejected_until > now and st.consecutive_failures:
                return  # already out of the pool
            # zero progress with queued work for a full window: wedged
            st.wedged = True
            st.consecutive_failures = max(st.consecutive_failures,
                                          self.eject_threshold)
            backoff = min(self.eject_backoff_max,
                          self.eject_backoff * (2 ** st.ejections))
            st.ejections += 1
            st.ejected_until = now + backoff
            st.progress_at = now
            wedged = True
        if wedged:
            record_fault("routing")
            self._health_gauge(st.endpoint.name).set(0.0)
            mark("router_endpoint_wedged", endpoint=st.endpoint.name,
                 inflight=inflight)
            reqtrace.flight_trigger("wedge", endpoint=st.endpoint.name,
                                    inflight=inflight)

    def _note_success(self, st: _EndpointState, latency_ms: float,
                      model: Optional[str] = None) -> None:
        with self._lock:
            st.inflight = max(0, st.inflight - 1)
            was_ejected = st.consecutive_failures >= self.eject_threshold
            st.consecutive_failures = 0
            st.in_trial = False
            st.ejected_until = 0.0
            st.wedged = False
            st.progress_sig = None
            st.progress_at = None
            st.ewma_ms = (latency_ms if st.ewma_ms is None else
                          (1 - self.ewma_alpha) * st.ewma_ms
                          + self.ewma_alpha * latency_ms)
            if model is not None:
                prev = st.model_ewma_ms.get(model)
                st.model_ewma_ms[model] = (
                    latency_ms if prev is None else
                    (1 - self.ewma_alpha) * prev
                    + self.ewma_alpha * latency_ms)
        self._health_gauge(st.endpoint.name).set(1.0)
        if was_ejected:
            mark("router_endpoint_reinstated", endpoint=st.endpoint.name)

    def _note_failure(self, st: _EndpointState) -> None:
        with self._lock:
            st.inflight = max(0, st.inflight - 1)
            st.failures += 1
            st.consecutive_failures += 1
            st.in_trial = False
            ejected = st.consecutive_failures >= self.eject_threshold
            if ejected:
                backoff = min(self.eject_backoff_max,
                              self.eject_backoff * (2 ** st.ejections))
                st.ejections += 1
                st.ejected_until = time.monotonic() + backoff
        record_fault("routing")
        if ejected:
            self._health_gauge(st.endpoint.name).set(0.0)
            mark("router_endpoint_ejected", endpoint=st.endpoint.name,
                 failures=st.consecutive_failures)
            # ejection is a flight-recorder trigger: the ring of recent
            # traces + events dumps as JSONL when a dump_dir is armed —
            # the evidence an operator reads AFTER the endpoint is gone
            reqtrace.flight_trigger("ejection",
                                    endpoint=st.endpoint.name,
                                    failures=st.consecutive_failures)

    def probe_now(self) -> None:
        """Collapse every ejection backoff: each ejected endpoint turns
        half-open immediately (its next request is the reinstatement
        probe) — the deterministic seam tests and operators use."""
        with self._lock:
            for st in self._eps.values():
                st.ejected_until = 0.0
                st.in_trial = False

    # --------------------------------------------------------- admission

    def _estimate_ms(self, st: _EndpointState,
                     model: Optional[str] = None) -> Tuple[float, float]:
        """(queue_wait_ms, total_ms) completion estimate for one more
        request on this endpoint, from its last stats snapshot and the
        router's observed EWMA service time — the MODEL's own EWMA when
        the request names one and it has history (per-model admission:
        a heavy cotenant must not inflate a light model's estimate, nor
        hide its own). Cold endpoints (no latency observed yet)
        estimate 0 — admit optimistically and let observation catch
        up."""
        svc = st.ewma_ms
        if model is not None:
            svc = st.model_ewma_ms.get(model, svc)
        if svc is None:
            return 0.0, 0.0
        stats = st.endpoint.stats()
        depth = float(stats.get("queue_depth", 0) or 0)
        replicas = max(1.0, float(stats.get("healthy_replicas",
                                            stats.get("replicas", 1)) or 1))
        backlog = depth + st.inflight
        wait = (backlog / replicas) * svc
        return wait, wait + svc

    def _prefix_key(self, prompt, model: Optional[str]) -> Optional[Tuple]:
        """Affinity key for a decode prompt: its first
        ``prefix_affinity_tokens`` ids (+ the model) — the head shared
        system prompts share. None when disabled or unkeyable."""
        if self.prefix_affinity_tokens <= 0:
            return None
        try:
            row = np.asarray(prompt).reshape(-1)
        except Exception:
            return None
        if row.size == 0:
            return None
        head = tuple(int(t) for t in row[:self.prefix_affinity_tokens])
        return (model, head)

    def _prefix_owner(self, key: Optional[Tuple]) -> Optional[str]:
        if key is None:
            return None
        with self._lock:
            return self._prefix_owners.get(key)

    def _note_prefix_owner(self, key: Optional[Tuple], name: str) -> None:
        if key is None:
            return
        with self._lock:
            self._prefix_owners.pop(key, None)
            self._prefix_owners[key] = name
            while len(self._prefix_owners) > self._prefix_owners_cap:
                self._prefix_owners.popitem(last=False)

    def _admit(self, deadline_ms: Optional[float], priority: str,
               session: Optional[str],
               model: Optional[str] = None,
               prefix_key: Optional[Tuple] = None
               ) -> Tuple[_EndpointState, float, float]:
        """Pick the endpoint AND make the shed decision against it;
        returns ``(endpoint, est_wait_ms, est_total_ms)`` so the
        admission span can record the decision WITH its estimate
        inputs. Raises :class:`RetryAfter` when nothing can serve in
        time."""
        now = time.monotonic()
        pool = self._pool(now)
        if not pool:
            self._shed(priority, "no_endpoint", model)
            raise RetryAfter("no endpoint available", self.eject_backoff)
        # a half-open endpoint gets the next request as its probe
        with self._lock:
            trial = next((st for st in pool
                          if st.consecutive_failures >= self.eject_threshold
                          and not st.in_trial), None)
        pick: Optional[_EndpointState] = None
        if session is not None:
            pinned = self._affinity.get(session)
            if pinned is not None:
                pick = next((st for st in pool
                             if st.endpoint.name == pinned[0]), None)
                if pick is None:
                    # the KV-holding endpoint left the pool (died,
                    # drained, or was ejected): this admission is a
                    # session migration — the stream re-pins below
                    st0 = self._eps.get(pinned[0])
                    if st0 is None:
                        reason = "endpoint_lost"
                    elif self._endpoint_state(st0) in (
                            wire.STATE_DRAINING, wire.STATE_STOPPED):
                        reason = "drain"
                    elif self._slice_degraded(st0):
                        reason = "slice_degraded"
                    elif st0.wedged:
                        reason = "wedged"
                    else:
                        reason = "endpoint_lost"
                    self._note_migration(reason)
                    mark("router_session_repinned", session=session,
                         frm=pinned[0], reason=reason)
        if pick is None and trial is not None:
            pick = trial
            with self._lock:
                trial.in_trial = True
        if pick is None:
            # least estimated wait; a warm prefix cache breaks EXACT
            # estimate ties (the cache-aware affinity satellite);
            # stable name tie-break last
            owner = self._prefix_owner(prefix_key)
            pick = min(pool, key=lambda st: (
                self._estimate_ms(st, model)[0],
                0 if st.endpoint.name == owner else 1,
                st.endpoint.name))
        wait_ms, total_ms = self._estimate_ms(pick, model)
        self._reg().histogram(
            ROUTER_QUEUE_WAIT_HISTOGRAM,
            "Estimated queue wait at admission time").observe(wait_ms)
        # backlog the admission decision saw on the picked endpoint
        # (reported queue depth + router-dispatched inflight) — the
        # pressure-over-time series behind window queries
        ts_record(TS_ROUTER_QUEUE_DEPTH,
                  float((pick.endpoint.stats() or {}).get("queue_depth", 0)
                        or 0) + pick.inflight)
        if deadline_ms is not None:
            headroom = PRIORITY_HEADROOM.get(priority, 1.0)
            if total_ms > deadline_ms * headroom:
                self._shed(priority, "deadline", model)
                raise RetryAfter(
                    f"estimated completion {total_ms:.1f}ms exceeds "
                    f"deadline {deadline_ms:.1f}ms × {headroom} headroom "
                    f"({priority})", max(1e-3, wait_ms / 1e3))
        if session is not None:
            # pin (endpoint, model): the stream's KV state lives on one
            # endpoint, and the version pin rides engine-side on the
            # same session key
            self._affinity[session] = (pick.endpoint.name, model)
        return pick, wait_ms, total_ms

    def _note_migration(self, reason: str) -> None:
        self._reg().counter(
            SESSION_MIGRATIONS_COUNTER,
            "Decode-session migrations: the stream's endpoint failed "
            "(or drained/wedged) and the router re-pinned it, resuming "
            "from the journaled prefix where possible",
            reason=reason).inc()
        reqtrace.flight_event("migration", reason=reason)

    def _migration_reason(self, st: _EndpointState,
                          err: BaseException) -> str:
        from deeplearning4j_tpu.serving.endpoint import EndpointTimeout
        if type(err).__name__ == "SliceDegraded":
            return "slice_degraded"
        if st.wedged:
            return "wedged"
        if isinstance(err, EndpointTimeout):
            return "timeout"
        if type(err).__name__ == "DecodeBurstError":
            return "burst_error"
        return "endpoint_error"

    def _journal_gauge(self) -> None:
        with self._lock:
            size = sum(len(rf.received) for rf in self._streams)
        self._reg().gauge(
            SESSION_JOURNAL_BYTES_GAUGE,
            "Live bytes of journaled stream tokens (what a migration "
            "would re-prefill)").set(8 * size)

    def _shed(self, priority: str, reason: str,
              model: Optional[str] = None) -> None:
        labels = {"priority": priority, "reason": reason}
        if model is not None:
            labels["model"] = model
        self._reg().counter(
            ROUTER_SHED_COUNTER,
            "Requests rejected by deadline admission control",
            **labels).inc()
        ts_record(TS_ROUTER_SHED, 1.0)
        mark("router_shed", priority=priority, reason=reason)

    # ------------------------------------------------------------ submit

    def submit(self, x: np.ndarray, deadline_ms: Optional[float] = None,
               priority: str = "interactive",
               session: Optional[str] = None,
               model: Optional[str] = None,
               version: Optional[int] = None) -> "Future[np.ndarray]":
        """Route one classify request (x: [n, ...features]); the Future
        resolves to the [n, ...out] predictions, possibly after
        failover/hedging, or raises :class:`RetryAfter` HERE (before a
        Future exists) when admission sheds it. ``model=``/``version=``
        route multi-model engines; admission then estimates with that
        model's per-endpoint latency EWMA."""
        return self._route(np.asarray(x), None, "classify", deadline_ms,
                           priority, session, model, version)

    def submit_generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        priority: str = "interactive",
                        session: Optional[str] = None,
                        model: Optional[str] = None,
                        version: Optional[int] = None,
                        on_tokens=None,
                        hibernate: bool = False,
                        **gen_kwargs) -> "Future[np.ndarray]":
        """Route one decode request; ``session=`` keeps every burst of
        a decode stream on the (endpoint, model, version) it started on
        — the endpoint pin lives here, the version pin rides the same
        session key down in the engine, so a mid-stream hot-swap never
        switches KV-cache owners.

        ``on_tokens(offset, tokens)`` makes this a DURABLE STREAM: the
        callback receives append-only token deltas (dedupe-by-offset —
        no gap, no repeat, asserted in the journal), the router records
        every received token in a per-stream journal, and when the
        serving endpoint dies mid-generation (timeout, heartbeat loss,
        typed burst error, wedge) the stream MIGRATES: it re-pins and
        re-submits prompt + received prefix as a resume request, so the
        surviving engine re-prefills only the prefix instead of
        re-generating it, and the delivered tokens are token-for-token
        what an uninterrupted run would have produced.

        ``hibernate=True`` (requires ``session=``) makes the turn file
        a DURABLE session handle at end-of-turn: the serving engine
        parks the session's KV in its host tier instead of freeing it,
        and the router records the prompt + output journal plus — when
        the worker ships one — the host-tier payload itself. A later
        :meth:`resume_generate` restores the session on ANY endpoint
        (swap-in when the pin survived, shipped blocks on a survivor,
        journaled re-prefill as the last resort), token-for-token what
        an uninterrupted run would have produced."""
        if hibernate and session is None:
            raise ValueError(
                "hibernate=True files a durable session handle at "
                "end-of-turn — it needs session=")
        gen = dict(gen_kwargs, max_new_tokens=int(max_new_tokens))
        if hibernate:
            gen["hibernate"] = True
        fut = self._route(np.asarray(prompt_ids), gen, "generate",
                          deadline_ms, priority, session, model, version,
                          on_tokens)
        if hibernate:
            prompt = np.asarray(prompt_ids)

            def _file(f):
                # the journal half of the handle: prompt + full output,
                # enough for the re-prefill rung even when no payload
                # ever ships (v3 peer, over-budget tier)
                if f.exception() is None:
                    self._note_hibernated_turn(session, prompt,
                                               np.asarray(f.result()))
            fut.add_done_callback(_file)
        return fut

    def stream(self, prompt_ids, max_new_tokens,
               timeout: Optional[float] = None, **kwargs):
        """Generator facade over the streaming seam: yields ``(offset,
        tokens)`` deltas as they arrive (migration-transparent — the
        offsets are contiguous across an engine death) and returns
        after the terminal frame; raises the stream's error if it
        ultimately failed. ``stream=True`` ergonomics for callers that
        would rather iterate than register a callback."""
        q: "queue.Queue" = queue.Queue()
        fut = self.submit_generate(
            prompt_ids, max_new_tokens,
            on_tokens=lambda off, toks: q.put((off, toks)), **kwargs)
        fut.add_done_callback(lambda f: q.put(None))
        while True:
            item = q.get(timeout=timeout)
            if item is None:
                err = fut.exception()
                if err is not None:
                    raise err
                return
            yield item

    def output(self, x, timeout: Optional[float] = None, **kwargs):
        return self.submit(x, **kwargs).result(timeout=timeout)

    def generate(self, prompt_ids, max_new_tokens,
                 timeout: Optional[float] = None, **kwargs):
        return self.submit_generate(prompt_ids, max_new_tokens,
                                    **kwargs).result(timeout=timeout)

    # ------------------------------------------------------ hibernation

    def _note_hibernated_turn(self, session: str, prompt: np.ndarray,
                              output: np.ndarray) -> None:
        with self._lock:
            rec = self._hibernated.setdefault(session, {})
            rec["prompt"] = np.asarray(prompt).reshape(1, -1)
            rec["output"] = np.asarray(output).reshape(1, -1)

    def _store_hibernation(self, session: str, payload) -> None:
        """The worker shipped the session's host-tier payload (KV
        blocks + token journal): park it — this is what makes resume
        survive the endpoint's death without a re-prefill."""
        with self._lock:
            rec = self._hibernated.setdefault(session, {})
            rec["payload"] = payload
        mark("router_session_hibernated", session=session,
             blocks=len(payload.get("blocks") or ()))

    def hibernation_handle(self, session: str) -> Optional[Dict[str, Any]]:
        """The durable handle of a hibernated session (None when the
        session has none): ``prompt`` + ``output`` journal, plus the
        shipped host-tier ``payload`` when the worker delivered one."""
        with self._lock:
            rec = self._hibernated.get(session)
            return dict(rec) if rec is not None else None

    def hibernated_sessions(self) -> List[str]:
        with self._lock:
            return sorted(self._hibernated)

    def release_hibernated(self, session: str) -> bool:
        """Drop a session's durable handle (the abandon path — a
        resume consumes it itself)."""
        with self._lock:
            return self._hibernated.pop(session, None) is not None

    def resume_generate(self, session: str, max_new_tokens: int,
                        deadline_ms: Optional[float] = None,
                        priority: str = "interactive",
                        model: Optional[str] = None,
                        version: Optional[int] = None,
                        on_tokens=None,
                        hibernate: bool = False,
                        **gen_kwargs) -> "Future[np.ndarray]":
        """Resume a hibernated session for its next turn, restoring its
        KV state down a three-rung exactness ladder — every rung yields
        the tokens an uninterrupted run would have produced:

        1. **host** — the pinned endpoint is still alive: the request
           routes back to it and its scheduler swaps the session's
           blocks in from the host tier (no re-prefill);
        2. **ship** — the pin is gone (endpoint died, drained, was
           removed) but the worker shipped the host-tier payload at
           hibernate time: the blocks ride the request to a SURVIVOR,
           which seeds its own host tier and swaps in;
        3. **journal** — no payload: the survivor re-prefills prompt +
           journaled output (``dl4j_kvtier_restore_total``
           ``path="journal"``), exact but costlier.

        ``max_new_tokens`` counts ALL generated tokens of the session
        including earlier turns' (the resume prefix) — the same
        contract as a stream migration's resume. ``on_tokens`` offsets
        continue where the hibernated turn left off (no gap, no
        repeat). ``hibernate=True`` re-files the handle at this turn's
        end, chaining turns indefinitely."""
        with self._lock:
            rec = dict(self._hibernated.get(session) or {})
        if not rec or "output" not in rec:
            raise KeyError(f"no hibernated session {session!r}")
        prompt = rec["prompt"]
        output = rec["output"]
        t0 = prompt.shape[1]
        prefix = np.asarray(output[0, t0:], np.int64)
        pin = self._affinity.get(session)
        pinned_alive = False
        if pin is not None:
            st0 = self._eps.get(pin[0])
            pinned_alive = (
                st0 is not None and st0.endpoint.alive()
                and self._endpoint_state(st0) not in (
                    wire.STATE_DRAINING, wire.STATE_STOPPED)
                and not self._slice_degraded(st0) and not st0.wedged)
            if model is None:
                model = pin[1]
        gen = dict(gen_kwargs, max_new_tokens=int(max_new_tokens))
        if hibernate:
            gen["hibernate"] = True
        if prefix.size:
            gen["prefix"] = prefix
        path = "host"
        if not pinned_alive:
            with self._lock:
                self._affinity.pop(session, None)  # re-pin on a survivor
            payload = rec.get("payload")
            if payload is not None:
                # rung 2: the parked host-tier blocks ride the request
                # to whichever endpoint admission picks
                gen["kv_state"] = payload
                path = "ship"
            else:
                path = "journal"
                self._reg().counter(
                    KVTIER_RESTORE_COUNTER,
                    "Hibernated-session restores by path (host = local "
                    "swap-in, ship = cross-endpoint shipped blocks, "
                    "journal = re-prefill from the token journal)",
                    path="journal").inc()
        mark("router_session_resumed", session=session, path=path,
             prefix=int(prefix.size))
        fut = self._route(
            prompt, gen, "generate", deadline_ms, priority, session,
            model, version, on_tokens,
            seed_received=(prefix.tolist() if on_tokens is not None
                           else None))
        with self._lock:
            self._hibernated.pop(session, None)  # the resume consumed it
        if hibernate:
            def _file(f):
                if f.exception() is None:
                    self._note_hibernated_turn(session, prompt,
                                               np.asarray(f.result()))
            fut.add_done_callback(_file)
        return fut

    def _route(self, x, gen, kind, deadline_ms, priority, session,
               model=None, version=None, on_tokens=None,
               seed_received=None):
        if self._closed:
            raise RuntimeError("router is closed")
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms.get(priority)
        labels = {"priority": priority}
        if model is not None:
            labels["model"] = model
        self._reg().counter(
            ROUTER_REQUESTS_COUNTER, "Requests routed", **labels).inc()
        prefix_key = (self._prefix_key(x, model) if kind == "generate"
                      else None)
        # the request trace is MINTED HERE, at router admission — the
        # root span every hop's child spans (dispatch, wire, engine,
        # scheduler) resolve back to, and the unit the flight recorder
        # retains. Sampling decides once; an unsampled request carries
        # a None context and every downstream record no-ops.
        troot = reqtrace.begin_trace(
            "request", kind=kind, priority=priority,
            **{k: v for k, v in (("model", model), ("session", session))
               if v is not None})
        tctx = None if troot is None else troot.ctx
        t_adm = time.perf_counter()
        try:
            st, est_wait, est_total = self._admit(
                deadline_ms, priority, session, model, prefix_key)
        except RetryAfter as e:
            # the admission decision is recorded WITH its estimate
            # inputs — a shed trace completes right here, attributing
            # the rejection instead of silently vanishing
            reqtrace.record_span(
                tctx, "admission", to_origin_us(t_adm),
                (time.perf_counter() - t_adm) * 1e6, decision="shed",
                deadline_ms=deadline_ms,
                retry_after_s=round(e.retry_after_s, 6))
            self._slo_burn(model, "shed")
            reqtrace.finish_trace(troot, outcome="shed")
            raise
        reqtrace.record_span(
            tctx, "admission", to_origin_us(t_adm),
            (time.perf_counter() - t_adm) * 1e6, decision="admitted",
            endpoint=st.endpoint.name, est_wait_ms=round(est_wait, 3),
            est_total_ms=round(est_total, 3), deadline_ms=deadline_ms,
            headroom=PRIORITY_HEADROOM.get(priority, 1.0))
        rf = _Routed(kind, x, gen,
                     None if deadline_ms is None
                     else time.monotonic() + deadline_ms / 1e3,
                     priority, session, self.per_try_timeout,
                     model, version, on_tokens)
        if seed_received:
            # resumed session: the journal opens with the already-
            # delivered tokens, so the engine's emission offsets (which
            # start past the resume prefix) align — no false gap, and
            # the dedupe ledger spans turns
            rf.received.extend(int(t) for t in seed_received)
        rf.prefix_key = prefix_key
        rf.troot, rf.tctx = troot, tctx
        rf.deadline_ms = deadline_ms
        rf.est_wait_ms = est_wait
        if tctx is not None:
            # surface the trace id to the caller (bench/debug lookup)
            rf.future.trace_id = tctx.trace_id
        if on_tokens is not None:
            with self._lock:
                self._streams.add(rf)
        pf = None
        if kind == "generate":
            # disaggregated prefill/decode: when a prefill-specialized
            # endpoint is up, the prompt's KV is computed THERE and the
            # session hands to the decode endpoint like a resume —
            # decode bursts never stall behind a long prompt forward
            pf = self._pick_prefill()
        if pf is not None:
            self._dispatch_disagg(rf, st, pf)
        else:
            self._dispatch(rf, st)
        if self.hedge_after > 0 and session is None and \
                on_tokens is None and self.max_attempts > 1:
            # candidate availability is checked when the timer FIRES —
            # an endpoint added after dispatch is a valid hedge target.
            # Streams never hedge: a duplicate stream would double-emit.
            # The hedge rides the router loop: one clock, no per-request
            # threading.Timer thread.
            rf.timer = self._loop.call_later(self.hedge_after,
                                             self._hedge, rf)
        return rf.future

    # --------------------------------------------------------- dispatch

    def _pick_prefill(self) -> Optional[_EndpointState]:
        """The least-loaded healthy PREFILL-role endpoint, or None when
        disaggregation is not configured (no prefill endpoint alive) —
        the caller then runs the classic fused path."""
        pool = self._pool(time.monotonic(), role="prefill")
        if not pool:
            return None
        return min(pool, key=lambda st: (self._estimate_ms(st)[0],
                                         st.endpoint.name))

    def _dispatch_disagg(self, rf: _Routed, st: _EndpointState,
                         pf: _EndpointState) -> None:
        """The disaggregation hop: prefill on ``pf``, then dispatch the
        decode half to ``st`` with the shipped KV. A prefill failure is
        NOT a request failure — the decode endpoint just prefills
        locally (same tokens, classic path)."""
        with self._lock:
            pf.requests += 1
            pf.inflight += 1
        t0 = time.perf_counter()
        hspan = reqtrace.start_span("prefill_hop", rf.tctx,
                                    endpoint=pf.endpoint.name)
        try:
            with reqtrace.use_trace(None if hspan is None else hspan.ctx):
                inner = pf.endpoint.submit_prefill(
                    rf.x, timeout_s=rf.per_try_timeout)
        except BaseException as e:
            if hspan is not None:
                hspan.close(outcome="error", error=type(e).__name__)
            self._note_failure(pf)
            self._dispatch(rf, st)
            return

        def _after(f: Future) -> None:
            err = f.exception()
            if err is None:
                if hspan is not None:
                    hspan.close(outcome="ok")
                self._note_success(pf, (time.perf_counter() - t0) * 1e3)
                with rf.lock:
                    rf.kv_state = f.result()
                mark("router_disagg_handoff", prefill=pf.endpoint.name,
                     decode=st.endpoint.name)
            else:
                if hspan is not None:
                    hspan.close(outcome="error", error=type(err).__name__)
                self._note_failure(pf)
            self._dispatch(rf, st)
        inner.add_done_callback(_after)

    @staticmethod
    def _typed_engine_error(e: BaseException) -> bool:
        """Engine errors that must surface to the caller as their own
        type (not wrapped in EndpointError) — the same classes a
        LocalEndpoint's in-process engine raises for a shed or a
        quarantined model."""
        from deeplearning4j_tpu.parallel.inference import \
            InferenceBackpressure
        from deeplearning4j_tpu.serving.registry import ModelUnavailable
        return isinstance(e, (InferenceBackpressure, ModelUnavailable))

    def _dispatch(self, rf: _Routed, st: _EndpointState) -> None:
        resume_prefix = None
        with rf.lock:
            rf.attempts += 1
            attempt = rf.attempts
            rf.outstanding += 1
            rf.tried.add(st.endpoint.name)
            if rf.on_tokens is not None:
                # stamp the active dispatch: chunks from any earlier
                # dispatch (a slow-not-dead engine replying late) are
                # dropped by epoch, never merged into the journal
                rf.epoch += 1
                epoch = rf.epoch
                if rf.attempts > 1 and rf.received \
                        and not rf.journal_dropped:
                    # MIGRATION RESUME: ship the journaled prefix; the
                    # new engine re-prefills prompt + prefix and emits
                    # from offset len(prefix) — no re-generation, no
                    # re-emission
                    resume_prefix = np.asarray(rf.received, np.int64)
        with self._lock:
            st.requests += 1
            st.inflight += 1
        if rf.kind == "generate":
            # this endpoint is about to hold the prompt's prefix (its
            # scheduler caches it on retire) — remember it for the
            # cache-aware tiebreak on the next same-prefix admission
            self._note_prefix_owner(rf.prefix_key, st.endpoint.name)
        if resume_prefix is not None:
            self._reg().counter(
                ROUTER_RESUME_PREFIX_COUNTER,
                "Journaled prefix tokens re-submitted by stream "
                "migrations (re-prefilled, not re-generated)"
            ).inc(len(resume_prefix))
        t_disp = time.perf_counter()
        rf.t_last_activity = t_disp
        # the dispatch span opens NOW (its id must exist before the
        # endpoint call so engine/worker child spans can parent to it)
        # and closes when this attempt's future resolves
        dspan = reqtrace.start_span(
            "dispatch", rf.tctx, endpoint=st.endpoint.name,
            attempt=attempt, kind=rf.kind,
            **({"resume_prefix": int(len(resume_prefix))}
               if resume_prefix is not None else {}))
        # routing fields travel only when set, so single-model
        # endpoints (and minimal EngineEndpoint stubs) keep working
        route = {k: v for k, v in (("model", rf.model),
                                   ("version", rf.version),
                                   ("session", rf.session))
                 if v is not None}
        try:
            with reqtrace.use_trace(None if dspan is None else dspan.ctx):
                if rf.kind == "generate":
                    g = dict(rf.gen)
                    if g.get("hibernate") and rf.session is not None:
                        # the worker ships the session's host-tier
                        # payload before the terminal reply; parking it
                        # here is what survives the endpoint's death
                        g["on_hibernate"] = (
                            lambda payload, s=rf.session:
                            self._store_hibernation(s, payload))
                    if rf.on_tokens is not None:
                        g["on_tokens"] = (
                            lambda off, toks, e=epoch:
                            self._on_chunk(rf, e, off, toks))
                    if resume_prefix is not None:
                        g["prefix"] = resume_prefix
                    elif rf.kv_state is not None:
                        # shipped prompt KV: the decode endpoint admits
                        # the session without recomputing the prompt (a
                        # journaled-prefix resume supersedes it — both
                        # are exact)
                        g["kv_state"] = rf.kv_state
                    inner = st.endpoint.submit_generate(
                        rf.x, g.pop("max_new_tokens"),
                        timeout_s=rf.per_try_timeout, **route, **g)
                else:
                    inner = st.endpoint.submit(
                        rf.x, timeout_s=rf.per_try_timeout, **route)
        except BaseException as e:
            # submit itself failed (endpoint closed / backpressure /
            # model quarantine): resolve through the same failure path
            # as a bad reply, PRESERVING the typed engine errors so the
            # caller sees the same exception a local engine would raise
            inner = Future()
            inner.set_exception(
                e if isinstance(e, (EndpointError, RetryAfter))
                or self._typed_engine_error(e) else EndpointError(str(e)))
        inner.add_done_callback(
            lambda f: self._on_done(rf, st, f, t_disp, dspan))

    def _on_chunk(self, rf: _Routed, epoch: int, off: int, toks) -> None:
        """Journal + dedupe one incremental chunk, then deliver ONLY
        the genuinely-new tokens to the caller. The append-only
        invariant lives here: a token enters the journal exactly when
        its offset equals the journal length, so across timeouts,
        migrations and late replies the caller observes every offset
        once, in order — no gap, no repeat."""
        toks = np.asarray(toks).reshape(-1)
        with rf.lock:
            if epoch != rf.epoch or rf.future.done():
                rf.late += len(toks)
                return
            now = time.perf_counter()
            rf.t_last_activity = now
            if rf.t_first_chunk is None:
                rf.t_first_chunk = now  # TTFT as the caller saw it
            start = len(rf.received)
            for i, t in enumerate(toks.tolist()):
                idx = int(off) + i
                if idx < len(rf.received):
                    rf.dups += 1       # already delivered: dropped
                elif idx == len(rf.received):
                    rf.received.append(int(t))
                else:
                    rf.gaps += 1       # out-of-order hole: never valid
            if len(rf.received) > self.journal_limit:
                # over the journal budget: keep the dedupe ledger but
                # stop offering it as a resume prefix — a migration of
                # this stream restarts (still exact, just costlier)
                rf.journal_dropped = True
            new = rf.received[start:]
            noff = start
            cb = rf.on_tokens
        self._journal_gauge()
        if new and cb is not None:
            try:
                cb(noff, np.asarray(new, np.int64))
            except BaseException as e:
                mark("stream_callback_error", error=type(e).__name__)

    def _hedge(self, rf: _Routed) -> None:
        """Tail-latency duplicate: one extra dispatch to an untried
        endpoint when the primary is slow; first reply wins. The
        duplicate is safe by construction — classify is pure, and a
        duplicate's Future result is simply dropped (``set_result``
        first-wins under ``rf.lock``)."""
        with rf.lock:
            if rf.future.done() or rf.hedged or \
                    rf.attempts >= self.max_attempts:
                return
            rf.hedged = True
            tried = set(rf.tried)
        st = self._pick_excluding(tried, rf.model)
        if st is None:
            return
        self._reg().counter(
            ROUTER_HEDGES_COUNTER,
            "Hedged duplicate dispatches (tail-latency)").inc()
        mark("router_hedge", endpoint=st.endpoint.name)
        reqtrace.trace_event(rf.tctx, "hedge", endpoint=st.endpoint.name)
        self._dispatch(rf, st)

    def _pick_excluding(self, tried: set,
                        model: Optional[str] = None
                        ) -> Optional[_EndpointState]:
        now = time.monotonic()
        pool = [st for st in self._pool(now)
                if st.endpoint.name not in tried]
        if not pool:
            return None
        return min(pool, key=lambda st: (self._estimate_ms(st, model)[0],
                                         st.endpoint.name))

    def _on_done(self, rf: _Routed, st: _EndpointState, inner: Future,
                 t_disp: float, dspan=None):
        err = inner.exception()
        if err is None:
            now = time.perf_counter()
            if dspan is not None:
                dspan.close(outcome="ok")
            # the endpoint's EWMA tracks ITS dispatch→reply time only;
            # attributing the full request latency would pollute a
            # healthy endpoint's estimate with the timeout a dead
            # sibling burned before the failover reached it
            self._note_success(st, (now - t_disp) * 1e3, rf.model)
            with rf.lock:
                rf.outstanding -= 1
                won = not rf.future.done()
                if won:
                    rf.future.set_result(inner.result())
            if won:
                if rf.timer is not None:
                    rf.timer.cancel()
                self._reg().histogram(
                    ROUTER_LATENCY_HISTOGRAM,
                    "End-to-end submit→result latency through the "
                    "router").observe((now - rf.t0) * 1e3)
                self._finish_request(rf, now)
                self._stream_done(rf)
            return
        # failure: endpoint bookkeeping, then failover if budget allows
        if dspan is not None:
            dspan.close(outcome="error", error=type(err).__name__)
        self._note_failure(st)
        retry_to: Optional[_EndpointState] = None
        give_up = False
        with rf.lock:
            rf.outstanding -= 1
            if rf.future.done():
                return
            expired = rf.deadline is not None and \
                time.monotonic() >= rf.deadline
            if rf.attempts < self.max_attempts and not expired:
                retry_to = self._pick_excluding(rf.tried, rf.model)
            if retry_to is None and rf.outstanding == 0:
                give_up = True
        if retry_to is not None:
            t_detect = time.perf_counter()
            is_stream = rf.on_tokens is not None or rf.session is not None
            reason = None
            if is_stream:
                # this failover moves a decode stream: account the
                # migration (the resume prefix rides in _dispatch), and
                # attribute the SILENCE the stream just sat through —
                # last delivered chunk (or the dispatch) → detection.
                # This span is most of the migration token-gap.
                reason = self._migration_reason(st, err)
                rf.migrations += 1
                self._note_migration(reason)
                t_quiet = rf.t_last_activity if rf.t_last_activity \
                    is not None else t_disp
                reqtrace.record_span(
                    rf.tctx, "silence_wait", to_origin_us(t_quiet),
                    (t_detect - t_quiet) * 1e6, reason=reason,
                    endpoint=st.endpoint.name,
                    error=type(err).__name__)
                mark("router_stream_migrated", frm=st.endpoint.name,
                     to=retry_to.endpoint.name, reason=reason,
                     prefix=len(rf.received))
            if rf.session is not None:
                # the pinned endpoint failed: re-pin the session
                self._affinity[rf.session] = (retry_to.endpoint.name,
                                              rf.model)
            self._reg().counter(
                ROUTER_FAILOVERS_COUNTER,
                "Requests re-dispatched to another endpoint after an "
                "endpoint failure").inc()
            mark("router_failover", frm=st.endpoint.name,
                 to=retry_to.endpoint.name)
            t_repin = time.perf_counter()
            self._dispatch(rf, retry_to)
            if is_stream:
                # the re-pin decision + resume re-submit, distinct from
                # the silence it ends and the resume prefill that
                # follows engine-side
                reqtrace.record_span(
                    rf.tctx, "repin", to_origin_us(t_repin),
                    (time.perf_counter() - t_repin) * 1e6,
                    frm=st.endpoint.name, to=retry_to.endpoint.name,
                    reason=reason, prefix=len(rf.received))
            else:
                reqtrace.trace_event(rf.tctx, "failover",
                                     frm=st.endpoint.name,
                                     to=retry_to.endpoint.name)
        elif give_up:
            if rf.timer is not None:
                rf.timer.cancel()
            if not rf.future.done():
                self._finish_request(rf, time.perf_counter(), err)
                rf.future.set_exception(err)
            self._stream_done(rf)

    def _slo_burn(self, model: Optional[str], outcome: str) -> None:
        """Tick the per-model SLO burn counter: ``missed`` + ``shed`` +
        ``failed`` outcomes burn the error budget, ``met`` is the
        denominator — burn rate = burned / total."""
        self._reg().counter(
            REQ_SLO_BURN_COUNTER,
            "Per-model SLO outcomes (met / missed deadline / shed at "
            "admission / failed) — missed+shed+failed burn the budget",
            model=model if model is not None else "default",
            outcome=outcome).inc()
        if outcome != "met":
            # burn-event series: one sample per burned request, so a
            # window query's COUNT is "misses over the window" — the
            # signal the flight recorder's burn trigger reads
            ts_record(TS_SLO_BURN, 1.0)
            reqtrace.note_slo_burn(outcome, model=model)

    def _finish_request(self, rf: _Routed, now: float,
                        err: Optional[BaseException] = None) -> None:
        """Request-level SLO attribution + trace completion: TTFT as
        the CALLER observed it (first delivered chunk; terminal reply
        for non-streams), TPOT across the delivered tokens, the
        deadline verdict, and the sealed trace handed to the flight
        recorder."""
        total_ms = (now - rf.t0) * 1e3
        with rf.lock:
            t_first = rf.t_first_chunk
            tokens = len(rf.received)
        ttft_ms = ((t_first - rf.t0) * 1e3 if t_first is not None
                   else total_ms)
        if err is None and rf.est_wait_ms is not None:
            # admission-estimate report card: how far off the queue-wait
            # estimate was from the wait the caller actually observed
            # (signed — positive means the estimator was optimistic)
            ts_record(TS_ROUTER_ADMIT_ERROR, ttft_ms - rf.est_wait_ms)
        reg = self._reg()
        model = rf.model if rf.model is not None else "default"
        reg.histogram(
            REQ_TTFT_HISTOGRAM,
            "Time to first token as the caller observed it (terminal "
            "reply for non-streaming requests)",
            model=model).observe(ttft_ms)
        tpot_ms = None
        if t_first is not None and tokens > 1:
            tpot_ms = (now - t_first) * 1e3 / (tokens - 1)
            reg.histogram(
                REQ_TPOT_HISTOGRAM,
                "Time per output token after the first (streamed "
                "decode requests)", model=model).observe(tpot_ms)
        if err is not None:
            self._slo_burn(rf.model, "failed")
        elif rf.deadline_ms is not None:
            self._slo_burn(rf.model,
                           "met" if total_ms <= rf.deadline_ms
                           else "missed")
        attrs = {"outcome": "error" if err is not None else "ok",
                 "total_ms": round(total_ms, 3),
                 "ttft_ms": round(ttft_ms, 3),
                 "migrations": rf.migrations, "hedged": rf.hedged,
                 "attempts": rf.attempts}
        if tokens:
            attrs["tokens"] = tokens
        if tpot_ms is not None:
            attrs["tpot_ms"] = round(tpot_ms, 3)
        if err is not None:
            attrs["error"] = type(err).__name__
        reqtrace.finish_trace(rf.troot, **attrs)

    def _stream_done(self, rf: _Routed) -> None:
        if rf.on_tokens is None:
            return
        with self._lock:
            self._streams.discard(rf)
        self._journal_gauge()

    # ------------------------------------------------------------- state

    def fleet_snapshot(self) -> Dict[str, Any]:
        """Aggregated fleet state: what ``/healthz`` serves and what
        :class:`ScalePolicy` consumes."""
        now = time.monotonic()
        eps = {}
        with self._lock:
            items = list(self._eps.items())
        healthy = 0
        queue_depth = 0.0
        ts_summaries: List[Dict[str, Any]] = []
        for name, st in items:
            if self.wedge_timeout is not None:
                # the watchdog also runs on observation, so a wedged
                # endpoint is caught even while no new submit arrives
                self._check_wedge(st, now)
            alive = st.endpoint.alive()
            ejected = bool(st.ejected_until > now
                           and st.consecutive_failures)
            in_pool = alive and not ejected
            healthy += in_pool
            stats = st.endpoint.stats()
            queue_depth += float(stats.get("queue_depth", 0) or 0)
            last = st.endpoint.last_seen
            # prefix-cache summary riding the endpoint's stats snapshot
            # (heartbeat-carried for remote workers): cached-prefix
            # count + bytes + hit rate — the cache-aware affinity view
            pc = (stats.get("scheduler") or {}).get("prefix_cache") \
                if isinstance(stats.get("scheduler"), dict) else None
            prefix_cache = None
            if isinstance(pc, dict):
                prefix_cache = {
                    "cached_blocks": pc.get("cached_blocks", 0),
                    "cached_bytes": pc.get("cached_bytes", 0),
                    "hit_rate": pc.get("hit_rate", 0.0),
                }
            # windowed telemetry summary riding the stats snapshot
            # (heartbeat-carried for remote workers) — collected here so
            # the fleet view below can answer window queries fleet-wide
            ts = stats.get("timeseries")
            if isinstance(ts, dict) and ts:
                ts_summaries.append(ts)
            sl = stats.get("slice")
            if isinstance(sl, dict) and sl.get("degraded"):
                # positively-declared slice death: out of the pool even
                # while its heartbeats keep arriving
                in_pool = False
                healthy -= 1 if alive and not ejected else 0
            # host-tier occupancy riding the same snapshot: the KV
            # tiering view (/healthz surfaces it fleet-wide)
            kvtier = (stats.get("scheduler") or {}).get("kvtier") \
                if isinstance(stats.get("scheduler"), dict) else None
            eps[name] = {
                "prefix_cache": prefix_cache,
                "kvtier": kvtier if isinstance(kvtier, dict) else None,
                "alive": alive,
                "ejected": ejected,
                "in_pool": in_pool,
                "role": st.role,
                "slice": sl if isinstance(sl, dict) else None,
                "wedged": st.wedged,
                "state": self._endpoint_state(st),
                "consecutive_failures": st.consecutive_failures,
                "ejections": st.ejections,
                "requests": st.requests,
                "failures": st.failures,
                "inflight": st.inflight,
                "ewma_ms": (None if st.ewma_ms is None
                            else round(st.ewma_ms, 3)),
                "model_ewma_ms": {m: round(v, 3)
                                  for m, v in sorted(st.model_ewma_ms.items())},
                "last_seen_age_s": (None if last == float("-inf")
                                    else round(now - last, 3)),
                "stats": stats,
            }
        reg = self._reg()
        lat = reg.get(ROUTER_LATENCY_HISTOGRAM)
        with self._lock:
            active_streams = len(self._streams)
            journal_tokens = sum(len(rf.received) for rf in self._streams)
            hibernated = len(self._hibernated)
        # SLO attribution derived from the request traces: burn
        # outcomes per model, caller-observed TTFT tails, and the
        # per-phase decomposition (what /healthz surfaces so "which
        # phase ate the budget" is one HTTP GET away)
        burn: Dict[str, Dict[str, int]] = {}
        for labels, c in reg.family(REQ_SLO_BURN_COUNTER).items():
            d = dict(labels)
            burn.setdefault(d.get("model", "default"), {})[
                d.get("outcome", "?")] = int(c.value)
        ttft = {}
        for labels, h in reg.family(REQ_TTFT_HISTOGRAM).items():
            if h.count:
                ttft[dict(labels).get("model", "default")] = {
                    "count": int(h.count),
                    "p50_ms": round(h.percentile(0.5), 3),
                    "p99_ms": round(h.percentile(0.99), 3)}
        slo = {
            "burn": burn,
            "burned": sum(v for d in burn.values()
                          for o, v in d.items() if o != "met"),
            "ttft_ms": ttft,
            "phases": phase_breakdown(reg, name=REQ_PHASE_HISTOGRAM),
        }
        return {
            "endpoints": eps,
            "healthy_endpoints": healthy,
            "total_endpoints": len(eps),
            "degraded": healthy < len(eps) or healthy == 0,
            "queue_depth": queue_depth,
            "sessions": len(self._affinity),
            "hibernated_sessions": hibernated,
            "active_streams": active_streams,
            "journal_bytes": 8 * journal_tokens,
            "migrations": int(reg.family_total(SESSION_MIGRATIONS_COUNTER)),
            "resume_prefix_tokens": int(
                reg.family_total(ROUTER_RESUME_PREFIX_COUNTER)),
            "p99_ms": (None if lat is None or lat.count == 0
                       else round(lat.percentile(0.99), 3)),
            "slo": slo,
            # fleet-wide windowed view: per-endpoint summaries merged
            # (counts/rates add, means count-weight, p99 = max — an
            # honest upper bound without shipping raw samples)
            "timeseries": merge_summaries(ts_summaries),
            "shed": int(reg.family_total(ROUTER_SHED_COUNTER)),
            "hedges": int(reg.family_total(ROUTER_HEDGES_COUNTER)),
            "failovers": int(reg.family_total(ROUTER_FAILOVERS_COUNTER)),
            # router event-loop health: lag of the last executed
            # deferred action and the worst seen (ms)
            "loop_lag_ms": {
                "last": round(self._loop_lag_last_ms, 3),
                "max": round(self._loop_lag_max_ms, 3),
            },
        }

    def session_endpoint(self, session: str) -> Optional[str]:
        pin = self._affinity.get(session)
        return pin[0] if pin is not None else None

    def session_pin(self, session: str) -> Optional[Tuple[str, Optional[str]]]:
        """The (endpoint, model) pin of a decode session — the version
        half of the pin lives engine-side on the same session key."""
        return self._affinity.get(session)

    def close(self) -> None:
        self._closed = True
        self._loop.close()
