"""Continuous batching: iteration-level decode scheduling over a paged
KV-cache block pool.

The PR-5 whole-burst path dispatches ALL of ``max_new_tokens`` as one
scan per coalesced (prompt bucket, max_new, sampler) group: a request
arriving one step after dispatch waits out the entire burst, and every
sequence pins a dense ``bucket + max_new`` cache for its lifetime —
the head-of-line and fragmentation problems Orca's iteration-level
scheduling and vLLM's PagedAttention were built to kill. This module
is the fix:

- decode runs in **short fixed-K bursts** (one ``lax.scan`` dispatch
  over ``slots`` batch rows — ``TransformerGenerator.burst_program``);
  between bursts the scheduler **retires** EOS/max-len rows (freeing
  their KV blocks immediately), **admits** queued prefills into the
  vacated batch slots, and goes straight into the next burst, so a new
  request waits at most K tokens, not a whole generation;
- KV state lives in a :class:`~deeplearning4j_tpu.nn.kvpool.
  PagedKVCachePool`: sequences own ordered block tables, grow by one
  block at a time, and free everything the moment they finish — cache
  memory recycles continuously under sustained traffic;
- when the pool is exhausted the scheduler **preempts or sheds**
  deterministically: the victim is the lowest-priority, then
  youngest-admitted active sequence (its blocks are freed and it is
  re-queued AT THE FRONT with its prompt + generated prefix, resuming
  on its own PRNG token clock so the final tokens are identical to an
  uninterrupted run); a sequence that cannot fit even alone fails
  typed with :class:`KVPoolExhausted`, and a full admission queue
  rejects with ``InferenceBackpressure``;
- every device program has a **fixed shape** — prefill is bucketed
  (PR-3 ladder), the burst is (slots × K × max_blocks) no matter which
  sequences occupy the slots, and sampler knobs/PRNG clocks enter as
  traced per-row vectors — so :meth:`warmup` AOT-compiles the whole
  set and steady state pays zero XLA compiles
  (``dl4j_jit_cache_miss_total`` asserts it);
- **lanes**: in registry mode each (model, version) pair schedules its
  own batch slots (a dispatched burst runs one params pytree), but
  lanes whose nets share a KV layout share ONE pool — a sequence's
  blocks and version stay pinned across bursts through a PR-7 canary
  cutover, while stable and canary recycle the same block budget;
- **cross-request prefix cache** (``prefix_cache=True``; off by
  default — it deliberately retains blocks past drain): retiring and
  preempted sequences insert their written token runs into a
  :class:`~deeplearning4j_tpu.serving.prefixcache.PrefixCache` radix
  index per (model, version) lane; an admitted prompt CLONES the block
  table of its longest matched prefix (pool refcounts — the sharer
  frees only its private tail) and prefills ONLY the uncached tail
  through ``tail_prefill_program`` (matched partial tail blocks are
  copy-on-written before the scatter lands), and ``prefix=`` resume
  rows probe the index like any admission, so a migration against a
  warm cache degrades to a table clone plus the journaled suffix.
  Output stays bitwise identical to the uncached run — the cache only
  ever substitutes K/V a prefill of the same tokens at the same
  positions would have written.

``ParallelInference(continuous=True)`` routes ``submit_generate``
through a scheduler; the scheduler is also usable standalone (and
``start=False`` + :meth:`step` gives tests a fully deterministic
single-threaded drive). Transformer (KV-cache) stacks only — the
recurrent path has no paged cache to schedule.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import numpy as np

from deeplearning4j_tpu.monitor import (
    ATTR_DECODE_TOKENS_COUNTER,
    ATTR_PREFILL_TOKENS_COUNTER,
    ATTR_QUEUE_MS_COUNTER,
    KVTIER_HIBERNATED_COUNTER,
    KVTIER_RESTORE_COUNTER,
    SCHED_ACTIVE_GAUGE,
    SCHED_ADMITTED_COUNTER,
    SCHED_BURST_LATENCY_HISTOGRAM,
    SCHED_BURSTS_COUNTER,
    SCHED_PREEMPTIONS_COUNTER,
    SCHED_QUEUED_GAUGE,
    SCHED_RETIRED_COUNTER,
    SPEC_ACCEPT_RATE_GAUGE,
    SPEC_ACCEPTED_TOKENS_COUNTER,
    SPEC_DRAFT_LATENCY_HISTOGRAM,
    SPEC_PROPOSED_TOKENS_COUNTER,
    SPEC_REJECTED_TOKENS_COUNTER,
    STREAM_CHUNKS_COUNTER,
    TS_SCHED_ACTIVE,
    TS_SCHED_POOL_OCCUPANCY,
    TS_SCHED_PREFIX_HIT_RATE,
    TS_SCHED_QUEUED,
    get_registry,
    mark,
    record_fault,
    span,
    timeseries_enabled,
    ts_record,
)
from deeplearning4j_tpu.monitor import reqtrace
from deeplearning4j_tpu.monitor.tracing import to_origin_us
from deeplearning4j_tpu.datasets.iterators import bucket_for, bucket_sizes
from deeplearning4j_tpu.nn.generate import (
    TransformerGenerator,
    build_generator,
    row_keys,
)
from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool, pool_spec
from deeplearning4j_tpu.optimize.deferred import note_dispatch
from deeplearning4j_tpu.parallel.inference import (EngineShutdown,
                                                   InferenceBackpressure)


class DecodeBurstError(RuntimeError):
    """A burst/prefill dispatch died under a sequence: its future
    carries this (typed, with the device error as ``__cause__``), its
    blocks are freed, and the scheduler keeps serving everyone else."""


class KVPoolExhausted(RuntimeError):
    """A sequence needs more KV blocks than the pool can EVER provide
    (even with every other sequence preempted) — a sizing error, not a
    transient: fail fast instead of deadlocking the admission queue."""


def _owner_key(lane_key: Tuple) -> str:
    """Attribution owner tag for a lane: the model name, with the
    version pinned when one is (a canary and its stable version meter
    SEPARATELY — attribution exactness under a cutover is the point).
    Net-mode lanes (no registry) bill ``default``."""
    model, version = lane_key
    base = model if model is not None else "default"
    return base if version is None else f"{base}@v{version}"


class _DecodeRequest:
    """One ``submit()`` — n prompt rows sharing a sampler/seed; the
    Future resolves to [n, t0 + max_new] ids once every row retires.
    ``on_tokens`` (single-row streams only) receives ``(offset,
    tokens)`` deltas as bursts retire; ``prefix`` seeds a RESUME — the
    row re-prefills prompt + prefix and its PRNG clock starts at
    ``len(prefix)``, so the continuation is token-for-token what an
    uninterrupted run would have produced (and offsets continue after
    the prefix, never re-emitting delivered tokens)."""

    __slots__ = ("prompt", "n", "t_in", "max_new", "temperature", "top_k",
                 "top_p", "eos", "seed", "priority", "model", "version",
                 "session", "future", "rows_done", "t_submit", "t_first",
                 "rows", "on_tokens", "prefix", "kv_state", "hibernate",
                 "trace", "root")

    def __init__(self, prompt: np.ndarray, max_new: int, temperature: float,
                 top_k: int, top_p: float, eos: Optional[int], seed: int,
                 priority: int, model, version, session,
                 on_tokens=None, prefix: Optional[np.ndarray] = None,
                 kv_state=None, hibernate: bool = False):
        self.prompt = np.asarray(prompt, np.int64)
        self.n, self.t_in = self.prompt.shape
        self.max_new = int(max_new)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.eos = None if eos is None else int(eos)
        self.seed = int(seed)
        self.priority = int(priority)
        self.model = model
        self.version = version
        self.session = session
        self.on_tokens = on_tokens
        self.prefix = prefix  # [p] int64 generated-so-far (row 0)
        # disaggregated-prefill handoff: {"kv", "logits", "t_in"} from a
        # prefill endpoint's export — admission scatters the shipped KV
        # into pool blocks and samples tok0 off the shipped logits
        # instead of running the prompt forward here
        self.kv_state = kv_state
        # end-of-turn hibernation (host-tier sessions): instead of
        # freeing the finished row's blocks, swap them out and file a
        # durable session record a later turn restores via swap-in
        self.hibernate = bool(hibernate)
        self.future: "Future[np.ndarray]" = Future()
        self.rows_done = 0
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        self.rows: List["_Seq"] = []
        # request-trace context captured at submit (router/worker
        # installs it thread-locally); when absent AND tracing is on,
        # the scheduler self-roots a trace so engine-level callers get
        # the same TTFT decomposition the router-owned path does
        self.trace = reqtrace.current_trace()
        self.root = None


class _Seq:
    """One decode row: the schedulable unit. ``fed`` is what the next
    (re)prefill feeds — original prompt plus everything generated
    before the last preemption; ``generated`` is the full output-so-far
    across preemptions; ``n_gen`` is the row's PRNG token clock (fold
    index of the NEXT sample), which is what makes a resumed sequence's
    draws identical to an uninterrupted run."""

    __slots__ = ("req", "row", "fed", "generated", "key", "n_gen", "slot",
                 "blocks", "draft_blocks", "pos", "seq_id", "preemptions",
                 "emitted", "t_queued", "carry", "host_handles",
                 "host_covered")

    def __init__(self, req: _DecodeRequest, row: int, key: np.ndarray,
                 seq_id: int):
        self.req = req
        self.row = row
        self.t_queued = time.perf_counter()
        self.fed = req.prompt[row].astype(np.int32)
        self.generated: List[int] = []
        self.key = key
        self.n_gen = 0
        self.slot: Optional[int] = None
        self.blocks: List[int] = []
        # the sequence's block table on the DRAFT lane's pool (empty
        # when the scheduler is not speculative)
        self.draft_blocks: List[int] = []
        # speculative pending-carry resume: a preempted spec-mode row's
        # LAST generated token is the pending (KV-unwritten) token; on
        # re-admission it is restored here instead of re-drawing tok0 —
        # the unsalted admission draw would break sampled resume parity
        # (the uninterrupted run draws that clock index on a spec lane)
        self.carry: Optional[int] = None
        # host-tier preempt-swap: a preemption with the host tier on
        # swaps the victim's blocks out instead of freeing them; the
        # handles (and the written-KV token count they cover) ride the
        # queue and the next admission swaps them back in instead of
        # re-prefilling (subject to the per-block crossover)
        self.host_handles: Optional[List[int]] = None
        self.host_covered = 0
        self.pos = 0
        self.seq_id = seq_id
        self.preemptions = 0
        # tokens already delivered through on_tokens — the append-only
        # stream cursor. A resume request starts it at len(prefix):
        # those tokens were delivered by the engine the stream migrated
        # off, so re-emitting them would violate no-repeat.
        self.emitted = 0
        if req.prefix is not None and len(req.prefix):
            pre = np.asarray(req.prefix, np.int32)
            self.fed = np.concatenate([self.fed, pre])
            self.generated = [int(t) for t in pre]
            self.n_gen = len(self.generated)
            self.emitted = self.n_gen

    @property
    def priority(self) -> int:
        return self.req.priority

    @property
    def remaining(self) -> int:
        return self.req.max_new - self.n_gen


class _AdmitPlan:
    """One admission's claimed resources: ``blocks`` in table order
    (shared prefix blocks first — the cloned table — then the COW'd
    partial, then fresh tail blocks), ``start`` cached tokens the tail
    prefill skips, the pending ``cow_src`` shared-partial reference
    (released once the device copy lands), and the group ``sig`` that
    decides which admissions coalesce into one prefill dispatch."""

    __slots__ = ("seq", "blocks", "start", "cow_src", "sig", "restored")

    def __init__(self, seq: _Seq, blocks: List[int], start: int,
                 cow_src: Optional[int], sig: Tuple,
                 restored: bool = False):
        self.seq = seq
        self.blocks = blocks
        self.start = start
        self.cow_src = cow_src
        self.sig = sig
        # host-tier swap-in restore: ``start`` tokens were restored
        # from the host tier (not matched in the prefix cache) — the
        # tail prefill treats both the same, the accounting must not
        self.restored = restored


class _Lane:
    """The per-(model, version) slot batch: one params pytree per
    dispatched burst, host-mirrored slot state vectors, and a shared
    pool reference. Empty slots are ``done`` rows with all-trash block
    tables, so the burst program's shape never changes."""

    def __init__(self, key: Tuple, net, gen: TransformerGenerator,
                 pool: PagedKVCachePool, slots: int):
        self.key = key
        self.net = net
        self.gen = gen
        self.pool = pool
        self.slots = slots
        self.mb = pool.blocks_for(gen.max_context())
        self.seqs: List[Optional[_Seq]] = [None] * slots
        self.tables = np.zeros((slots, self.mb), np.int32)
        self.pos = np.zeros(slots, np.int32)
        self.tok = np.zeros(slots, np.int32)
        self.n_gen = np.zeros(slots, np.int32)
        self.done = np.ones(slots, bool)
        self.keys = np.zeros((slots, 2), np.asarray(row_keys(0, 1)).dtype)
        self.temp = np.zeros(slots, np.float32)
        self.top_k = np.zeros(slots, np.int32)
        self.top_p = np.zeros(slots, np.float32)
        self.eos = np.full(slots, -1, np.int32)
        self.max_new_v = np.zeros(slots, np.int32)
        # speculative draft pairing (attached by the scheduler when
        # speculative=True): the draft net decodes on its OWN pool —
        # separable accounting, so the dual-lane leak audit can name
        # which lane leaked
        self.draft_net = None
        self.draft_gen: Optional[TransformerGenerator] = None
        self.draft_pool: Optional[PagedKVCachePool] = None
        self.draft_mb = 0
        self.draft_tables: Optional[np.ndarray] = None

    def attach_draft(self, net, gen: TransformerGenerator,
                     pool: PagedKVCachePool) -> None:
        self.draft_net = net
        self.draft_gen = gen
        self.draft_pool = pool
        self.draft_mb = pool.blocks_for(gen.max_context())
        self.draft_tables = np.zeros((self.slots, self.draft_mb), np.int32)

    def free_slot(self) -> Optional[int]:
        for i, s in enumerate(self.seqs):
            if s is None:
                return i
        return None

    def active(self) -> List[_Seq]:
        return [s for s in self.seqs if s is not None]

    def clear_slot(self, slot: int) -> None:
        self.seqs[slot] = None
        self.tables[slot] = 0
        if self.draft_tables is not None:
            self.draft_tables[slot] = 0
        self.pos[slot] = 0
        self.tok[slot] = 0
        self.n_gen[slot] = 0
        self.done[slot] = True
        self.keys[slot] = 0
        self.temp[slot] = 0.0
        self.top_k[slot] = 0
        self.top_p[slot] = 0.0
        self.eos[slot] = -1
        self.max_new_v[slot] = 0


class ContinuousDecodeScheduler:
    """Iteration-level decode scheduler over a paged KV block pool.

    Knobs: ``slots`` batch rows per lane (the burst program's row
    count), ``burst_tokens`` = K (a new request waits at most K steps;
    smaller K = lower time-to-first-token, larger K = fewer host
    round-trips), ``block_size`` tokens per KV block, ``num_blocks``
    pool budget (default: enough for every slot at full context — no
    preemption unless oversubscribed), ``queue_capacity`` bounded
    admission (full queue sheds with ``InferenceBackpressure``).

    ``prefix_cache=True`` turns on the cross-request prefix cache
    (``serving/prefixcache.py``): retiring/preempted sequences index
    their KV blocks per (model, version) lane, admissions clone their
    longest matched prefix's table and prefill only the tail, and
    ``prefix_cache_blocks`` optionally caps the cached-block budget
    (pool pressure evicts regardless, deterministically). Off by
    default — the cache retains blocks past drain by design, so the
    drained-pool audit becomes ``free + cached == total`` (use
    :meth:`prefix_caches` + ``clear()`` for the strict check).

    ``start=False`` skips the scheduler thread; tests drive
    :meth:`step` directly for fully deterministic schedules. The
    ``burst_hook(lane_key, burst_index)`` seam lets the faultinject
    harness kill a burst deterministically (the affected sequences
    fail typed :class:`DecodeBurstError`, their blocks are freed, and
    the pool drains back to fully free)."""

    def __init__(self, net=None, registry=None, device=None, slots: int = 8,
                 burst_tokens: int = 8, block_size: int = 16,
                 num_blocks: Optional[int] = None,
                 queue_capacity: int = 256, admit_rows: int = 4,
                 start: bool = True, burst_hook=None, on_resolve=None,
                 prefix_cache: bool = False,
                 prefix_cache_blocks: Optional[int] = None,
                 on_fatal=None, kv_quant: Optional[str] = None,
                 kv_bytes_budget: Optional[int] = None,
                 speculative: bool = False, spec_tokens: int = 4,
                 spec_max_rows: Optional[int] = None, draft_net=None,
                 host_kv_blocks: Optional[int] = None):
        if net is None and registry is None:
            raise ValueError(
                "ContinuousDecodeScheduler needs a net or a registry")
        if net is not None and registry is not None:
            raise ValueError("net= and registry= are exclusive")
        self.net = net
        self._registry = registry
        # committing arrays to the default device is a pure loss (every
        # dispatch then pays placement copies — measured 2.5x on CPU);
        # an explicit device only matters when it is NOT the default
        if device is not None and device == jax.devices()[0]:
            device = None
        self.device = device
        self.slots = max(1, int(slots))
        self.burst_tokens = max(1, int(burst_tokens))
        self.block_size = max(1, int(block_size))
        self._num_blocks = num_blocks
        # quantized KV (nn/quantize.py + the kvpool quant variant):
        # "int8"/"fp8" stores pool values at 1 byte/element with
        # per-(position, head) scales — same block accounting, same
        # program ladder, ~2-4x the decode rows per device byte.
        # kv_bytes_budget sizes num_blocks FROM a device-byte budget
        # (per pool), so "same bytes, more rows" is a config, not math
        # the caller repeats.
        if kv_quant is not None:
            from deeplearning4j_tpu.nn.quantize import quant_modes
            if kv_quant not in quant_modes():
                raise ValueError(
                    f"unknown kv_quant {kv_quant!r}; pick from "
                    f"{quant_modes()}")
        self.kv_quant = kv_quant
        if kv_bytes_budget is not None and num_blocks is not None:
            raise ValueError("kv_bytes_budget= and num_blocks= are "
                             "exclusive — the budget derives num_blocks")
        self._kv_bytes_budget = kv_bytes_budget
        # KV tiering (CachedAttention/InfiniGen discipline): give every
        # pool a host-RAM tier of ``host_kv_blocks`` blocks. Preemption
        # and hibernating end-of-turn retires swap blocks OUT instead of
        # freeing them, resumes swap back IN instead of re-prefilling
        # (per-block H2D-vs-recompute crossover), and pool exhaustion
        # demotes cold prefix-cache blocks to host before dropping any.
        # None/0 = tier off: behavior is bit-for-bit the pre-tier path.
        self._host_kv_blocks = (None if host_kv_blocks is None
                                else max(0, int(host_kv_blocks)))
        # durable hibernated sessions: session -> {handles, covered,
        # tokens, lane, prompt, generated, imported}; host blocks held
        # here intentionally survive drain (like the prefix cache) —
        # release via resume or hibernate_release()
        self._hibernated: Dict[str, Dict[str, Any]] = {}
        self._hibernated_total = 0
        self._preempt_swapouts = 0
        self._swap_restores = 0
        # prefill cost EWMA (ms per computed token) — the recompute
        # side of the swap-in crossover; None until the first prefill
        self._prefill_ms_per_token: Optional[float] = None
        self.queue_capacity = max(1, int(queue_capacity))
        # speculative decoding (Leviathan/Chen 2023): a cheap DRAFT net
        # proposes spec_tokens greedy/sampled tokens per round on its
        # own paged lane, the target verifies all of them in ONE
        # forward, and exact rejection sampling keeps the output
        # distribution identical to plain decode (greedy:
        # token-for-token). draft_net=None self-speculates through
        # quantize(net, "int8") — PR 14's zero-training draft, whose
        # accuracy-gate greedy-match rate is the acceptance prior.
        # spec_max_rows caps the batch width speculation runs at:
        # speculation is a LATENCY tool — past the cap the verify
        # forward's extra K× token compute no longer rides free on an
        # underutilized device, so saturated batches fall back to
        # plain bursts (counted in stats()["speculative"]).
        self.speculative = bool(speculative)
        if draft_net is not None and not self.speculative:
            raise ValueError("draft_net= needs speculative=True")
        if draft_net is not None and net is None:
            raise ValueError(
                "draft_net= is the net-mode pairing knob; registry mode "
                "pairs drafts per version via deploy(draft=...)")
        self.spec_tokens = max(1, int(spec_tokens))
        self.spec_max_rows = (max(1, self.slots // 2)
                              if spec_max_rows is None
                              else max(1, min(int(spec_max_rows),
                                              self.slots)))
        self._draft_net_knob = draft_net
        self._draft_pools: Dict[Tuple, PagedKVCachePool] = {}
        self._draft_params_cache: Dict[Tuple, Any] = {}
        self._spec_rounds = 0
        self._spec_fallbacks = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_rejected = 0
        # same-(lane, bucket) admissions coalesce into one prefill up
        # the row ladder (a spike pays one dispatch chain, not N)
        self.admit_rows = max(1, min(int(admit_rows), self.slots))

        def pow2_ladder(top: int) -> Tuple[int, ...]:
            out, t = [], 1
            while t < top:
                out.append(t)
                t *= 2
            out.append(top)
            return tuple(out)

        self._admit_ladder = pow2_ladder(self.admit_rows)
        # slice fault domain: a ChipFailure surfacing under any dispatch
        # is reported here (the engine poisons the whole slice); the
        # scheduler itself is then poisoned via :meth:`poison`
        self._on_fatal = on_fatal
        self._fatal: Optional[BaseException] = None
        self._kv_handoffs = 0
        # (t0, dt_ms, slot bucket, tier, active rows) of the last
        # accounted burst — consumed by _trace_burst right after
        self._last_burst: Optional[Tuple] = None
        # burst row-bucket ladder: a burst dispatches the smallest slot
        # bucket covering the ACTIVE rows (compacted), so a half-empty
        # batch never pays full-slot compute — same doctrine as the
        # admit and block-tier ladders
        self._slot_ladder = pow2_ladder(self.slots)
        self._burst_hook = burst_hook
        self._on_resolve = on_resolve
        # burst-coalesced emit (wire v4): while a retire pass has the
        # batch open, deltas for callbacks MARKED with a ``burst_sink``
        # attribute accumulate here and flush as ONE call per sink —
        # one frame per endpoint per retiring burst, not one per stream
        self._emit_batch: Optional[List[Tuple[Any, int, np.ndarray]]] = None
        # cross-request prefix caching: one PrefixCache per pool spec,
        # lane-keyed radix roots inside (a canary never matches the
        # stable's cache). Off by default: the cache RETAINS blocks
        # past drain by design, which changes the free==total audit.
        self.prefix_cache = bool(prefix_cache)
        self._prefix_cache_blocks = prefix_cache_blocks
        self._caches: Dict[Tuple, Any] = {}
        self._prefill_computed_tokens = 0
        self._resume_reprefill_tokens = 0
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: Deque[_Seq] = deque()
        self._lanes: Dict[Tuple, _Lane] = {}
        self._pools: Dict[Tuple, PagedKVCachePool] = {}
        self._params_cache: Dict[Tuple, Any] = {}
        self._seq_counter = 0
        # per-owner (model[@vN]) resource attribution: prompt tokens
        # actually computed at prefill, tokens decoded, milliseconds
        # spent queued before admission — the host half of the capacity
        # bill (the KV byte-seconds half lives in each pool)
        self._attr: Dict[str, Dict[str, float]] = {}
        self._attr_metrics: Dict[str, Tuple] = {}
        self._accepted = 0
        self._resolved = 0
        self._admitted_rows = 0
        self._retired_rows = 0
        self._preemptions = 0
        self._bursts = 0
        self._warmed = False
        self._stopping = False
        self._cancel = False
        self._closed = False
        #: bounded audit trail the deterministic tests read — every
        #: admit/retire/preempt/burst-fail event, in schedule order
        self.events: Deque[str] = deque(maxlen=4096)
        #: per-request completion log for the bench: t_submit/t_first/
        #: t_done/rows/tokens of every resolved request
        self.completed: Deque[Dict[str, float]] = deque(maxlen=65536)
        self._thread: Optional[threading.Thread] = None
        if net is not None:
            # net-mode: one lane, built eagerly so submit validates fast
            self._lane_for(None, None)
        if start:
            self.start()

    # ---------------------------------------------------------- public

    def start(self) -> "ContinuousDecodeScheduler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dl4j-tpu-decode-sched")
            self._thread.start()
        return self

    def submit(self, prompt_ids: np.ndarray, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 0.0,
               eos_token: Optional[int] = None, seed: int = 0,
               priority: int = 0, model: Optional[str] = None,
               version: Optional[int] = None,
               session: Optional[str] = None,
               on_tokens=None,
               prefix: Optional[np.ndarray] = None,
               kv_state=None,
               hibernate: bool = False) -> "Future[np.ndarray]":
        """Enqueue one decode request; the Future resolves to the
        [n, t0 + max_new_tokens] ids a solo ``net.generate`` of the
        same rows would return (greedy: token-for-token; sampled: the
        same seeded draws regardless of admission timing, cotenants,
        or preemptions). Higher ``priority`` sequences are preempted
        last.

        ``on_tokens(offset, tokens)`` (single-row requests only) is the
        incremental streaming seam: as bursts retire, the row's new
        tokens are delivered tagged with their sequence offset —
        append-only, no gap, no repeat, across preemptions included.
        ``prefix`` (single-row) makes this a RESUME request: the row
        re-prefills prompt + prefix, its PRNG clock starts at
        ``len(prefix)``, and ``max_new_tokens`` still counts the TOTAL
        generated tokens (prefix included) — the cross-engine migration
        contract: a resumed stream's tokens equal an uninterrupted
        run's, with the delivered prefix never re-emitted."""
        if self._closed:
            # typed (wire-registered): a remote caller racing a drain
            # sees the same class a local one does
            raise EngineShutdown("ContinuousDecodeScheduler is shut down")
        if self._fatal is not None:
            raise self._fatal
        prompt = np.asarray(prompt_ids)
        if prompt.ndim != 2:
            raise ValueError(
                f"prompt_ids must be [n, t0] int tokens, got {prompt.shape}")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new}")
        pre = None
        if prefix is not None:
            pre = np.asarray(prefix, np.int64).reshape(-1)
        if (on_tokens is not None or pre is not None
                or kv_state is not None) and prompt.shape[0] != 1:
            raise ValueError(
                "token streaming / prefix resume / kv handoff are "
                f"per-stream: prompt must be [1, t0], got {prompt.shape}")
        if kv_state is not None and pre is not None:
            raise ValueError(
                "kv_state ships the PROMPT's cache; a resume prefix "
                "re-prefills — the two paths are exclusive")
        if hibernate and session is None:
            raise ValueError(
                "hibernate=True files a durable SESSION record at "
                "end-of-turn — it needs session=")
        if hibernate and prompt.shape[0] != 1:
            raise ValueError(
                "hibernation is per-session: prompt must be [1, t0], "
                f"got {prompt.shape}")
        if pre is not None and len(pre) >= max_new:
            # every token was already generated before the migration —
            # only the terminal frame was lost; synthesize it
            out = np.concatenate(
                [np.asarray(prompt, np.int64), pre[None, :max_new]], axis=1)
            req = _DecodeRequest(prompt, max_new, temperature, top_k, top_p,
                                 eos_token, seed, priority, model, version,
                                 session, on_tokens, pre)
            self._trace_begin(req)
            with self._cv:
                self._accepted += 1
            reqtrace.finish_trace(req.root, outcome="short_circuit",
                                  tokens=max_new)
            req.future.set_result(out)
            self._count_resolved()
            return req.future
        lane = self._lane_for(model, version)
        # validates prompt(+prefix)-length/max_new against the context
        lane.gen.prompt_bucket(
            prompt.shape[1] + (0 if pre is None else len(pre)),
            max(1, max_new - (0 if pre is None else len(pre))))
        req = _DecodeRequest(prompt, max_new, temperature, top_k, top_p,
                             eos_token, seed, priority, model, version,
                             session, on_tokens, pre, kv_state, hibernate)
        self._trace_begin(req)
        keys = np.asarray(row_keys(req.seed, req.n))
        with self._cv:
            if len(self._queue) + req.n > self.queue_capacity:
                raise InferenceBackpressure(
                    f"decode admission queue full "
                    f"({self.queue_capacity} rows)")
            for row in range(req.n):
                self._seq_counter += 1
                seq = _Seq(req, row, keys[row], self._seq_counter)
                req.rows.append(seq)
                self._queue.append(seq)
            self._accepted += 1
            self._cv.notify_all()
        self._gauges()
        return req.future

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            active = sum(len(lane.active()) for lane in self._lanes.values())
            queued = len(self._queue)
            pools = [p.stats() for _, p in sorted(self._pools.items())]
            out = {
                "slots": self.slots,
                "burst_tokens": self.burst_tokens,
                "block_size": self.block_size,
                "kv_quant": self.kv_quant,
                "lanes": len(self._lanes),
                "active_sequences": active,
                "queued_prefills": queued,
                "accepted": self._accepted,
                "resolved": self._resolved,
                "admitted_rows": self._admitted_rows,
                "retired_rows": self._retired_rows,
                "preemptions": self._preemptions,
                "bursts": self._bursts,
                "warmed": self._warmed,
                "prefill_tokens_computed": self._prefill_computed_tokens,
                "resume_reprefill_tokens": self._resume_reprefill_tokens,
                "kv_handoffs": self._kv_handoffs,
                "speculative": {
                    "enabled": self.speculative,
                    "k": self.spec_tokens,
                    "max_rows": self.spec_max_rows,
                    "rounds": self._spec_rounds,
                    "fallbacks": self._spec_fallbacks,
                    "proposed_tokens": self._spec_proposed,
                    "accepted_tokens": self._spec_accepted,
                    "rejected_tokens": self._spec_rejected,
                    "accept_rate": (self._spec_accepted
                                    / max(1, self._spec_proposed)),
                },
            }
            dpools = [p.stats()
                      for _, p in sorted(self._draft_pools.items())]
            caches = [c for _, c in sorted(self._caches.items(),
                                           key=lambda kv: repr(kv[0]))]
        agg = {"blocks_total": sum(p["blocks_total"] for p in pools),
               "blocks_free": sum(p["blocks_free"] for p in pools),
               "shared_blocks": sum(p.get("shared_blocks", 0)
                                    for p in pools),
               "alloc_failures": sum(p["alloc_failures"] for p in pools)}
        agg["occupancy"] = (
            (agg["blocks_total"] - agg["blocks_free"]) / agg["blocks_total"]
            if agg["blocks_total"] else 0.0)
        out["pool"] = agg
        out["pools"] = pools
        out["kvtier"] = {
            "enabled": bool(self._host_kv_blocks),
            "host_blocks_used": sum(p.get("host_blocks_used", 0)
                                    for p in pools),
            "host_budget": sum(p.get("host_budget", 0) for p in pools),
            "hibernated_sessions": len(self._hibernated),
            "hibernated_total": self._hibernated_total,
            "preempt_swapouts": self._preempt_swapouts,
            "swap_restores": self._swap_restores,
        }
        if dpools:
            # the draft lane's pools stay OUT of the main aggregate so
            # a dual-lane leak audit can name which lane leaked
            out["draft_pools"] = dpools
            out["draft_pool"] = {
                "blocks_total": sum(p["blocks_total"] for p in dpools),
                "blocks_free": sum(p["blocks_free"] for p in dpools),
            }
        out["attribution"] = self.attribution()
        if self.prefix_cache:
            cs = [c.stats() for c in caches]
            hits = sum(c["hits"] for c in cs)
            misses = sum(c["misses"] for c in cs)
            out["prefix_cache"] = {
                "cached_blocks": sum(c["cached_blocks"] for c in cs),
                "cached_bytes": sum(c["cached_bytes"] for c in cs),
                "shared_blocks": sum(c["shared_blocks"] for c in cs),
                "hits": hits, "misses": misses,
                "hit_rate": hits / (hits + misses) if hits + misses
                else 0.0,
                "evictions": sum(c["evictions"] for c in cs),
                "cow_copies": sum(c["cow_copies"] for c in cs),
                "saved_prefill_tokens": sum(c["saved_prefill_tokens"]
                                            for c in cs),
            }
        return out

    def drain(self, timeout: Optional[float] = None,
              poll_s: float = 2e-3) -> bool:
        """Block until every accepted request has resolved (the
        zero-leaked-blocks assertion point: a drained scheduler's pools
        are fully free). False when ``timeout`` elapses first."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                idle = self._resolved >= self._accepted
            if idle:
                return True
            if deadline is not None and time.monotonic() >= deadline:
                return False
            if self._thread is None:
                self.step()  # manual mode: drive the schedule ourselves
            else:
                time.sleep(poll_s)

    def shutdown(self, drain: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Stop accepting work; drain (default) or fail what is queued
        and in flight, then join the scheduler thread."""
        if self._closed:
            return
        self._closed = True
        if drain and self._thread is not None:
            self.drain(timeout)
        with self._cv:
            self._stopping = True
            self._cancel = not drain
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        else:
            if drain:
                self.drain(timeout)
            else:
                self._fail_everything(
                    EngineShutdown("scheduler shut down before dispatch"))
        # hibernated sessions' host-tier entries die with the scheduler
        # (a durable handle outlives the ENDPOINT only when the worker
        # shipped it — the router's copy, not this one)
        with self._lock:
            recs = list(self._hibernated.values())
            self._hibernated.clear()
        for rec in recs:
            self._lane_for(*rec["lane"]).pool.free_host(
                rec["handles"], owner=_owner_key(rec["lane"]))

    def warmup(self, prompt_lengths, max_new_tokens: int = 1,
               model: Optional[str] = None,
               version: Optional[int] = None,
               tail_lengths=None) -> int:
        """AOT-compile the continuous-decode program set for one lane:
        the rowwise sampler, every covering prompt bucket's prefill +
        pool scatter, and THE burst program (its (slots × K ×
        max_blocks) shape is sequence-independent, so one compile
        covers every admission mix — the structural reason steady
        state is compile-free). Warm dispatches run all-masked: writes
        land in the trash block, pool accounting is untouched. Returns
        the fresh-program count."""
        from deeplearning4j_tpu.monitor import JIT_CACHE_MISS_COUNTER
        lane = self._lane_for(model, version)
        pool = lane.pool
        reg = get_registry()
        before = reg.family_total(JIT_CACHE_MISS_COUNTER)
        params = self._params(lane)
        gen = lane.gen
        with span("stage", path="warmup_continuous", slots=self.slots,
                  burst=self.burst_tokens):
            # rowwise sampler (admission tok0 program) over the REAL
            # vocab width and every admit-ladder row count — the
            # programs are shape-keyed
            rs = gen.row_sample_program()
            vocab = int(gen.emb.conf.n_in)
            for rows in self._admit_ladder:
                note_dispatch(lane.net, ("gen_row_sample", "sched", rows))
                np.asarray(rs(np.zeros((rows, vocab), np.float32),
                              np.zeros((rows, 2), lane.keys.dtype),
                              np.zeros(rows, np.int32),
                              np.zeros(rows, np.float32),
                              np.zeros(rows, np.int32),
                              np.zeros(rows, np.float32)))
            done_buckets = set()
            for t_in in prompt_lengths:
                t_pad = gen.prompt_bucket(int(t_in), int(max_new_tokens))
                t_blk = self._round_blocks(t_pad)
                # the prefill program is shaped by the prompt bucket,
                # its block-rounded cache length AND the admit-ladder
                # row count
                if (t_pad, t_blk) in done_buckets:
                    continue
                done_buckets.add((t_pad, t_blk))
                for rows in self._admit_ladder:
                    ids = np.zeros((rows, t_pad), np.int32)
                    lens = np.full(rows, min(int(t_in), t_pad), np.int32)
                    pre = gen.prefill_program(t_blk)
                    fresh = note_dispatch(
                        lane.net,
                        ("gen_prefill", "sched", rows, t_pad, t_blk))
                    with span("compile" if fresh else "inference",
                              path="warmup_continuous_prefill",
                              bucket=t_pad, rows=rows):
                        caches, logits = pre(params, ids, lens)
                        jax.block_until_ready(logits)
                    scat = gen.scatter_program(rows, t_blk,
                                               self.block_size)
                    tnb = np.zeros((rows, t_blk // self.block_size),
                                   np.int32)
                    note_dispatch(lane.net,
                                  ("gen_pool_scatter", "sched", rows,
                                   t_blk))
                    pool.set_layers(scat(pool.layers, caches, tnb))
            # the full burst-program ladder: every (slot bucket ×
            # block tier), greedy AND sampling variants (all slots
            # empty: masked writes land in the trash block only)
            for tier in self._burst_tiers(lane):
                for rows in self._slot_ladder:
                    for sampling in (False, True):
                        self._dispatch_burst(lane, params, tier=tier,
                                             sampling=sampling, rows=rows)
            if self.speculative and lane.draft_gen is not None:
                # the speculative program set: the draft lane's dense
                # prefill + scatter ladder (admissions write the prompt
                # into the draft pool too — the draft net's own _jits
                # cache, so its programs compile separately), then the
                # spec draft/verify rounds over (spec row bucket ×
                # block tier). ONE program per shape — the rejection
                # sampler handles any greedy/sampled row mix with
                # per-row where()s, and accept length never shapes a
                # program (host truncation), so the accept "ladder"
                # warms for free.
                dgen, dpool = lane.draft_gen, lane.draft_pool
                dparams = self._draft_params(lane)
                k = self.spec_tokens
                # catch-up prefills (_draft_catchup) replay a row's
                # WRITTEN history, whose length can reach prompt +
                # max_new — warm every DRAFT-ladder bucket from the
                # smallest admitted prompt's bucket up to that horizon,
                # not just the admission buckets, or the first
                # post-saturation re-arm compiles mid-stream
                d_sizes = bucket_sizes(dgen.max_context())
                lo = min(bucket_for(int(t), d_sizes)
                         for t in prompt_lengths)
                hi = bucket_for(
                    min(int(dgen.max_context()),
                        max(int(t) for t in prompt_lengths)
                        + int(max_new_tokens)), d_sizes)
                spec_pre = sorted(
                    {(t, self._round_blocks(t))
                     for t in d_sizes if lo <= t <= hi}
                    | set(done_buckets))
                for (t_pad, t_blk) in spec_pre:
                    for rows in self._admit_ladder:
                        prd = dgen.prefill_program(t_blk)
                        fresh = note_dispatch(
                            lane.draft_net,
                            ("gen_prefill", "sched", rows, t_pad, t_blk))
                        with span("compile" if fresh else "inference",
                                  path="warmup_spec_draft_prefill",
                                  bucket=t_pad, rows=rows):
                            caches, logits = prd(
                                dparams,
                                np.zeros((rows, t_pad), np.int32),
                                np.ones(rows, np.int32))
                            jax.block_until_ready(logits)
                        scat = dgen.scatter_program(rows, t_blk,
                                                    self.block_size)
                        note_dispatch(
                            lane.draft_net,
                            ("gen_pool_scatter", "sched", rows, t_blk))
                        dpool.set_layers(scat(
                            dpool.layers, caches,
                            np.zeros((rows, t_blk // self.block_size),
                                     np.int32)))
                vocab = int(gen.emb.conf.n_in)
                for rows in self._spec_rows_ladder():
                    z_pos = np.zeros(rows, np.int32)
                    z_tok = np.zeros(rows, np.int32)
                    z_ng = np.zeros(rows, np.int32)
                    z_keys = np.zeros((rows, 2), lane.keys.dtype)
                    z_t = np.zeros(rows, np.float32)
                    z_k = np.zeros(rows, np.int32)
                    z_p = np.zeros(rows, np.float32)
                    z_live = np.zeros(rows, bool)
                    for dtier in self._draft_tiers(lane):
                        dp = dgen.spec_draft_program(
                            rows, k, dtier, dpool.num_blocks,
                            self.block_size)
                        note_dispatch(
                            lane.draft_net,
                            ("gen_spec_draft", "sched", rows, k, dtier))
                        dpools, props, q = dp(
                            dparams, dpool.layers,
                            np.zeros((rows, dtier), np.int32), z_pos,
                            z_tok, z_ng, z_keys, z_t, z_k, z_p, z_live)
                        dpool.set_layers(dpools)
                        jax.block_until_ready(props)
                    zp = np.zeros((rows, k), np.int32)
                    zq = np.zeros((rows, k, vocab), np.float32)
                    for tier in self._burst_tiers(lane):
                        vp = gen.spec_verify_program(
                            rows, k, tier, pool.num_blocks,
                            self.block_size)
                        note_dispatch(
                            lane.net,
                            ("gen_spec_verify", "sched", rows, k, tier))
                        pools_o, out, acc = vp(
                            params, pool.layers,
                            np.zeros((rows, tier), np.int32), z_pos,
                            z_tok, zp, zq, z_ng, z_keys, z_t, z_k, z_p,
                            z_live)
                        pool.set_layers(pools_o)
                        jax.block_until_ready(acc)
            if self.prefix_cache:
                # cache-hit admissions dispatch the COW block copy and
                # the tail-prefill ladder: every (admit rows × tail
                # bucket × block tier) shape a warmed prompt mix can
                # produce. All-trash tables: accounting untouched.
                # ``tail_lengths`` narrows the ladder to the tails the
                # caller's workload actually yields (a bench's shared
                # preamble pins the match length, so the full product
                # would mostly warm programs that never dispatch).
                cp = lane.gen.block_copy_program(1, pool.num_blocks,
                                                 self.block_size)
                note_dispatch(lane.net, ("gen_block_copy", "sched", 1))
                pool.set_layers(cp(pool.layers, np.zeros(1, np.int32),
                                   np.zeros(1, np.int32)))
                sizes = bucket_sizes(gen.max_context())
                if tail_lengths is None:
                    max_tail = max(int(t) for t in prompt_lengths)
                    tail_buckets = sorted({
                        bucket_for(t, sizes)
                        for t in range(1, max_tail + 1)})
                    tiers = list(self._burst_tiers(lane))
                else:
                    tail_buckets = sorted({
                        bucket_for(int(t), sizes) for t in tail_lengths})
                    tiers = sorted({
                        self._tier_cover(lane, pool.blocks_for(int(t)))
                        for t in prompt_lengths})
                for t_tail in tail_buckets:
                    for tier in tiers:
                        for rows in self._admit_ladder:
                            tp = gen.tail_prefill_program(
                                rows, t_tail, tier, pool.num_blocks,
                                self.block_size)
                            note_dispatch(
                                lane.net, ("gen_tail_prefill", "sched",
                                           rows, t_tail, tier))
                            pool.set_layers(tp(
                                params, pool.layers,
                                np.zeros((rows, t_tail), np.int32),
                                np.zeros(rows, np.int32),
                                np.full(rows, t_tail, np.int32),
                                np.zeros((rows, tier), np.int32))[0])
            if pool.host_enabled:
                # swap gather/scatter run on the trash block — the
                # steady-state ladder includes the tiering programs
                pool.warm_swap_programs()
                if lane.draft_pool is not None \
                        and lane.draft_pool.host_enabled:
                    lane.draft_pool.warm_swap_programs()
        self._warmed = True
        return int(reg.family_total(JIT_CACHE_MISS_COUNTER) - before)

    def step(self) -> bool:
        """One scheduling iteration: admit queued prefills into free
        slots, top up every active sequence's block horizon (preempting
        deterministically when the pool is exhausted), dispatch one
        fixed-K burst per lane with active rows, and retire finished
        rows (blocks freed immediately). Returns whether any work
        happened — the thread loop's park signal, and the manual-drive
        entry point for deterministic tests."""
        progressed = self._admit()
        for key in sorted(self._lanes, key=repr):
            lane = self._lanes[key]
            if not lane.active():
                continue
            self._draft_catchup(lane)
            self._ensure_blocks(lane)
            if not lane.active():
                continue
            try:
                params = self._params(lane)
                if self._spec_eligible(lane):
                    outs = self._dispatch_spec_round(lane, params)
                else:
                    if self.speculative and lane.draft_gen is not None:
                        with self._lock:
                            self._spec_fallbacks += 1
                    outs = self._dispatch_burst(lane, params,
                                                accounted=True)
            except BaseException as e:
                self._burst_failed(lane, e)
                progressed = True
                continue
            self._trace_burst(lane)
            self._retire(lane, outs)
            progressed = True
        self._gauges()
        return progressed

    # ------------------------------------------------------ lanes/pools

    def _lane_for(self, model: Optional[str],
                  version: Optional[int]) -> _Lane:
        key = (model, version)
        with self._lock:
            lane = self._lanes.get(key)
        if lane is not None:
            return lane
        if model is None:
            net = self.net
        else:
            if self._registry is None:
                raise ValueError("model= needs a registry-mode scheduler")
            net = self._registry.version(model, version).net()
        gen = build_generator(net)
        if not isinstance(gen, TransformerGenerator):
            raise ValueError(
                "continuous batching schedules paged KV caches; "
                f"{type(gen).__name__} nets have none — serve them through "
                "the whole-burst submit_generate path")
        n_layers, heads, hd, dtype = gen.kv_layout()
        spec = pool_spec(n_layers, heads, hd, self.block_size, dtype,
                         self.kv_quant)
        # sliced net: the pool's block arrays shard their HEADS axis
        # over the slice's tp axis (per-head attention is
        # shard-independent — accounting and arithmetic unchanged)
        kv_sharding = gen.kv_sharding()
        with self._lock:
            pool = self._pools.get(spec)
            if pool is None:
                blocks = self._num_blocks
                if blocks is None and self._kv_bytes_budget is not None:
                    # byte-budget sizing: a quantized pool's smaller
                    # block_bytes buys MORE blocks from the same budget
                    # — the "same bytes, 2-4x the rows" knob
                    bb = PagedKVCachePool.bytes_per_block(
                        n_layers, self.block_size, heads, hd, dtype,
                        self.kv_quant)
                    blocks = max(2, int(self._kv_bytes_budget) // bb + 1)
                if blocks is None:
                    # default: every slot can reach full context — the
                    # no-preemption budget; size DOWN to exercise
                    # preemption/shedding
                    mb = -(-gen.max_context() // self.block_size)
                    blocks = self.slots * mb + 1
                pool = PagedKVCachePool(
                    int(blocks), self.block_size, n_layers, heads, hd,
                    dtype, device=None if kv_sharding is not None
                    else self.device,
                    sharding=kv_sharding,
                    name=model if model is not None else "decode",
                    quant=self.kv_quant,
                    host_blocks=self._host_kv_blocks)
                self._pools[spec] = pool
                if self.prefix_cache:
                    from deeplearning4j_tpu.serving.prefixcache import \
                        PrefixCache
                    self._caches[spec] = PrefixCache(
                        pool, capacity_blocks=self._prefix_cache_blocks)
            lane = self._lanes.get(key)
            if lane is None:
                lane = _Lane(key, net, gen, pool, self.slots)
                self._lanes[key] = lane
        if self.speculative and lane.draft_gen is None:
            self._attach_draft(lane)
        return lane

    def _attach_draft(self, lane: _Lane) -> None:
        """Resolve and attach the lane's draft net + its dedicated
        pool. Resolution order: the version record's deploy(draft=...)
        pairing (registry mode) / the draft_net= knob (net mode), else
        self-speculation via ``quantize(net, "int8")`` — the PR-14
        zero-training draft. The draft decodes on its OWN pool so the
        dual-lane leak audit stays separable; lanes whose drafts share
        a KV layout share one draft pool, and a stream's lane (hence
        its draft) is pinned for its lifetime — a canary cutover never
        switches a running stream's draft."""
        model, version = lane.key
        dn = None
        if model is not None:
            ver = self._registry.version(model, version)
            dn = ver.draft() if hasattr(ver, "draft") else None
        elif self._draft_net_knob is not None:
            dn = self._draft_net_knob
        if dn is None:
            from deeplearning4j_tpu.nn.quantize import quantize
            dn = quantize(lane.net, "int8")
        dgen = build_generator(dn)
        if not isinstance(dgen, TransformerGenerator):
            raise ValueError(
                "speculative decoding drafts on a paged KV cache; "
                f"{type(dgen).__name__} draft nets have none")
        n_layers, heads, hd, dtype = dgen.kv_layout()
        spec = pool_spec(n_layers, heads, hd, self.block_size, dtype,
                         self.kv_quant)
        kv_sharding = dgen.kv_sharding()
        with self._lock:
            dpool = self._draft_pools.get(spec)
            if dpool is None:
                mb = -(-dgen.max_context() // self.block_size)
                dpool = PagedKVCachePool(
                    self.slots * mb + 1, self.block_size, n_layers,
                    heads, hd, dtype,
                    device=None if kv_sharding is not None
                    else self.device,
                    sharding=kv_sharding,
                    name=(f"{model if model is not None else 'decode'}"
                          ":draft"),
                    quant=self.kv_quant)
                self._draft_pools[spec] = dpool
        lane.attach_draft(dn, dgen, dpool)

    def _cache_of(self, lane: _Lane):
        """The lane's PrefixCache (None when prefix caching is off)."""
        if not self.prefix_cache:
            return None
        return self._caches.get(lane.pool.spec)

    def prefix_caches(self):
        """Every live PrefixCache (spec-sorted) — drain-time audits
        ``clear()`` them to prove free==total with zero double-frees."""
        with self._lock:
            return [c for _, c in sorted(self._caches.items(),
                                         key=lambda kv: repr(kv[0]))]

    def _params(self, lane: _Lane):
        model, version = lane.key
        if model is not None:
            return self._registry.acquire(model, version, self.device)[1]
        cached = self._params_cache.get(lane.key)
        if cached is None:
            p = lane.net.params
            if self.device is not None:
                p = jax.device_put(p, self.device)
            cached = self._params_cache[lane.key] = p
        return cached

    def _draft_params(self, lane: _Lane):
        cached = self._draft_params_cache.get(lane.key)
        if cached is None:
            p = lane.draft_net.params
            if self.device is not None:
                p = jax.device_put(p, self.device)
            cached = self._draft_params_cache[lane.key] = p
        return cached

    def _round_blocks(self, tokens: int) -> int:
        bs = self.block_size
        return -(-int(tokens) // bs) * bs

    # -------------------------------------------------------- admission

    def _admit(self) -> bool:
        """Admit queued sequences FIFO (preempted resumes ride at the
        front): same-signature neighbors coalesce into ONE row-bucketed
        prefill + pool scatter (padding rows carry length 0 and
        all-trash tables), so a traffic spike pays one dispatch chain,
        not one per sequence. With the prefix cache on, an admission's
        signature also carries its matched-prefix shape: cache hits
        clone their matched block tables and batch through the TAIL
        prefill instead. A sequence whose lane has no free slot or
        whose blocks do not fit is skipped this round — running
        sequences retiring is what unblocks it; admission never
        preempts."""
        admitted = False
        while True:
            group = self._pick_admissions()
            if not group:
                return admitted
            lane, kind, entries = group
            try:
                if kind[0] == "tail":
                    self._prefill_tail_batch(lane, kind[1], kind[2],
                                             entries)
                elif kind[0] == "ship":
                    self._prefill_shipped_batch(lane, kind[1], entries)
                else:
                    self._prefill_batch(lane, kind[1],
                                        [(p.seq, p.blocks)
                                         for p in entries])
            except BaseException as e:
                record_fault("serving")
                for p in entries:
                    self._rollback_plan(lane, p)
                    self._fail_seq(p.seq, self._typed(e, p.seq))
                self._note_fatal(e)
                continue
            admitted = True

    def _lane_key(self, seq: _Seq) -> Tuple:
        return (seq.req.model, seq.req.version)

    def _tier_cover(self, lane: _Lane, need: int) -> int:
        for t in self._burst_tiers(lane):
            if need <= t:
                return t
        return lane.mb

    def _plan_blocks(self, lane: _Lane, seq: _Seq,
                     allow_restore: bool = True):
        """Probe the host tier and the prefix cache and claim every
        block this admission needs. Returns an ``_AdmitPlan`` (blocks
        in table order, matched ``start``, the pending COW source ref,
        and the group signature), or None when the pool cannot cover
        it right now (everything claimed was released — blocks return
        as running rows retire). ``allow_restore=False`` (non-anchor
        group riders) defers host-tier restores to a round where the
        sequence anchors — a restore consumed into a plan must never
        be rolled back by a mere signature mismatch."""
        pool = lane.pool
        owner = _owner_key(lane.key)
        t_full = len(seq.fed)
        need_total = pool.blocks_for(t_full)
        if seq.req.kv_state is not None and seq.n_gen == 0:
            # disaggregated handoff: the prompt's KV arrives shipped —
            # claim the blocks, no prefill forward, no cache probe (a
            # preempted handoff row falls back to a plain re-prefill)
            got = pool.alloc(need_total, owner=owner)
            if got is None:
                return None
            t_pad = lane.gen.prompt_bucket(t_full, max(1, seq.remaining))
            return _AdmitPlan(seq, got, 0, None,
                              ("ship", self._round_blocks(t_pad)))
        if not allow_restore and self._has_host_state(seq):
            return None
        if allow_restore:
            restored, plan = self._plan_host_restore(
                lane, seq, owner, t_full, need_total)
            if restored:
                return plan
        cache = self._cache_of(lane)
        m, shared, partial = (0, [], None)
        if cache is not None:
            m, shared, partial = cache.match(lane.key, seq.fed)
        if m <= 0:
            got = pool.alloc(need_total, owner=owner)
            if got is None:
                return None
            t_pad = lane.gen.prompt_bucket(t_full, max(1, seq.remaining))
            return _AdmitPlan(seq, got, 0, None, ("dense", t_pad))
        t_tail = t_full - m
        have = len(shared) + (1 if partial is not None else 0)
        fresh_need = (need_total - have) + (1 if partial is not None else 0)
        got = pool.alloc(fresh_need, owner=owner)
        if got is None:
            pool.free_blocks(shared
                             + ([partial] if partial is not None else []))
            return None
        if partial is not None:
            # the matched partial tail block will be WRITTEN (the tail
            # starts inside it): copy-on-write — a fresh block takes
            # its place in the table, the device copy lands before the
            # tail scatter, and the shared ref releases after the copy
            blocks = shared + [got[0]] + got[1:]
        else:
            blocks = shared + got
        t_tail_pad = bucket_for(t_tail,
                                bucket_sizes(lane.gen.max_context()))
        tier = self._tier_cover(lane, len(blocks))
        return _AdmitPlan(seq, blocks, m, partial,
                          ("tail", t_tail_pad, tier))

    # ------------------------------------------------- host-tier restore

    def _has_host_state(self, seq: _Seq) -> bool:
        """Whether this sequence's admission could restore from the
        host tier (preempt-swap handles on the seq, or a hibernated
        record for its session)."""
        if seq.host_handles:
            return True
        if seq.req.session is None:
            return False
        with self._lock:
            return seq.req.session in self._hibernated

    def _restore_cut(self, pool: PagedKVCachePool, handles: List[int],
                     covered: int) -> int:
        """The per-block H2D-vs-recompute crossover: walk the restored
        prefix from its END and drop each block whose measured swap-in
        cost exceeds recomputing its tokens at the measured prefill
        rate (a partial tail block holds fewer tokens, so it loses
        first). Restores are prefixes — dropping block i drops
        everything after it too. Unmeasured on either side = swap
        everything (the first restores are what produce the
        measurements)."""
        swap_ms = pool.swap_in_cost_ms()
        per_tok = self._prefill_ms_per_token
        keep = len(handles)
        if not swap_ms or not per_tok:
            return keep
        bs = pool.block_size
        while keep > 0:
            toks = min(covered - (keep - 1) * bs, bs)
            if toks > 0 and swap_ms <= toks * per_tok:
                break
            keep -= 1
        return keep

    def _plan_host_restore(self, lane: _Lane, seq: _Seq, owner: str,
                           t_full: int, need_total: int):
        """Try to source this admission's KV prefix from the HOST
        tier: a preempt-swapped row carries its handles on the
        sequence; a hibernated-session resume matches its durable
        record by exact token prefix. Returns ``(handled, plan)``:
        (False, None) = not a host restore — fall through to the
        cache probe; (True, None) = restore pending but the pool
        cannot cover it right now (handles kept — retry as rows
        retire); (True, plan) = blocks claimed, prefix restored."""
        pool = lane.pool
        handles, covered, rec = seq.host_handles, seq.host_covered, None
        if not handles and seq.req.session is not None:
            with self._lock:
                rec = self._hibernated.get(seq.req.session)
            if rec is not None:
                cov = int(rec["covered"])
                if (rec["lane"] != lane.key or cov >= t_full
                        or not np.array_equal(
                            np.asarray(rec["tokens"], np.int64),
                            np.asarray(seq.fed[:cov], np.int64))):
                    # stale record: the resumed turn does not extend
                    # the hibernated run — release it and re-prefill
                    self._hibernate_drop(seq.req.session)
                    rec = None
                else:
                    handles, covered = list(rec["handles"]), cov
        if not handles:
            return False, None
        keep = self._restore_cut(pool, handles, covered)
        drop = handles[keep:]
        if keep < len(handles):
            handles = handles[:keep]
            covered = min(covered, keep * pool.block_size)
            if rec is None:
                # seq-owned handles: the crossover's verdict is final —
                # release the dropped tail now (the tail prefill
                # recomputes those tokens whether or not this plan
                # lands this round)
                pool.free_host(drop, owner=owner)
                seq.host_handles = handles if handles else None
                seq.host_covered = covered
                drop = []
        if keep == 0:
            # recompute beats swapping for every block — abandon the
            # restore entirely and admit through the normal paths
            if rec is not None:
                self._hibernate_drop(seq.req.session)
            return False, None
        fresh_need = need_total - len(handles)
        got = pool.alloc(fresh_need, owner=owner) if fresh_need > 0 else []
        if got is None:
            return True, None
        dev = pool.swap_in(handles, owner=owner)
        if dev is None:
            if got:
                pool.free_blocks(got, owner=owner)
            return True, None
        if rec is not None:
            with self._lock:
                self._hibernated.pop(seq.req.session, None)
            if drop:
                pool.free_host(drop, owner=owner)
        seq.host_handles, seq.host_covered = None, 0
        with self._lock:
            self._swap_restores += 1
        path = "ship" if (rec is not None and rec.get("imported")) \
            else "host"
        get_registry().counter(
            KVTIER_RESTORE_COUNTER,
            "Sessions/rows restored from the KV tier, by restore-"
            "ladder rung (host swap-in / cross-endpoint shipped / "
            "journal re-prefill)", path=path).inc()
        self.events.append(
            f"swap_in seq={seq.seq_id} blocks={len(dev)} "
            f"covered={covered} fresh={len(got)}")
        blocks = dev + got
        t_tail_pad = bucket_for(t_full - covered,
                                bucket_sizes(lane.gen.max_context()))
        tier = self._tier_cover(lane, len(blocks))
        return True, _AdmitPlan(seq, blocks, covered, None,
                                ("tail", t_tail_pad, tier),
                                restored=True)

    def _free_host_of(self, seq: _Seq) -> None:
        """Release a dropped sequence's preempt-swap host handles
        (every path that removes a queued sequence without admitting
        it must come through here, or the host tier leaks)."""
        if not seq.host_handles:
            return
        lane = self._lane_for(*self._lane_key(seq))
        lane.pool.free_host(seq.host_handles, owner=_owner_key(lane.key))
        seq.host_handles = None
        seq.host_covered = 0

    def _note_prefill_cost(self, tokens: int, dt_s: float) -> None:
        """Feed the prefill-cost EWMA (ms per computed token) — the
        recompute side of the swap-in crossover."""
        if tokens <= 0 or dt_s <= 0:
            return
        ms = dt_s * 1e3 / tokens
        with self._lock:
            cur = self._prefill_ms_per_token
            self._prefill_ms_per_token = (
                ms if cur is None else 0.8 * cur + 0.2 * ms)

    def _rollback_plan(self, lane: _Lane, plan: "_AdmitPlan") -> None:
        owner = _owner_key(lane.key)
        lane.pool.free_blocks(plan.blocks, owner=owner)
        if plan.cow_src is not None:
            lane.pool.free_blocks([plan.cow_src], owner=owner)
            plan.cow_src = None
        plan.seq.blocks = []
        self._free_draft_blocks(lane, plan.seq)

    def _free_draft_blocks(self, lane: _Lane, seq: _Seq) -> None:
        """Return a sequence's DRAFT-lane blocks (no-op when the lane
        is not speculative) — called everywhere the target blocks free
        so the dual-pool drain audit holds on both lanes."""
        if seq.draft_blocks and lane.draft_pool is not None:
            lane.draft_pool.free_blocks(seq.draft_blocks,
                                        owner=_owner_key(lane.key))
        seq.draft_blocks = []

    def _pick_admissions(self):
        """Claim the next admissible FIFO group: the first sequence
        with a free slot + allocable blocks anchors the (lane,
        signature); later queue entries with the same signature ride
        the same prefill while slots, blocks and the admit ladder
        allow (a signature mismatch is rolled back and left queued —
        it anchors a later round). Each picked sequence's blocks are
        claimed HERE (rolled back by the caller on prefill failure)."""
        with self._lock:
            pending = list(self._queue)
        anchor = None
        entries: List[_AdmitPlan] = []
        free_slots = 0
        for seq in pending:
            if seq.req.future.done():
                with self._lock:
                    self._queue.remove(seq)
                self._free_host_of(seq)
                continue
            lane = self._lane_for(*self._lane_key(seq))
            t_full = len(seq.fed)
            need = lane.pool.blocks_for(t_full)
            if anchor is None:
                if lane.free_slot() is None:
                    continue
                if need > lane.pool.total_blocks or need > lane.mb:
                    with self._lock:
                        self._queue.remove(seq)
                    self._fail_seq(seq, KVPoolExhausted(
                        f"sequence needs {need} KV blocks; pool holds "
                        f"{lane.pool.total_blocks} (max {lane.mb}"
                        f"/sequence)"))
                    continue
                # validates prompt-length/max_new against the context
                lane.gen.prompt_bucket(t_full, max(1, seq.remaining))
                plan = self._plan_blocks(lane, seq)
                if plan is None:
                    continue  # blocks return as running rows retire
                anchor = (lane, plan.sig)
                free_slots = sum(1 for s in lane.seqs if s is None)
            else:
                if lane is not anchor[0]:
                    continue
                if len(entries) >= min(free_slots, self._admit_ladder[-1]):
                    break
                if need > lane.pool.total_blocks or need > lane.mb:
                    continue  # it fails typed when it anchors
                plan = self._plan_blocks(lane, seq, allow_restore=False)
                if plan is None:
                    break
                if plan.sig != anchor[1]:
                    # different program shape: not this group's ride —
                    # release its claim, leave it queued to anchor later
                    self._rollback_plan(lane, plan)
                    continue
            with self._lock:
                self._queue.remove(seq)
            entries.append(plan)
            if anchor is not None and \
                    len(entries) >= min(free_slots, self._admit_ladder[-1]):
                break
        if not entries:
            return None
        return anchor[0], anchor[1], entries

    def _prefill_batch(self, lane: _Lane, t_pad: int,
                       entries: List[Tuple[_Seq, List[int]]]) -> None:
        """One row-bucketed prefill of a same-bucket admission group →
        page every row's dense cache into its blocks (ONE scatter) →
        sample each row's next token on its own PRNG clock → install
        into batch slots (rows whose first token already finishes them
        retire immediately, never occupying a slot)."""
        gen, pool = lane.gen, lane.pool
        n = len(entries)
        rows = bucket_for(n, self._admit_ladder)
        t_blk = self._round_blocks(t_pad)
        nb = t_blk // self.block_size
        ids = np.zeros((rows, t_pad), np.int32)
        lens = np.zeros(rows, np.int32)
        tnb = np.zeros((rows, nb), np.int32)
        keys = np.zeros((rows, 2), lane.keys.dtype)
        folds = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.zeros(rows, np.float32)
        for i, (seq, blocks) in enumerate(entries):
            t_full = len(seq.fed)
            ids[i, :t_full] = seq.fed
            lens[i] = t_full
            tnb[i, :len(blocks)] = blocks
            keys[i] = seq.key
            folds[i] = seq.n_gen
            temp[i] = seq.req.temperature
            top_k[i] = seq.req.top_k
            top_p[i] = seq.req.top_p
        params = self._params(lane)
        pre = gen.prefill_program(t_blk)
        t0p = time.perf_counter()
        fresh = note_dispatch(lane.net,
                              ("gen_prefill", "sched", rows, t_pad, t_blk))
        with span("compile" if fresh else "inference",
                  path="continuous_prefill", bucket=t_pad, rows=n):
            caches, logits = pre(params, ids, lens)
        scat = gen.scatter_program(rows, t_blk, self.block_size)
        note_dispatch(lane.net, ("gen_pool_scatter", "sched", rows, t_blk))
        pool.set_layers(scat(pool.layers, caches, tnb))
        rs = gen.row_sample_program()
        note_dispatch(lane.net, ("gen_row_sample", "sched", rows))
        # SANCTIONED SYNC (one per admission group): tok0 must reach the
        # host to seed the slot state and the retire-at-step-0 check —
        # one small [rows] fetch, off the burst loop's critical K steps
        # dl4j-lint: disable=hot-path-host-sync
        toks = np.asarray(rs(logits, keys, folds, temp, top_k, top_p))
        t1p = time.perf_counter()
        self._note_prefill_cost(sum(len(s.fed) for s, _ in entries),
                                t1p - t0p)
        self._trace_admitted(
            [(seq, {"bucket": t_pad, "rows": n, "computed": len(seq.fed)})
             for seq, _ in entries], t0p, t1p, "dense")
        if self._draft_admit_ok(lane, len(entries)):
            self._draft_prefill(lane, [seq for seq, _ in entries])
        for i, (seq, blocks) in enumerate(entries):
            self._note_prefilled(seq, len(seq.fed), t0p)
            cache = self._cache_of(lane)
            if cache is not None:
                cache.note_admitted(0)
            self._install(lane, seq, blocks, int(toks[i]))

    def _prefill_tail_batch(self, lane: _Lane, t_tail_pad: int, tier: int,
                            entries: List[_AdmitPlan]) -> None:
        """One row-bucketed TAIL prefill of a cache-hit admission group:
        copy-on-write any matched partial tail blocks (device clone
        lands BEFORE the tail scatter; the shared ref releases after),
        then one ``tail_prefill_program`` dispatch writes every row's
        uncached tail K/V into its fresh blocks while attention reads
        the cloned table (cached prefix + fresh tail) causally, then
        each row's tok0 samples on its own PRNG clock exactly like the
        dense path. ``dl4j_sched_admitted_rows_total`` semantics are
        unchanged — a cached admission is still one admitted row."""
        gen, pool = lane.gen, lane.pool
        cache = self._cache_of(lane)
        t0p = time.perf_counter()
        # (src, dst) pairs: dst is the fresh block standing in at the
        # partial's table index — start // block_size by construction
        copies = [(p.cow_src, p.blocks[p.start // self.block_size])
                  for p in entries if p.cow_src is not None]
        if copies:
            cp = gen.block_copy_program(1, pool.num_blocks,
                                        self.block_size)
            for src, dst in copies:
                note_dispatch(lane.net, ("gen_block_copy", "sched", 1))
                pool.set_layers(cp(pool.layers,
                                   np.asarray([src], np.int32),
                                   np.asarray([dst], np.int32)))
            for p in entries:
                if p.cow_src is not None:
                    pool.free_blocks([p.cow_src])
                    p.cow_src = None
            if cache is not None:
                cache.note_cow(len(copies))
        n = len(entries)
        rows = bucket_for(n, self._admit_ladder)
        ids = np.zeros((rows, t_tail_pad), np.int32)
        starts = np.zeros(rows, np.int32)
        lens = np.zeros(rows, np.int32)
        tables = np.zeros((rows, tier), np.int32)
        keys = np.zeros((rows, 2), lane.keys.dtype)
        folds = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.zeros(rows, np.float32)
        for i, p in enumerate(entries):
            seq = p.seq
            tail = seq.fed[p.start:]
            ids[i, :len(tail)] = tail
            starts[i] = p.start
            lens[i] = len(tail)
            tables[i, :len(p.blocks)] = p.blocks
            keys[i] = seq.key
            folds[i] = seq.n_gen
            temp[i] = seq.req.temperature
            top_k[i] = seq.req.top_k
            top_p[i] = seq.req.top_p
        params = self._params(lane)
        tp = gen.tail_prefill_program(rows, t_tail_pad, tier,
                                      pool.num_blocks, self.block_size)
        fresh = note_dispatch(
            lane.net,
            ("gen_tail_prefill", "sched", rows, t_tail_pad, tier))
        with span("compile" if fresh else "inference",
                  path="continuous_tail_prefill", bucket=t_tail_pad,
                  rows=n, tier=tier):
            pools_out, logits = tp(params, pool.layers, ids, starts,
                                   lens, tables)
        pool.set_layers(pools_out)
        rs = gen.row_sample_program()
        note_dispatch(lane.net, ("gen_row_sample", "sched", rows))
        # SANCTIONED SYNC: the tail-prefill group's tok0 fetch — same
        # contract as the dense admission path above
        # dl4j-lint: disable=hot-path-host-sync
        toks = np.asarray(rs(logits, keys, folds, temp, top_k, top_p))
        t1p = time.perf_counter()
        self._note_prefill_cost(
            sum(len(p.seq.fed) - p.start for p in entries), t1p - t0p)
        self._trace_admitted(
            [(p.seq, {"bucket": t_tail_pad, "tier": tier, "rows": n,
                      "computed": len(p.seq.fed) - p.start,
                      "cached": p.start}) for p in entries],
            t0p, t1p, "tail")
        if self._draft_admit_ok(lane, len(entries)):
            self._draft_prefill(lane, [p.seq for p in entries])
        for i, p in enumerate(entries):
            self._note_prefilled(p.seq, len(p.seq.fed) - p.start, t0p)
            if cache is not None:
                # a host-tier restore's prefix came from the TIER, not
                # the cache — it must not inflate cache-saved tokens
                cache.note_admitted(0 if p.restored else p.start)
            self._install(lane, p.seq, p.blocks, int(toks[i]))

    def _prefill_shipped_batch(self, lane: _Lane, t_blk: int,
                               entries: List["_AdmitPlan"]) -> None:
        """Admit a disaggregated-handoff group WITHOUT a prefill
        forward: rebuild each row's dense caches from the shipped KV
        (padded/cut to this scheduler's block-rounded length — shipped
        positions past the true prompt are garbage-inert exactly like a
        local prefill's bucket padding), page them into the claimed
        blocks through the SAME scatter program a local admission uses,
        and sample tok0 off the SHIPPED last-token logits on the row's
        own PRNG clock. Zero prompt tokens are computed here — that is
        the disaggregation win the ``dl4j_disagg_kv_handoffs_total``
        counter and the decode-p99 bench measure."""
        gen, pool = lane.gen, lane.pool
        t0p = time.perf_counter()
        n = len(entries)
        rows = bucket_for(n, self._admit_ladder)
        nb = t_blk // self.block_size
        n_layers, heads, hd, dtype = gen.kv_layout()
        vocab = int(gen.emb.conf.n_in)
        caches = [{"k": np.zeros((rows, t_blk, heads, hd),
                                 np.dtype(dtype)),
                   "v": np.zeros((rows, t_blk, heads, hd),
                                 np.dtype(dtype))}
                  for _ in range(n_layers)]
        tnb = np.zeros((rows, nb), np.int32)
        logits = np.zeros((rows, vocab), np.float32)
        keys = np.zeros((rows, 2), lane.keys.dtype)
        folds = np.zeros(rows, np.int32)
        temp = np.zeros(rows, np.float32)
        top_k = np.zeros(rows, np.int32)
        top_p = np.zeros(rows, np.float32)
        for i, p in enumerate(entries):
            seq = p.seq
            ship = seq.req.kv_state
            kv = np.asarray(ship["kv"])  # [L, 2, 1, t_ship, h, hd]
            t_cp = min(int(kv.shape[3]), t_blk)
            for layer in range(n_layers):
                caches[layer]["k"][i, :t_cp] = kv[layer, 0, 0, :t_cp]
                caches[layer]["v"][i, :t_cp] = kv[layer, 1, 0, :t_cp]
            tnb[i, :len(p.blocks)] = p.blocks
            logits[i] = np.asarray(ship["logits"])[0]
            keys[i] = seq.key
            folds[i] = seq.n_gen
            temp[i] = seq.req.temperature
            top_k[i] = seq.req.top_k
            top_p[i] = seq.req.top_p
        scat = gen.scatter_program(rows, t_blk, self.block_size)
        note_dispatch(lane.net, ("gen_pool_scatter", "sched", rows, t_blk))
        with span("inference", path="continuous_kv_handoff", rows=n,
                  bucket=t_blk):
            pool.set_layers(scat(pool.layers, caches, tnb))
        rs = gen.row_sample_program()
        note_dispatch(lane.net, ("gen_row_sample", "sched", rows))
        # SANCTIONED SYNC: the shipped-KV handoff group's tok0 fetch —
        # sampled off the SHIPPED logits, same admission contract
        # dl4j-lint: disable=hot-path-host-sync
        toks = np.asarray(rs(logits, keys, folds, temp, top_k, top_p))
        from deeplearning4j_tpu.monitor import DISAGG_KV_HANDOFFS_COUNTER
        get_registry().counter(
            DISAGG_KV_HANDOFFS_COUNTER,
            "Disaggregated prefill→decode sessions admitted from "
            "shipped KV (zero prompt tokens recomputed)").inc(n)
        with self._lock:
            self._kv_handoffs += n
        t1p = time.perf_counter()
        self._trace_admitted(
            [(p.seq, {"bucket": t_blk, "rows": n, "computed": 0})
             for p in entries], t0p, t1p, "shipped")
        if self._draft_admit_ok(lane, len(entries)):
            # the handoff ships only the TARGET's cache — the draft
            # lane still prefills the prompt (its quantized forward is
            # the cheap one; the disaggregation win is the target's)
            self._draft_prefill(lane, [p.seq for p in entries])
        for i, p in enumerate(entries):
            self._note_prefilled(p.seq, 0, t0p)
            p.seq.req.kv_state = None  # one-shot: a preempt re-prefills
            self.events.append(
                f"kv_handoff seq={p.seq.seq_id} t={len(p.seq.fed)} "
                f"blocks={len(p.blocks)}")
            self._install(lane, p.seq, p.blocks, int(toks[i]))

    def _draft_admit_ok(self, lane: _Lane, n: int) -> bool:
        """Admission-time draft prefill only pays when the lane can
        actually speculate soon: past ``spec_max_rows`` every round is
        a plain-burst fallback anyway, so the draft prefill dispatches
        would be pure overhead on the saturated path. Those rows admit
        draft-less and :meth:`_draft_catchup` re-arms them once the
        batch drains back under the cap."""
        return (lane.draft_gen is not None
                and len(lane.active()) + n <= self.spec_max_rows)

    def _draft_prefill(self, lane: _Lane, seqs: List[_Seq],
                       history=None) -> None:
        """Write every admitted row's full fed history into the DRAFT
        lane's pool: a dense draft-net prefill + scatter per prompt
        bucket. The draft has no prefix cache and its quantized forward
        is the cheap one, so tail/shipped TARGET admissions still
        draft-prefill densely. A row the draft pool cannot cover right
        now admits draft-less — the lane then serves it through plain
        bursts (spec fallback) instead of failing the admission:
        speculation is an accelerator, never a correctness dependency.
        ``history`` (a seq_id → int32 token-array mapping) overrides
        the fed tokens per row (the catch-up path feeds a mid-stream
        row's full written history instead)."""
        dgen, dpool = lane.draft_gen, lane.draft_pool
        owner = _owner_key(lane.key)
        dparams = self._draft_params(lane)
        groups: Dict[int, List[Tuple[_Seq, np.ndarray]]] = {}
        for seq in seqs:
            hv = history[seq.seq_id] if history is not None \
                else np.asarray(seq.fed, np.int32)
            t_pad = dgen.prompt_bucket(len(hv), max(1, seq.remaining))
            groups.setdefault(t_pad, []).append((seq, hv))
        for t_pad in sorted(groups):
            group = groups[t_pad]
            t_blk = self._round_blocks(t_pad)
            nb = t_blk // self.block_size
            rows = bucket_for(len(group), self._admit_ladder)
            ids = np.zeros((rows, t_pad), np.int32)
            lens = np.zeros(rows, np.int32)
            tnb = np.zeros((rows, nb), np.int32)
            any_rows = False
            for i, (seq, hv) in enumerate(group):
                got = dpool.alloc(dpool.blocks_for(len(hv)),
                                  owner=owner)
                if got is None:
                    seq.draft_blocks = []
                    mark("spec_draft_admit_skipped", seq=seq.seq_id)
                    continue
                seq.draft_blocks = got
                any_rows = True
                ids[i, :len(hv)] = hv
                lens[i] = len(hv)
                tnb[i, :len(got)] = got
            if not any_rows:
                continue
            pre = dgen.prefill_program(t_blk)
            fresh = note_dispatch(
                lane.draft_net,
                ("gen_prefill", "sched", rows, t_pad, t_blk))
            with span("compile" if fresh else "inference",
                      path="continuous_spec_draft_prefill", bucket=t_pad,
                      rows=len(group)):
                caches, _logits = pre(dparams, ids, lens)
            scat = dgen.scatter_program(rows, t_blk, self.block_size)
            note_dispatch(lane.draft_net,
                          ("gen_pool_scatter", "sched", rows, t_blk))
            dpool.set_layers(scat(dpool.layers, caches, tnb))

    def _draft_catchup(self, lane: _Lane) -> None:
        """Re-arm speculation on rows that admitted draft-less — either
        because the batch was over ``spec_max_rows`` (the admission
        gate skipped their draft prefill) or because the draft pool
        was exhausted at admit time. Once the lane drains back under
        the cap, replay each row's full WRITTEN history (positions
        0..pos-1 of prompt+generated; the pending token at index pos
        stays the verify step's job) through one draft prefill so the
        next round speculates again. Host-side pool math filters rows
        the draft pool cannot cover to the full speculation horizon,
        so a tight pool never thrashes failed allocs every step."""
        if lane.draft_gen is None or not self.speculative:
            return
        act = lane.active()
        if not (0 < len(act) <= self.spec_max_rows):
            return
        missing = [s for s in act if not s.draft_blocks]
        if not missing:
            return
        dpool = lane.draft_pool
        hist: Dict[int, np.ndarray] = {}
        free, take = dpool.free_count, []
        for seq in missing:
            pos = int(lane.pos[seq.slot])
            stream = np.concatenate(
                [np.asarray(seq.req.prompt[seq.row], np.int32),
                 np.asarray(seq.generated, np.int32)])
            if len(stream) != pos + 1:  # invariant guard: never
                continue                # speculate on a bad history
            need = dpool.blocks_for(pos + self.spec_tokens + 1)
            if need > free:
                continue
            free -= need
            hist[seq.seq_id] = stream[:pos]
            take.append(seq)
        if not take:
            return
        self._draft_prefill(lane, take, history=hist)
        for seq in take:
            if not seq.draft_blocks:
                continue
            lane.draft_tables[seq.slot] = 0
            lane.draft_tables[seq.slot, :len(seq.draft_blocks)] = \
                np.asarray(seq.draft_blocks, np.int32)
            mark("spec_draft_catchup", seq=seq.seq_id,
                 pos=int(lane.pos[seq.slot]))

    def poison(self, err: BaseException) -> None:
        """Slice death: fail everything queued and in flight with the
        typed error and reject new submits — the engine calls this when
        a ChipFailure poisons its slice. The scheduler object stays
        constructed (stats/pools readable) but never serves again."""
        with self._lock:
            if self._fatal is not None:
                return
            self._fatal = err
        self._fail_everything(err)

    def _note_fatal(self, err: BaseException) -> None:
        """Route a ChipFailure seen under any dispatch to the engine's
        slice-poison seam (no-op for every other error class)."""
        if self._on_fatal is None:
            return
        seen, e = 0, err
        while e is not None and seen < 8:
            if type(e).__name__ == "ChipFailure":
                self._on_fatal(err)
                return
            e = e.__cause__
            seen += 1

    def _note_prefilled(self, seq: _Seq, computed: int,
                        t0p: Optional[float] = None) -> None:
        """Account the prompt tokens this admission actually COMPUTED
        (the tail; cache hits skip the matched prefix) — what the
        prefill-FLOP-reduction and warm-migration benches read — and
        bill the owner: computed prefill tokens plus the queue time
        from enqueue (or the last preemption's requeue) to the
        admission dispatch."""
        self._prefill_computed_tokens += int(computed)
        if seq.req.prefix is not None:
            self._resume_reprefill_tokens += int(computed)
        q_ms = 0.0
        if t0p is not None:
            q_ms = max(0.0, (t0p - seq.t_queued) * 1e3)
        self._attr_note(_owner_key(self._lane_key(seq)),
                        prefill=int(computed), queue_ms=q_ms)

    def _attr_note(self, owner: str, prefill: int = 0, decode: int = 0,
                   queue_ms: float = 0.0) -> None:
        """Tick one owner's attribution accumulators (and the mirrored
        ``dl4j_attr_*`` counter families, label ``model=owner`` —
        metric objects cached per owner so the hot paths pay a dict
        lookup, not a family registration)."""
        with self._lock:
            a = self._attr.get(owner)
            if a is None:
                a = self._attr[owner] = {
                    "prefill_tokens": 0, "decode_tokens": 0,
                    "queue_ms": 0.0}
            a["prefill_tokens"] += prefill
            a["decode_tokens"] += decode
            a["queue_ms"] += queue_ms
        m = self._attr_metrics.get(owner)
        if m is None:
            reg = get_registry()
            m = self._attr_metrics[owner] = (
                reg.counter(ATTR_PREFILL_TOKENS_COUNTER,
                            "Prompt tokens actually computed at prefill, "
                            "attributed per model[@version]", model=owner),
                reg.counter(ATTR_DECODE_TOKENS_COUNTER,
                            "Tokens decoded, attributed per "
                            "model[@version]", model=owner),
                reg.counter(ATTR_QUEUE_MS_COUNTER,
                            "Milliseconds sequences spent queued before "
                            "admission, attributed per model[@version]",
                            model=owner))
        if prefill:
            m[0].inc(prefill)
        if decode:
            m[1].inc(decode)
        if queue_ms > 0:
            m[2].inc(queue_ms)

    def attribution(self) -> Dict[str, Any]:
        """The scheduler's capacity bill: per-owner prefill/decode
        token counts and queue milliseconds, plus each pool's KV
        byte-second attribution (conservation law inside) — what
        ``stats()["attribution"]`` and the ``/healthz`` top-K
        consumers view read."""
        with self._lock:
            models = {k: dict(v) for k, v in self._attr.items()}
            pools = [p for _, p in sorted(self._pools.items())]
        return {"models": models,
                "kv_pools": [p.attribution() for p in pools]}

    # ------------------------------------------------- request tracing

    def _trace_begin(self, req: _DecodeRequest) -> None:
        """Self-root a trace for engine-level callers (no ambient
        context) so the TTFT decomposition exists with or without a
        router in front; either way the owning trace id is surfaced on
        the request's Future as ``trace_id``."""
        if req.trace is None and reqtrace.request_tracer() is not None:
            req.root = reqtrace.begin_trace(
                "decode_request", rows=req.n, t_in=req.t_in,
                max_new=req.max_new, resume=req.prefix is not None)
            if req.root is not None:
                req.trace = req.root.ctx
        if req.trace is not None:
            req.future.trace_id = req.trace.trace_id

    def _trace_admitted(self, entries, t0: float, t1: float,
                        kind: str) -> None:
        """Record an admission group's queue-wait + prefill spans from
        the batch dispatch's timestamps (no extra clock reads per row).
        ``entries`` is ``[(seq, extra_attrs), ...]``; the group records
        in TWO passes (all queue_waits, then all prefills) so a
        multi-row request's spans stay close-order monotonic within
        its own trace. A migration resume's re-prefill is the
        distinctly-attributed span the durable-decode acceptance reads
        (``resume=True``)."""
        if reqtrace.request_tracer() is None:
            return
        for seq, _extra in entries:
            reqtrace.record_span(
                seq.req.trace, "queue_wait", to_origin_us(seq.t_queued),
                (t0 - seq.t_queued) * 1e6, row=seq.row,
                requeued=seq.preemptions)
        for seq, extra in entries:
            reqtrace.record_span(
                seq.req.trace, "prefill", to_origin_us(t0),
                (t1 - t0) * 1e6, kind=kind,
                resume=seq.req.prefix is not None,
                preemptions=seq.preemptions, **extra)

    def _trace_burst(self, lane: _Lane) -> None:
        """Attribute the just-dispatched burst to every traced rider:
        one ``decode_burst`` span per active traced sequence carrying
        the slot bucket / block tier the dispatch compiled against and
        the live row count the cost was shared across."""
        info = self._last_burst
        self._last_burst = None
        if info is None or reqtrace.request_tracer() is None:
            return
        t0, dt_ms, rows, tier, n_active = info
        for seq in lane.active():
            reqtrace.record_span(
                seq.req.trace, "decode_burst", to_origin_us(t0),
                dt_ms * 1e3, slot_bucket=rows, tier=tier,
                k=self.burst_tokens, rows=n_active, seq=seq.seq_id)

    def _cache_insert(self, lane: _Lane, seq: _Seq) -> None:
        """Insert-on-retire (and on preempt — the victim's own resume
        then matches its cached prefix): pin the sequence's written
        token run into the lane's radix index BEFORE its blocks free,
        so the cache's references carry the full interior blocks over
        while the private tail returns to the free list."""
        cache = self._cache_of(lane)
        if cache is None or not seq.blocks or seq.pos <= 0:
            return
        tokens = np.concatenate(
            [seq.req.prompt[seq.row].astype(np.int64),
             np.asarray(seq.generated, np.int64)])[:seq.pos]
        cache.insert(lane.key, tokens, seq.blocks)

    def _emit_tokens(self, seq: _Seq) -> None:
        """Deliver the row's not-yet-delivered tokens through the
        request's ``on_tokens`` seam, tagged with their stream offset.
        Append-only by construction: ``seq.emitted`` only advances, so
        a preempted-and-resumed (or migrated-in) row never re-delivers.
        A callback error is the CONSUMER's bug — it must not take the
        scheduler (and every cotenant stream) down with it."""
        req = seq.req
        if req.on_tokens is None or seq.emitted >= len(seq.generated):
            return
        off = seq.emitted
        new = seq.generated[off:]
        seq.emitted = len(seq.generated)
        get_registry().counter(
            STREAM_CHUNKS_COUNTER,
            "Incremental decode-token chunks emitted through the "
            "on_tokens streaming seam").inc()
        traced = req.trace is not None and \
            reqtrace.request_tracer() is not None
        t0c = time.perf_counter() if traced else 0.0
        cb = req.on_tokens
        if self._emit_batch is not None \
                and getattr(cb, "burst_sink", None) is not None:
            # coalescing-marked callback inside a retire pass: defer to
            # the burst flush (one sink call per endpoint per burst)
            self._emit_batch.append((cb, off, np.asarray(new, np.int64)))
        else:
            try:
                cb(off, np.asarray(new, np.int64))
            except BaseException as e:
                mark("stream_callback_error", error=type(e).__name__)
        if traced:
            reqtrace.record_span(
                req.trace, "chunk_deliver", to_origin_us(t0c),
                (time.perf_counter() - t0c) * 1e6, offset=off,
                n=len(new))

    def _install(self, lane: _Lane, seq: _Seq, blocks: List[int],
                 tok0: int) -> None:
        req = seq.req
        seq.blocks = blocks
        seq.pos = len(seq.fed)
        if seq.carry is not None:
            # speculative pending-carry resume: the pending token was
            # drawn (on a spec PRNG lane) and counted BEFORE the
            # preemption — restore it instead of consuming the
            # admission draw, keeping the resumed stream's draws
            # token-for-token with an uninterrupted run
            tok0 = seq.carry
            seq.carry = None
        else:
            seq.generated.append(tok0)
            seq.n_gen += 1
        self._note_first_token(req)
        self._emit_tokens(seq)
        self._admitted_rows += 1
        get_registry().counter(
            SCHED_ADMITTED_COUNTER,
            "Decode rows admitted into batch slots between bursts").inc()
        slot = lane.free_slot()
        self.events.append(
            f"admit seq={seq.seq_id} slot={slot} lane={lane.key} "
            f"t={seq.pos} blocks={len(blocks)}")
        done0 = seq.n_gen >= req.max_new or (
            req.eos is not None and tok0 == req.eos)
        if done0:
            # the prefill's first token already finished the row:
            # retire without ever occupying the slot
            if not self._maybe_hibernate(lane, seq):
                self._cache_insert(lane, seq)
                lane.pool.free_blocks(seq.blocks,
                                      owner=_owner_key(lane.key))
                seq.blocks = []
            self._free_draft_blocks(lane, seq)
            self._retire_seq(lane, seq)
            return
        lane.seqs[slot] = seq
        lane.tables[slot] = 0
        lane.tables[slot, :len(blocks)] = blocks
        if lane.draft_tables is not None:
            lane.draft_tables[slot] = 0
            lane.draft_tables[slot, :len(seq.draft_blocks)] = \
                seq.draft_blocks
        lane.pos[slot] = seq.pos
        lane.tok[slot] = tok0
        lane.n_gen[slot] = seq.n_gen
        lane.done[slot] = False
        lane.keys[slot] = seq.key
        lane.temp[slot] = req.temperature
        lane.top_k[slot] = req.top_k
        lane.top_p[slot] = req.top_p
        lane.eos[slot] = -1 if req.eos is None else req.eos
        lane.max_new_v[slot] = req.max_new
        seq.slot = slot

    # ------------------------------------------------ pool growth/preempt

    def _ensure_blocks(self, lane: _Lane) -> None:
        """Top up every active sequence's block table to cover the next
        K positions (capped at its remaining quota). Exhaustion
        preempts the lowest-priority, youngest active sequence across
        every lane sharing the pool — possibly the grower itself."""
        for slot in range(lane.slots):
            seq = lane.seqs[slot]
            if seq is None:
                continue
            grow = min(self.burst_tokens, max(1, seq.remaining))
            if lane.draft_gen is not None:
                # a spec round writes pos..pos+K on BOTH lanes no
                # matter how much of it survives rejection (truncation
                # and rollback are host bookkeeping), so the horizon
                # covers K+1 positions even near the quota edge
                grow = max(grow, self.spec_tokens + 1)
            horizon = int(lane.pos[slot]) + grow
            while seq.slot is not None:
                delta = lane.pool.blocks_for(horizon) - len(seq.blocks)
                if delta <= 0:
                    break
                got = lane.pool.alloc(delta, owner=_owner_key(lane.key))
                if got is not None:
                    start = len(seq.blocks)
                    seq.blocks.extend(got)
                    lane.tables[slot, start:start + len(got)] = got
                    break
                victim = self._pick_victim(lane.pool)
                if victim is None or victim is seq:
                    # nobody (else) to evict: the grower yields its own
                    # slot (or, alone and still too big, fails typed)
                    if victim is seq and lane.pool.blocks_for(horizon) \
                            <= lane.pool.total_blocks:
                        self._preempt(victim)
                    else:
                        self._evict_fail(lane, seq, KVPoolExhausted(
                            f"sequence {seq.seq_id} needs "
                            f"{lane.pool.blocks_for(horizon)} blocks; pool "
                            f"holds {lane.pool.total_blocks}"))
                    break
                self._preempt(victim)
            if (lane.draft_pool is not None and seq.slot is not None
                    and seq.draft_blocks):
                dhorizon = int(lane.pos[slot]) + self.spec_tokens + 1
                delta = (lane.draft_pool.blocks_for(dhorizon)
                         - len(seq.draft_blocks))
                if delta > 0:
                    got = lane.draft_pool.alloc(
                        delta, owner=_owner_key(lane.key))
                    if got is None:
                        # defensive (the draft pool is sized for every
                        # slot at full context): drop draft coverage —
                        # the lane serves this row through plain bursts
                        self._free_draft_blocks(lane, seq)
                        lane.draft_tables[slot] = 0
                        mark("spec_draft_grow_failed", seq=seq.seq_id)
                    else:
                        start = len(seq.draft_blocks)
                        seq.draft_blocks.extend(got)
                        lane.draft_tables[slot,
                                          start:start + len(got)] = got

    def _pick_victim(self, pool: PagedKVCachePool) -> Optional[_Seq]:
        """Deterministic preemption policy: among every active sequence
        whose lane shares ``pool``, the LOWEST priority loses first and
        the YOUNGEST admission breaks ties (oldest work is closest to
        finishing — evicting it wastes the most compute)."""
        cands: List[_Seq] = []
        for lane in self._lanes.values():
            if lane.pool is pool:
                cands.extend(lane.active())
        if not cands:
            return None
        return min(cands, key=lambda s: (s.priority, -s.seq_id))

    def _preempt(self, seq: _Seq) -> None:
        """Free the victim's blocks and re-queue it AT THE FRONT with
        prompt + generated prefix; its PRNG clock (``n_gen``) rides
        along, so the resumed tokens equal an uninterrupted run's."""
        lane = self._lane_for(*self._lane_key(seq))
        slot = seq.slot
        swapped = None
        if lane.draft_gen is None and lane.pool.host_enabled \
                and seq.pos > 0 and seq.blocks:
            # host-tier preempt-swap (non-spec lanes; a spec victim's
            # pending-carry keeps the cache/requeue path): the
            # victim's written KV moves to host and its resume swaps
            # back in instead of re-prefilling — subject to the
            # per-block crossover at admission time
            swapped = lane.pool.swap_out(seq.blocks,
                                         owner=_owner_key(lane.key))
        if swapped is not None:
            seq.host_handles = swapped
            seq.host_covered = seq.pos
            seq.blocks = []
            with self._lock:
                self._preempt_swapouts += 1
        else:
            # insert-before-free: with the prefix cache on, the
            # victim's interior blocks survive as cached prefix — its
            # resume then degrades to a table clone plus a short tail
            # prefill
            self._cache_insert(lane, seq)
            lane.pool.free_blocks(seq.blocks, owner=_owner_key(lane.key))
            seq.blocks = []
        self._free_draft_blocks(lane, seq)
        if lane.draft_gen is not None and seq.n_gen > 0:
            # speculative pending-carry (see _Seq.carry): re-prefill
            # everything EXCEPT the pending token and restore it at
            # re-admission without a fresh draw. Safe under plain-burst
            # fallback too: feeding the carry through a decode step
            # draws the same fold on the same lane as the admission
            # redraw would (prefill ≡ decode-chain equivalence), so the
            # tokens agree either way.
            seq.carry = int(seq.generated[-1])
            seq.fed = np.concatenate(
                [seq.req.prompt[seq.row].astype(np.int32),
                 np.asarray(seq.generated[:-1], np.int32)])
        else:
            seq.fed = np.concatenate(
                [seq.req.prompt[seq.row].astype(np.int32),
                 np.asarray(seq.generated, np.int32)])
        seq.slot = None
        seq.preemptions += 1
        seq.t_queued = time.perf_counter()
        reqtrace.trace_event(seq.req.trace, "preempt", seq=seq.seq_id,
                             n_gen=seq.n_gen, priority=seq.priority)
        if slot is not None:
            lane.clear_slot(slot)
        with self._lock:
            self._queue.appendleft(seq)
            self._preemptions += 1
        get_registry().counter(
            SCHED_PREEMPTIONS_COUNTER,
            "Sequences preempted (blocks freed, re-queued with their "
            "generated prefix) because the KV pool was exhausted").inc()
        mark("decode_preempted", seq=seq.seq_id, priority=seq.priority)
        self.events.append(
            f"preempt seq={seq.seq_id} prio={seq.priority} "
            f"n_gen={seq.n_gen}")

    def _evict_fail(self, lane: _Lane, seq: _Seq,
                    err: BaseException) -> None:
        lane.pool.free_blocks(seq.blocks, owner=_owner_key(lane.key))
        seq.blocks = []
        self._free_draft_blocks(lane, seq)
        if seq.slot is not None:
            lane.clear_slot(seq.slot)
            seq.slot = None
        self._fail_seq(seq, err)

    # ----------------------------------------------------------- bursts

    def _burst_tiers(self, lane: _Lane) -> List[int]:
        """The power-of-two block-count ladder for one lane's burst
        programs (the PR-3 bucket doctrine applied to attention
        length): a burst attends only as many table columns as its
        LONGEST active sequence needs, rounded up the ladder, so short
        contexts never pay full-max_len gather cost — and the ladder is
        small enough to AOT-warm completely."""
        tiers, t = [], 1
        while t < lane.mb:
            tiers.append(t)
            t *= 2
        tiers.append(lane.mb)
        return tiers

    def _tier_for(self, lane: _Lane) -> int:
        need = 1
        for seq in lane.active():
            need = max(need, len(seq.blocks))
        for t in self._burst_tiers(lane):
            if need <= t:
                return t
        return lane.mb

    def _dispatch_burst(self, lane: _Lane, params, accounted: bool = False,
                        tier: Optional[int] = None,
                        sampling: Optional[bool] = None,
                        rows: Optional[int] = None):
        """ONE fixed-shape device dispatch: K decode steps over the
        ACTIVE rows compacted into the smallest slot bucket that covers
        them (``rows``), attending ``tier`` block-table columns (the
        ladder slot covering the longest active sequence), through the
        greedy-only program when no active row samples. The (rows ×
        K × tier) shape set is a small pre-compilable ladder — a
        half-empty batch never pays full-slot compute. Donated pools
        are re-installed from the program's outputs whether or not any
        slot was live (warmup runs it all-masked). Returns full-slot
        (ys, tok, pos, n_gen, done) views so retirement indexes by
        slot."""
        pool = lane.pool
        active = [i for i, s in enumerate(lane.seqs) if s is not None]
        if tier is None:
            tier = self._tier_for(lane)
        if sampling is None:
            sampling = any(s.req.temperature > 0.0 for s in lane.active())
        if rows is None:
            rows = bucket_for(max(1, len(active)), self._slot_ladder)
        if self._burst_hook is not None and accounted:
            self._burst_hook(lane.key, self._bursts)
        n = min(len(active), rows)
        sel = active[:n]
        tables = np.zeros((rows, tier), np.int32)
        tables[:n] = lane.tables[sel, :tier]
        pos = np.zeros(rows, np.int32)
        pos[:n] = lane.pos[sel]
        tok = np.zeros(rows, np.int32)
        tok[:n] = lane.tok[sel]
        n_gen = np.zeros(rows, np.int32)
        n_gen[:n] = lane.n_gen[sel]
        done = np.ones(rows, bool)
        done[:n] = lane.done[sel]
        keys = np.zeros((rows, 2), lane.keys.dtype)
        keys[:n] = lane.keys[sel]
        temp = np.zeros(rows, np.float32)
        temp[:n] = lane.temp[sel]
        top_k = np.zeros(rows, np.int32)
        top_k[:n] = lane.top_k[sel]
        top_p = np.zeros(rows, np.float32)
        top_p[:n] = lane.top_p[sel]
        eos = np.full(rows, -1, np.int32)
        eos[:n] = lane.eos[sel]
        max_new_v = np.zeros(rows, np.int32)
        max_new_v[:n] = lane.max_new_v[sel]
        bp = lane.gen.burst_program(rows, self.burst_tokens, tier,
                                    pool.num_blocks, pool.block_size,
                                    sampling=sampling)
        fresh = note_dispatch(
            lane.net, ("gen_burst", "sched", rows, self.burst_tokens,
                       tier, pool.num_blocks, pool.block_size,
                       bool(sampling)))
        t0 = time.perf_counter()
        with span("compile" if fresh else "inference",
                  path="continuous_burst", slots=rows,
                  k=self.burst_tokens, tier=tier,
                  rows=n if accounted else 0):
            pools, ys, tok2, pos2, ng2, done2 = bp(
                params, pool.layers, tables, pos, tok, n_gen, done, keys,
                temp, top_k, top_p, eos, max_new_v)
            # SANCTIONED SYNC (one per K-token burst): the burst's
            # tokens must reach the host to retire rows / emit chunks —
            # ONE [rows, K] fetch per dispatch, the design minimum
            # dl4j-lint: disable=hot-path-host-sync
            ys = np.asarray(ys)
        pool.set_layers(pools)
        dt_ms = (time.perf_counter() - t0) * 1e3
        if accounted:
            reg = get_registry()
            reg.counter(SCHED_BURSTS_COUNTER,
                        "Fixed-K decode bursts dispatched").inc()
            reg.histogram(SCHED_BURST_LATENCY_HISTOGRAM,
                          "Decode burst dispatch latency (K steps, one "
                          "scan)").observe(dt_ms)
            # host timestamps already taken — the per-rider trace spans
            # are recorded post-hoc by _trace_burst, zero device syncs
            self._last_burst = (t0, dt_ms, rows, tier, n)
            with self._lock:
                self._bursts += 1
        # scatter the compact outputs back onto full-slot views
        ys_f = np.zeros((lane.slots, self.burst_tokens), np.int32)
        tok_f = lane.tok.copy()
        pos_f = lane.pos.copy()
        ng_f = lane.n_gen.copy()
        done_f = lane.done.copy()
        ys_f[sel] = ys[:n]
        # SANCTIONED SYNC: the burst's compact slot-state vectors
        # (tok/pos/n_gen/done, [rows] each) ride home with the tokens —
        # part of the same one-fetch-per-burst budget as ys above
        # dl4j-lint: disable=hot-path-host-sync
        tok_f[sel] = np.asarray(tok2)[:n]
        pos_f[sel] = np.asarray(pos2)[:n]  # dl4j-lint: disable=hot-path-host-sync
        ng_f[sel] = np.asarray(ng2)[:n]  # dl4j-lint: disable=hot-path-host-sync
        done_f[sel] = np.asarray(done2)[:n]  # dl4j-lint: disable=hot-path-host-sync
        return ys_f, tok_f, pos_f, ng_f, done_f

    # ----------------------------------------------- speculative rounds

    def _draft_tiers(self, lane: _Lane) -> List[int]:
        """The draft lane's pow2 block-tier ladder (mirror of
        :meth:`_burst_tiers` over the draft pool's per-sequence max)."""
        tiers, t = [], 1
        while t < lane.draft_mb:
            tiers.append(t)
            t *= 2
        tiers.append(lane.draft_mb)
        return tiers

    def _draft_tier_for(self, lane: _Lane) -> int:
        need = 1
        for seq in lane.active():
            need = max(need, len(seq.draft_blocks))
        for t in self._draft_tiers(lane):
            if need <= t:
                return t
        return lane.draft_mb

    def _spec_rows_ladder(self) -> List[int]:
        """The slot buckets a speculative round can dispatch at: the
        slot ladder truncated at the bucket covering spec_max_rows
        (wider batches fall back to plain bursts, so warming wider spec
        shapes would be wasted compiles)."""
        cap = bucket_for(self.spec_max_rows, self._slot_ladder)
        return [r for r in self._slot_ladder if r <= cap]

    def _spec_eligible(self, lane: _Lane) -> bool:
        """Run a speculative round iff the lane has a draft, the active
        batch is narrow enough that the verify forward's K× extra token
        compute rides free (past ``spec_max_rows`` speculation costs
        throughput for no latency win — fall back), every active row
        has draft-lane KV coverage, and no row is close enough to
        max_context that the round's K+1 writes would run off the
        table."""
        if not self.speculative or lane.draft_gen is None:
            return False
        act = lane.active()
        if not act or len(act) > self.spec_max_rows:
            return False
        k = self.spec_tokens
        ctx = lane.gen.max_context()
        for s in act:
            if not s.draft_blocks:
                return False
            if int(lane.pos[s.slot]) + k + 1 > ctx:
                return False
        return True

    def _dispatch_spec_round(self, lane: _Lane, params):
        """One speculative round over the lane's active rows: the draft
        program proposes K tokens on the DRAFT lane, the target
        verifies all of them in ONE forward fused with the exact
        rejection sampler, and the host truncates/retires — two device
        round-trips total instead of K. Returns the same full-slot outs
        tuple :meth:`_retire` consumes (ys is [slots, K+1]: up to K
        accepted proposals plus the correction/bonus token). KV
        "rollback" past rejected positions is host ``pos`` bookkeeping
        only: both lanes' stale writes sit beyond the rolled-back pos
        and the next round's writes cover them before any causal mask
        can attend them — per-token quantized scales make that
        re-scatter bit-identical (the PR-14 invariant), so no device
        copy is ever needed."""
        pool, dpool = lane.pool, lane.draft_pool
        gen, dgen = lane.gen, lane.draft_gen
        k = self.spec_tokens
        active = [i for i, s in enumerate(lane.seqs) if s is not None]
        tier = self._tier_for(lane)
        dtier = self._draft_tier_for(lane)
        rows = bucket_for(max(1, len(active)), self._slot_ladder)
        if self._burst_hook is not None:
            self._burst_hook(lane.key, self._bursts)
        n = min(len(active), rows)
        sel = active[:n]
        tables = np.zeros((rows, tier), np.int32)
        tables[:n] = lane.tables[sel, :tier]
        dtables = np.zeros((rows, dtier), np.int32)
        dtables[:n] = lane.draft_tables[sel, :dtier]
        pos = np.zeros(rows, np.int32)
        pos[:n] = lane.pos[sel]
        tok = np.zeros(rows, np.int32)
        tok[:n] = lane.tok[sel]
        n_gen = np.zeros(rows, np.int32)
        n_gen[:n] = lane.n_gen[sel]
        keys = np.zeros((rows, 2), lane.keys.dtype)
        keys[:n] = lane.keys[sel]
        temp = np.zeros(rows, np.float32)
        temp[:n] = lane.temp[sel]
        top_k = np.zeros(rows, np.int32)
        top_k[:n] = lane.top_k[sel]
        top_p = np.zeros(rows, np.float32)
        top_p[:n] = lane.top_p[sel]
        live = np.zeros(rows, bool)
        live[:n] = True
        dparams = self._draft_params(lane)
        dp = dgen.spec_draft_program(rows, k, dtier, dpool.num_blocks,
                                     self.block_size)
        fresh_d = note_dispatch(
            lane.draft_net, ("gen_spec_draft", "sched", rows, k, dtier))
        t0 = time.perf_counter()
        with span("compile" if fresh_d else "inference",
                  path="continuous_spec_draft", slots=rows, k=k,
                  tier=dtier, rows=n):
            dpools, props, q = dp(dparams, dpool.layers, dtables, pos,
                                  tok, n_gen, keys, temp, top_k, top_p,
                                  live)
            # SANCTIONED SYNC (1 of 2 per spec round): wait out the
            # draft burst so dl4j_spec_draft_latency_ms and the
            # spec_draft span measure the draft alone — the
            # amortization bound the accept-rate dial is read against
            # dl4j-lint: disable=hot-path-host-sync
            jax.block_until_ready(props)
        dpool.set_layers(dpools)
        t1 = time.perf_counter()
        vp = gen.spec_verify_program(rows, k, tier, pool.num_blocks,
                                     self.block_size)
        fresh_v = note_dispatch(
            lane.net, ("gen_spec_verify", "sched", rows, k, tier))
        with span("compile" if fresh_v else "inference",
                  path="continuous_spec_verify", slots=rows, k=k,
                  tier=tier, rows=n):
            pools, out_d, acc_d = vp(params, pool.layers, tables, pos,
                                     tok, props, q, n_gen, keys, temp,
                                     top_k, top_p, live)
            # SANCTIONED SYNC (2 of 2): the round's output tokens and
            # accept lengths must reach the host to retire rows / emit
            # chunks — one [rows, K+1] + [rows] fetch per round
            # dl4j-lint: disable=hot-path-host-sync
            out = np.asarray(out_d)
            acc = np.asarray(acc_d)  # dl4j-lint: disable=hot-path-host-sync
        pool.set_layers(pools)
        t2 = time.perf_counter()
        # ---- host phase (the "rollback"): truncate each row's round
        # at its EOS/max-new and advance the shared pos/tok/n_gen
        # clocks by the surviving length only
        ys_f = np.zeros((lane.slots, k + 1), np.int32)
        tok_f = lane.tok.copy()
        pos_f = lane.pos.copy()
        ng_f = lane.n_gen.copy()
        done_f = lane.done.copy()
        accepted = 0
        for j, slot in enumerate(sel):
            seq = lane.seqs[slot]
            a = int(acc[j])
            toks = [int(t) for t in out[j, :a + 1]]
            budget = seq.req.max_new - int(lane.n_gen[slot])
            if len(toks) > budget:
                toks = toks[:budget]
            eos = seq.req.eos
            if eos is not None and eos in toks:
                toks = toks[:toks.index(eos) + 1]
            e = len(toks)
            accepted += min(a, e)
            ys_f[slot, :e] = toks
            tok_f[slot] = toks[-1]
            pos_f[slot] = int(lane.pos[slot]) + e
            ng_f[slot] = int(lane.n_gen[slot]) + e
            done_f[slot] = (ng_f[slot] >= seq.req.max_new
                            or (eos is not None and toks[-1] == eos))
        t3 = time.perf_counter()
        proposed = n * k
        rejected = proposed - accepted
        reg = get_registry()
        owner = _owner_key(lane.key)
        reg.counter(SPEC_PROPOSED_TOKENS_COUNTER,
                    "Draft tokens proposed to speculative verify "
                    "rounds", model=owner).inc(proposed)
        reg.counter(SPEC_ACCEPTED_TOKENS_COUNTER,
                    "Proposed draft tokens the target's rejection "
                    "sampler accepted", model=owner).inc(accepted)
        reg.counter(SPEC_REJECTED_TOKENS_COUNTER,
                    "Proposed draft tokens rejected (the residual "
                    "correction token replaces the first)",
                    model=owner).inc(rejected)
        reg.histogram(SPEC_DRAFT_LATENCY_HISTOGRAM,
                      "Speculative draft-burst dispatch latency (K+1 "
                      "chained draft steps, one scan)"
                      ).observe((t1 - t0) * 1e3)
        with self._lock:
            self._spec_rounds += 1
            self._spec_proposed += proposed
            self._spec_accepted += accepted
            self._spec_rejected += rejected
            rate = self._spec_accepted / max(1, self._spec_proposed)
            self._bursts += 1
        reg.gauge(SPEC_ACCEPT_RATE_GAUGE,
                  "Running speculative acceptance rate (accepted / "
                  "proposed) — the speedup dial; its prior is the "
                  "draft's quality-gate greedy-match rate",
                  model=owner).set(rate)
        reg.counter(SCHED_BURSTS_COUNTER,
                    "Fixed-K decode bursts dispatched").inc()
        reg.histogram(SCHED_BURST_LATENCY_HISTOGRAM,
                      "Decode burst dispatch latency (K steps, one "
                      "scan)").observe((t2 - t0) * 1e3)
        self._last_burst = (t0, (t2 - t0) * 1e3, rows, tier, n)
        if reqtrace.request_tracer() is not None:
            for seq in lane.active():
                tr = seq.req.trace
                reqtrace.record_span(tr, "spec_draft", to_origin_us(t0),
                                     (t1 - t0) * 1e6, k=k, rows=n,
                                     seq=seq.seq_id)
                reqtrace.record_span(tr, "spec_verify",
                                     to_origin_us(t1), (t2 - t1) * 1e6,
                                     k=k, rows=n, seq=seq.seq_id)
                reqtrace.record_span(tr, "spec_rollback",
                                     to_origin_us(t2), (t3 - t2) * 1e6,
                                     seq=seq.seq_id)
        return ys_f, tok_f, pos_f, ng_f, done_f

    def _retire(self, lane: _Lane, outs) -> None:
        ys, tok, pos, n_gen, done = outs
        finished: List[_Seq] = []
        self._emit_batch = []
        try:
            for slot in range(lane.slots):
                seq = lane.seqs[slot]
                if seq is None:
                    continue
                emitted = int(n_gen[slot]) - int(lane.n_gen[slot])
                if emitted > 0:
                    seq.generated.extend(int(t) for t in ys[slot, :emitted])
                    seq.n_gen = int(n_gen[slot])
                    seq.pos = int(pos[slot])
                    self._attr_note(_owner_key(lane.key), decode=emitted)
                    self._note_first_token(seq.req)
                    self._emit_tokens(seq)
                lane.tok[slot] = tok[slot]
                lane.pos[slot] = pos[slot]
                lane.n_gen[slot] = n_gen[slot]
                if bool(done[slot]):
                    if not self._maybe_hibernate(lane, seq):
                        self._cache_insert(lane, seq)
                        lane.pool.free_blocks(seq.blocks,
                                              owner=_owner_key(lane.key))
                        seq.blocks = []
                    self._free_draft_blocks(lane, seq)
                    lane.clear_slot(slot)
                    seq.slot = None
                    finished.append(seq)
        finally:
            # flush STRICTLY before any terminal resolution below: a
            # coalesced last chunk must reach the endpoint before the
            # terminal reply resolves (and un-registers) its stream
            self._flush_emit_batch()
        for seq in finished:
            self._retire_seq(lane, seq)

    def _flush_emit_batch(self) -> None:
        batch, self._emit_batch = self._emit_batch, None
        if not batch:
            return
        by_sink: Dict[Any, List[Tuple[Any, int, np.ndarray]]] = {}
        for cb, off, toks in batch:
            by_sink.setdefault(cb.burst_sink, []).append((cb, off, toks))
        for sink, entries in by_sink.items():
            try:
                sink(entries)
            except BaseException as e:
                mark("stream_callback_error", error=type(e).__name__)

    def _burst_failed(self, lane: _Lane, err: BaseException) -> None:
        """A burst dispatch died: every sequence that was riding it
        fails typed, its blocks free immediately (the kill-mid-burst
        contract: the pool must drain back to fully free), and the
        scheduler keeps serving later admissions."""
        record_fault("serving")
        mark("decode_burst_failed", lane=str(lane.key),
             error=type(err).__name__)
        self.events.append(f"burst_failed lane={lane.key} "
                           f"err={type(err).__name__}")
        for slot in range(lane.slots):
            seq = lane.seqs[slot]
            if seq is None:
                continue
            lane.pool.free_blocks(seq.blocks, owner=_owner_key(lane.key))
            seq.blocks = []
            self._free_draft_blocks(lane, seq)
            lane.clear_slot(slot)
            seq.slot = None
            self._fail_seq(seq, self._typed(err, seq))
        self._note_fatal(err)

    def _typed(self, err: BaseException, seq: _Seq) -> DecodeBurstError:
        e = DecodeBurstError(
            f"decode dispatch failed under sequence {seq.seq_id} "
            f"({type(err).__name__}: {err})")
        e.__cause__ = err
        return e

    # ------------------------------------------------------- completion

    def _note_first_token(self, req: _DecodeRequest) -> None:
        if req.t_first is None:
            req.t_first = time.perf_counter()

    # ------------------------------------------------ session hibernation

    def _maybe_hibernate(self, lane: _Lane, seq: _Seq) -> bool:
        """End-of-turn hibernation: swap the finished row's blocks out
        and file the durable session record (handles + the exact token
        run they cover) a later same-session submit restores from.
        Returns whether the blocks were taken — the caller then skips
        the cache-insert/free path. Swap-out refusal (tier off, host
        budget full) falls back to the normal retire: the journaled-
        prefix rung still resumes the session, just slower."""
        req = seq.req
        if not req.hibernate or req.session is None:
            return False
        if not lane.pool.host_enabled or not seq.blocks or seq.pos <= 0:
            return False
        owner = _owner_key(lane.key)
        handles = lane.pool.swap_out(seq.blocks, owner=owner)
        if handles is None:
            return False
        seq.blocks = []
        tokens = np.concatenate(
            [req.prompt[seq.row].astype(np.int64),
             np.asarray(seq.generated, np.int64)])[:seq.pos]
        with self._lock:
            old = self._hibernated.pop(req.session, None)
            self._hibernated[req.session] = {
                "handles": handles, "covered": int(seq.pos),
                "tokens": tokens, "lane": lane.key,
                "prompt": np.asarray(req.prompt[seq.row], np.int64),
                "generated": np.asarray(seq.generated, np.int64),
                "imported": False,
            }
            self._hibernated_total += 1
        if old is not None:
            self._lane_for(*old["lane"]).pool.free_host(
                old["handles"], owner=_owner_key(old["lane"]))
        get_registry().counter(
            KVTIER_HIBERNATED_COUNTER,
            "Sessions hibernated at end-of-turn (KV swapped to the "
            "host tier, durable resume record filed)").inc()
        self.events.append(
            f"hibernate session={req.session} covered={seq.pos} "
            f"blocks={len(handles)}")
        return True

    def _hibernate_drop(self, session: str) -> bool:
        with self._lock:
            rec = self._hibernated.pop(session, None)
        if rec is None:
            return False
        self._lane_for(*rec["lane"]).pool.free_host(
            rec["handles"], owner=_owner_key(rec["lane"]))
        return True

    def hibernated_count(self) -> int:
        with self._lock:
            return len(self._hibernated)

    def hibernated_sessions(self) -> List[str]:
        with self._lock:
            return sorted(self._hibernated)

    def hibernate_release(self, session: str) -> bool:
        """Free a hibernated session's host blocks and drop its record
        (the no-resume cleanup path). False when unknown."""
        return self._hibernate_drop(session)

    def hibernate_export(self, session: str) -> Optional[Dict[str, Any]]:
        """Read a hibernated session's full restore payload — host
        block contents (quantized components byte-exact), the covered
        token run, and its lane — WITHOUT consuming the local record.
        This is the cross-endpoint shipping source: the receiver
        ``hibernate_import``s it and the resume then rides the same
        local swap-in path a never-moved session uses."""
        with self._lock:
            rec = self._hibernated.get(session)
        if rec is None:
            return None
        lane = self._lane_for(*rec["lane"])
        return {
            "blocks": lane.pool.host_export(rec["handles"]),
            "covered": int(rec["covered"]),
            "tokens": np.asarray(rec["tokens"], np.int64),
            "prompt": np.asarray(rec["prompt"], np.int64),
            "generated": np.asarray(rec["generated"], np.int64),
            "model": rec["lane"][0],
            "version": rec["lane"][1],
        }

    def hibernate_import(self, session: str, blocks, covered: int,
                         tokens, model: Optional[str] = None,
                         version: Optional[int] = None,
                         prompt=None, generated=None) -> bool:
        """File a SHIPPED hibernation payload into this scheduler's
        host tier (cross-endpoint restore): the blocks land via
        ``host_insert`` and the record looks exactly like a local
        hibernation — the resume submit rides the same swap-in path,
        no separate restore program. False when the tier is off or
        over budget; the caller falls back to the journaled-prefix
        rung."""
        lane = self._lane_for(model, version)
        if not lane.pool.host_enabled:
            return False
        owner = _owner_key(lane.key)
        handles = lane.pool.host_insert(blocks, owner=owner)
        if handles is None:
            return False
        # dl4j-lint: disable=hot-path-host-sync — control-plane import
        # (once per restored session), host int64 token journal
        tokens = np.asarray(tokens, np.int64)
        with self._lock:
            old = self._hibernated.pop(session, None)
            self._hibernated[session] = {
                "handles": handles, "covered": int(covered),
                "tokens": tokens, "lane": lane.key,
                "prompt": (tokens if prompt is None
                           else np.asarray(prompt, np.int64)),
                "generated": (np.zeros(0, np.int64) if generated is None
                              else np.asarray(generated, np.int64)),
                "imported": True,
            }
        if old is not None:
            self._lane_for(*old["lane"]).pool.free_host(
                old["handles"], owner=_owner_key(old["lane"]))
        self.events.append(
            f"hibernate_import session={session} covered={int(covered)} "
            f"blocks={len(handles)}")
        return True

    def _retire_seq(self, lane: _Lane, seq: _Seq) -> None:
        req = seq.req
        self._retired_rows += 1
        get_registry().counter(
            SCHED_RETIRED_COUNTER,
            "Decode rows retired (EOS/max-len) between bursts, blocks "
            "freed").inc()
        self.events.append(
            f"retire seq={seq.seq_id} n_gen={seq.n_gen} "
            f"preemptions={seq.preemptions}")
        req.rows_done += 1
        if req.rows_done >= req.n and not req.future.done():
            self._resolve(req)

    def _resolve(self, req: _DecodeRequest) -> None:
        out = np.zeros((req.n, req.t_in + req.max_new), np.int64)
        out[:, :req.t_in] = req.prompt
        tokens = 0
        for seq in self._seqs_of(req):
            row = np.asarray(seq.generated, np.int64)
            tokens += len(row)
            fill = req.eos if req.eos is not None else 0
            padded = np.full(req.max_new, fill, np.int64)
            padded[:len(row)] = row[:req.max_new]
            out[seq.row, req.t_in:] = padded
        t_done = time.perf_counter()
        t_first = req.t_first if req.t_first is not None else t_done
        self.completed.append({
            "t_submit": req.t_submit, "t_first": t_first,
            "t_done": t_done, "rows": req.n, "tokens": tokens})
        # engine-owned root: seal BEFORE resolving so a caller reading
        # the completed trace on future completion always finds it
        reqtrace.finish_trace(
            req.root, outcome="ok", tokens=tokens,
            ttft_ms=round((t_first - req.t_submit) * 1e3, 3))
        req.future.set_result(out)
        self._count_resolved()

    def _seqs_of(self, req: _DecodeRequest) -> List[_Seq]:
        return req.rows

    def _fail_seq(self, seq: _Seq, err: BaseException) -> None:
        req = seq.req
        self.events.append(f"fail seq={seq.seq_id} err={type(err).__name__}")
        self._free_host_of(seq)
        if not req.future.done():
            reqtrace.finish_trace(req.root, outcome="error",
                                  error=type(err).__name__)
            req.future.set_exception(err)
            self._count_resolved()
        # drop the request's other queued rows: the future already failed
        with self._lock:
            others = [s for s in self._queue if s.req is req]
            for other in others:
                self._queue.remove(other)
        for other in others:
            self._free_host_of(other)
        for lane in self._lanes.values():
            for slot in range(lane.slots):
                s = lane.seqs[slot]
                if s is not None and s.req is req and s is not seq:
                    lane.pool.free_blocks(s.blocks,
                                          owner=_owner_key(lane.key))
                    s.blocks = []
                    self._free_draft_blocks(lane, s)
                    lane.clear_slot(slot)
                    s.slot = None

    def _count_resolved(self) -> None:
        with self._lock:
            self._resolved += 1
        if self._on_resolve is not None:
            self._on_resolve(1)

    def _fail_everything(self, err: BaseException) -> None:
        with self._lock:
            queued = list(self._queue)
            self._queue.clear()
        failed = set()
        for seq in queued:
            self._free_host_of(seq)
            if seq.req not in failed and not seq.req.future.done():
                reqtrace.finish_trace(seq.req.root, outcome="error",
                                      error=type(err).__name__)
                seq.req.future.set_exception(err)
                failed.add(seq.req)
                self._count_resolved()
        for lane in self._lanes.values():
            for slot in range(lane.slots):
                seq = lane.seqs[slot]
                if seq is None:
                    continue
                lane.pool.free_blocks(seq.blocks,
                                      owner=_owner_key(lane.key))
                seq.blocks = []
                self._free_draft_blocks(lane, seq)
                lane.clear_slot(slot)
                seq.slot = None
                if seq.req not in failed and not seq.req.future.done():
                    reqtrace.finish_trace(seq.req.root, outcome="error",
                                          error=type(err).__name__)
                    seq.req.future.set_exception(err)
                    failed.add(seq.req)
                    self._count_resolved()

    # -------------------------------------------------------- thread/gauges

    def _work_available(self) -> bool:
        if self._queue:
            return True
        return any(lane.active() for lane in self._lanes.values())

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._stopping and not self._work_available():
                    self._cv.wait(0.05)
                if self._stopping and (self._cancel
                                       or not self._work_available()):
                    break
            try:
                progressed = self.step()
            except BaseException as e:  # never die silently
                record_fault("serving")
                self._fail_everything(e)
                return
            if not progressed:
                with self._cv:
                    if self._stopping:
                        break
                    self._cv.wait(0.01)
        if self._cancel:
            self._fail_everything(
                RuntimeError("scheduler shut down before dispatch"))

    def _gauges(self) -> None:
        reg = get_registry()
        with self._lock:
            active = sum(len(lane.active()) for lane in self._lanes.values())
            queued = len(self._queue)
            pools = list(self._pools.values())
            caches = list(self._caches.values())
        reg.gauge(SCHED_ACTIVE_GAUGE,
                  "Decode sequences currently occupying batch slots"
                  ).set(active)
        reg.gauge(SCHED_QUEUED_GAUGE,
                  "Decode sequences queued awaiting admission").set(queued)
        if not timeseries_enabled():
            return
        # burst-boundary samples into the windowed time-series layer:
        # host ints/floats already in hand — zero device syncs
        ts_record(TS_SCHED_ACTIVE, active)
        ts_record(TS_SCHED_QUEUED, queued)
        for pool in pools:
            ts_record(TS_SCHED_POOL_OCCUPANCY, pool.occupancy())
        hits = misses = 0
        for c in caches:
            hits += c._hits
            misses += c._misses
        if hits + misses:
            ts_record(TS_SCHED_PREFIX_HIT_RATE, hits / (hits + misses))
