"""Engine-side worker: serves a broker request channel from a
``ParallelInference`` engine.

One :class:`EngineWorker` = one fleet endpoint. It consumes
``<service>.req`` frames, submits them to its engine, and publishes
each reply to the requester's private reply topic with the request's
correlation id — the engine's own micro-batching coalesces concurrent
broker requests exactly like in-process ones, so the fleet tier adds
routing without giving up batching efficiency.

Lifecycle (the shutdown half the router's failover depends on):

- ``serving`` — heartbeats flow every ``heartbeat_s`` with the engine's
  ``stats()`` snapshot riding along;
- ``drain_and_stop()`` — stop consuming NEW requests, let every
  accepted one resolve (``engine.drain``), announce ``draining`` then
  ``stopped`` heartbeats, and only then stop the engine: planned
  scale-down loses zero requests;
- ``kill()`` — the faultinject seam: stop everything abruptly,
  replying to nothing (what SIGKILL on the engine process looks like
  from the wire). In-flight requesters see silence; their endpoint
  times the futures out and the router fails over.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from deeplearning4j_tpu.monitor import (TS_WORKER_SERVED, reqtrace,
                                        timeseries_enabled)
from deeplearning4j_tpu.monitor.tracing import now_us
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.streaming.broker import MessageBroker

logger = logging.getLogger("deeplearning4j_tpu")


class EngineWorker:
    """Serve one ``ParallelInference`` engine over a broker channel."""

    def __init__(self, engine, broker: MessageBroker, service: str,
                 name: Optional[str] = None,
                 hb_broker: Optional[MessageBroker] = None,
                 reply_broker: Optional[MessageBroker] = None,
                 heartbeat_s: float = 0.25, poll_s: float = 0.05,
                 wire_version: int = wire.WIRE_VERSION,
                 start: bool = True):
        """``broker`` carries the request consume loop. Over a
        ``TcpBroker`` pass SEPARATE connections as ``reply_broker`` and
        ``hb_broker``: the consume long-poll holds its connection's
        lock for up to the server's poll window, and replies queued
        behind it would trickle out at the poll rate instead of
        resolving as the engine finishes (an ``InMemoryBroker`` has no
        such contention — sharing is fine there).

        ``wire_version`` pins the wire ceiling this worker SPEAKS and
        advertises in heartbeats (the rolling-upgrade test seam: pin 3
        and the worker behaves exactly like a pre-v4 build — serves
        legacy frames, rejects v4 frames typed)."""
        self.engine = engine
        self.service = service
        self.name = name or service
        self.wire_version = int(wire_version)
        self._broker = broker
        self._reply_broker = reply_broker or broker
        self._hb_broker = hb_broker or broker
        self.heartbeat_s = float(heartbeat_s)
        self._poll = float(poll_s)
        self._state = wire.STATE_SERVING
        self._seq = 0
        self._stop = threading.Event()      # stop consuming new work
        self._killed = threading.Event()    # abrupt: no replies either
        self._wedged = threading.Event()    # faultinject: alive, no work
        self._served = 0
        self._hb_served_prev = 0  # served count at the previous beat
        self._wedge_dropped = 0
        self._threads = []
        if start:
            self.start()

    def start(self) -> "EngineWorker":
        if self._threads:
            return self
        self._threads = [
            threading.Thread(target=self._serve_loop, daemon=True,
                             name=f"dl4j-tpu-worker-{self.name}"),
            threading.Thread(target=self._hb_loop, daemon=True,
                             name=f"dl4j-tpu-worker-{self.name}-hb"),
        ]
        for t in self._threads:
            t.start()
        return self

    # ------------------------------------------------------------ serve

    def _serve_loop(self):
        topic = self.service + wire.REQ_SUFFIX
        while not self._stop.is_set():
            try:
                msg = self._broker.consume(topic, timeout=self._poll)
            except BaseException as e:
                if self._stop.is_set():
                    return
                logger.warning("worker %s: consume failed (%s: %s)",
                               self.name, type(e).__name__, e)
                time.sleep(self._poll)
                continue
            if msg is None:
                continue
            try:
                header, x, segs = wire.unpack_request_any(msg)
            except wire.WireFrameError as e:
                # structurally damaged binary frame: rejected typed and
                # WHOLE — no partially-parsed tensor reaches the engine
                logger.warning("worker %s: damaged v4 frame rejected "
                               "(WireFrameError: %s)", self.name, e)
                continue
            except Exception as e:
                logger.warning("worker %s: undecodable request (%s)",
                               self.name, e)
                continue
            corr, reply_topic = header.get("id"), header.get("reply")
            # reply in the framing the request arrived in (a v3 caller
            # must never receive a v4 binary reply)
            req_v4 = int(header.get("v", 1)) >= 4 \
                and self.wire_version >= 4
            try:
                # a frame from a NEWER protocol is rejected typed, not
                # served garbled (the wire v2 skew contract; a worker
                # pinned below v4 rejects binary frames the same way)
                wire.check_version(header, cap=self.wire_version)
            except wire.WireVersionError as e:
                self._reply(reply_topic, wire.pack_reply(corr, error=e))
                continue
            if self._wedged.is_set():
                # faultinject wedge: the request is consumed and then
                # silently dropped — heartbeats keep flowing (liveness)
                # while every progress counter stays flat, the failure
                # mode heartbeats alone cannot see
                self._wedge_dropped += 1
                continue
            self._served += 1
            # multi-model routing fields ride the header; absent for a
            # single-model engine (whose submit() takes no model=)
            route = {k: header[k] for k in ("model", "version", "session")
                     if header.get(k) is not None}
            # propagated request-trace context (optional header field —
            # a worker that predates it never reads the key): installed
            # thread-locally so the engine's submit path picks it up,
            # plus one wire_ingress span marking the hop boundary
            tctx = reqtrace.from_wire(header.get("trace"))
            t_ingress = now_us()
            hib_session = None  # ship a hibernation handle at retire
            try:
                if header.get("kind") == wire.KIND_PREFILL:
                    # disaggregated prefill: compute prompt KV + logits
                    # and ship them back — one tagged tensor chunk (kv)
                    # then the terminal reply (logits); the decode
                    # endpoint admits the session from the shipped state
                    with reqtrace.use_trace(tctx):
                        out = self.engine.prefill_export(
                            x.astype(np.int32, copy=False))
                    reqtrace.record_span(
                        tctx, "wire_ingress", t_ingress,
                        now_us() - t_ingress, kind=wire.KIND_PREFILL,
                        worker=self.name)
                    if req_v4:
                        # shipped KV rides raw v4 segments: byte-exact,
                        # no npz container on the disagg hot path
                        self._reply(reply_topic, wire.pack_tensor_chunk_v4(
                            corr, "kv", out["kv"]))
                        self._reply(reply_topic,
                                    wire.pack_reply_v4(corr, out["logits"]))
                    else:
                        self._reply(reply_topic, wire.pack_tensor_chunk(
                            corr, "kv", out["kv"]))
                        self._reply(reply_topic,
                                    wire.pack_reply(corr, out["logits"]))
                    continue
                if header.get("kind") == wire.KIND_GENERATE:
                    g = header.get("gen") or {}
                    kwargs = dict(route)
                    if g.get("kv"):
                        if "kv" in segs:
                            # v4 handoff: prompt is the x segment, the
                            # shipped KV + logits ride raw segments
                            kwargs["kv_state"] = {
                                "kv": np.asarray(segs["kv"]),
                                "t_in": x.shape[-1],
                                "logits": np.asarray(segs["logits"])}
                            x = np.asarray(x, np.int32)
                        else:
                            # v3 handoff frame: the BODY is the shipped
                            # KV tensor; the (small) prompt rides the
                            # header
                            prompt = np.asarray(g["prompt"], np.int32)[None]
                            kwargs["kv_state"] = {
                                "kv": x, "t_in": prompt.shape[1],
                                "logits": np.asarray(g["logits"], np.float32)[None]}
                            x = prompt
                    if g.get("hib"):
                        # shipped hibernation payload (cross-endpoint
                        # resume): raw segments reassemble into the
                        # hibernate_import layout — the engine seeds
                        # its host tier, then the ordinary swap-in
                        # path finishes the restore
                        kwargs["kv_state"] = wire.hibernation_from_segments(
                            g["hib"], segs)
                    if g.get("hibernate"):
                        kwargs["hibernate"] = True
                        # after the turn retires, ship the session's
                        # durable handle back (v4 peers only — the
                        # journal rung covers v3 resumes)
                        if req_v4:
                            hib_session = header.get("session")
                    if "prefix" in segs:
                        kwargs["prefix"] = np.asarray(segs["prefix"],
                                                      np.int64)
                    elif g.get("prefix") is not None:
                        kwargs["prefix"] = np.asarray(g["prefix"], np.int64)
                    if g.get("stream"):
                        kwargs["on_tokens"] = self._make_stream_cb(
                            corr, reply_topic, req_v4)
                    with reqtrace.use_trace(tctx):
                        fut = self.engine.submit_generate(
                            x.astype(np.int32, copy=False),
                            g.get("max_new", 1),
                            temperature=g.get("temperature", 0.0),
                            top_k=g.get("top_k", 0),
                            top_p=g.get("top_p", 0.0),
                            eos_token=g.get("eos_token"),
                            seed=g.get("seed", 0), **kwargs)
                else:
                    with reqtrace.use_trace(tctx):
                        fut = self.engine.submit(x, **route)
            except BaseException as e:
                # typed: the caller's endpoint reconstructs the same
                # exception class (shed/quarantine isolation contract)
                pack = wire.pack_reply_v4 if req_v4 else wire.pack_reply
                self._reply(reply_topic, pack(corr, error=e))
                continue
            reqtrace.record_span(
                tctx, "wire_ingress", t_ingress, now_us() - t_ingress,
                kind=header.get("kind"), worker=self.name)
            fut.add_done_callback(
                lambda f, c=corr, rt=reply_topic, v4=req_v4,
                hs=hib_session:
                self._deliver(c, rt, f, v4, hs))

    def _make_stream_cb(self, corr, reply_topic, req_v4):
        """Build the per-stream token-delta callback. For a v4 caller
        the callback is MARKED for burst coalescing (``burst_sink`` /
        ``corr`` / ``reply_topic`` attributes): a coalescing-aware
        scheduler batches every cotenant stream's delta from one
        retiring burst and hands them to :meth:`_publish_burst` — ONE
        frame per endpoint per burst. Called outside a batch (or by a
        scheduler that predates coalescing) it degrades to a
        single-entry coalesced frame; v3 callers keep per-stream
        :func:`wire.pack_chunk` frames."""
        if not req_v4:
            return (lambda off, toks, c=corr, rt=reply_topic:
                    self._reply(rt, wire.pack_chunk(c, off, toks)))

        def cb(off, toks, c=corr, rt=reply_topic):
            self._reply(rt, wire.pack_chunks_v4([(c, off, toks)]))
        cb.burst_sink = self._publish_burst
        cb.corr = corr
        cb.reply_topic = reply_topic
        return cb

    def _publish_burst(self, entries):
        """Coalesced emit: ``entries`` is ``[(cb, off, tokens), ...]``
        — every stream delta one retiring burst produced for callbacks
        marked with this sink. Grouped by reply topic: each endpoint
        receives ONE v4 chunks frame carrying all of its streams'
        deltas."""
        by_topic = {}
        for cb, off, toks in entries:
            by_topic.setdefault(cb.reply_topic, []).append(
                (cb.corr, off, toks))
        for topic, chunk_entries in by_topic.items():
            self._reply(topic, wire.pack_chunks_v4(chunk_entries))

    def _deliver(self, corr, reply_topic, fut, v4=False,
                 hib_session=None):
        if self._killed.is_set():
            return  # a killed worker answers nothing
        pack = wire.pack_reply_v4 if v4 else wire.pack_reply
        err = fut.exception()
        if err is None and hib_session is not None:
            # the durable handle precedes the terminal frame: by the
            # time the caller sees the turn resolve, the router already
            # holds everything a survivor needs to resume the session
            # bitwise after this endpoint dies
            try:
                hp = self.engine.hibernate_export(hib_session)
                if hp is not None:
                    self._reply(reply_topic,
                                wire.pack_hibernation_v4(corr, hp))
            except ValueError:
                # session spans more blocks than one frame carries —
                # skip shipping; journaled-prefix resume stays exact
                pass
        if err is None:
            payload = pack(corr, np.asarray(fut.result()))
        else:
            payload = pack(corr, error=err)
        self._reply(reply_topic, payload)

    def _reply(self, reply_topic, payload):
        if self._killed.is_set() or not reply_topic:
            return
        try:
            self._reply_broker.publish(reply_topic, payload)
        except BaseException as e:
            logger.warning("worker %s: reply publish failed (%s: %s)",
                           self.name, type(e).__name__, e)

    # -------------------------------------------------------- heartbeat

    def _hb_loop(self):
        topic = self.service + wire.HB_SUFFIX
        while not self._killed.is_set():
            self._beat(topic)
            if self._state == wire.STATE_STOPPED:
                return
            self._killed.wait(self.heartbeat_s)

    def _beat(self, topic):
        self._seq += 1
        try:
            served = self._served
            if timeseries_enabled():
                # per-beat served delta into the ENGINE's private
                # store, so the summary riding this very heartbeat
                # carries the worker's throughput series too
                delta = served - self._hb_served_prev
                ts = getattr(self.engine, "timeseries", None)
                if ts is not None and delta >= 0:
                    ts.record(TS_WORKER_SERVED, float(delta))
            self._hb_served_prev = served
            stats = dict(self.engine.stats())
            stats["served"] = served
            self._hb_broker.publish(topic, wire.pack_heartbeat(
                self.name, self._seq, self._state, stats,
                wire_version=self.wire_version))
        except BaseException as e:
            logger.warning("worker %s: heartbeat failed (%s: %s)",
                           self.name, type(e).__name__, e)

    # -------------------------------------------------------- lifecycle

    def drain_and_stop(self, timeout: Optional[float] = 30.0) -> bool:
        """Graceful exit: stop consuming, announce the drain
        IMMEDIATELY (so routers pull this endpoint from their pools and
        re-pin its decode sessions before any request can strand),
        resolve everything accepted — in-flight decode streams finish
        here, chunks and terminal frames included: zero lost tokens —
        then say goodbye and stop the engine. Returns False when the
        engine did not drain within ``timeout``."""
        self._state = wire.STATE_DRAINING
        self._stop.set()
        # the draining beat precedes the drain itself: session hand-off
        # happens while this worker is still resolving its last work
        self._beat(self.service + wire.HB_SUFFIX)
        drained = self.engine.drain(timeout=timeout)
        self._state = wire.STATE_STOPPED
        self._beat(self.service + wire.HB_SUFFIX)  # announce the exit
        self._killed.set()
        for t in self._threads:
            t.join(timeout=2)
        self.engine.shutdown()
        return drained

    def kill(self) -> None:
        """Abrupt death (faultinject): stop consuming AND replying
        immediately — pending requesters hear nothing, heartbeats go
        silent, exactly the SIGKILL signature."""
        self._stop.set()
        self._killed.set()
        for t in self._threads:
            t.join(timeout=2)

    def wedge(self) -> None:
        """Faultinject seam: keep heartbeating (liveness) but silently
        drop every consumed request — zero progress with queued work,
        the wedged-worker signature the router's progress watchdog (not
        its heartbeat plane) must catch."""
        self._wedged.set()

    def unwedge(self) -> None:
        self._wedged.clear()

    @property
    def state(self) -> str:
        return self._state
