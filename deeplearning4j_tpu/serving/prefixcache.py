"""Cross-request prefix cache: a radix index over block-aligned token
runs mapped onto the refcounted paged KV pool.

Millions of requests share system prompts, few-shot preambles and
multi-turn histories, yet before this module every admitted prompt
re-prefilled from token 0. The paged pool (nn/kvpool.py) makes sharing
natural — KV state is already block-granular and position-local — so
this is the vLLM automatic-prefix-caching / SGLang RadixAttention
discipline on the existing machinery:

- **index**: a radix tree over BLOCK-ALIGNED token runs, one tree of
  full-block nodes per ``(model, version)`` lane (lanes share a pool
  when their KV spec matches, but cached K/V is computed by one
  version's params — a canary must never match the stable's cache).
  Each full node owns one pool block (the cache holds a reference);
  a node may also carry *partial* children: the inserting sequence's
  last, partially-filled block together with its token content;
- **insert-on-retire**: when a sequence retires (or is preempted) the
  scheduler offers its written token run + block table; the cache
  walks/extends the radix chain, taking a pool reference on each block
  it newly pins (a chain that already exists is just touched — no
  duplicate caching, the sequence's own blocks free normally);
- **longest-prefix match at admission**: an admitted prompt walks the
  chain, shares every matched full block (pool refcount + 1 per block,
  on the sequence's behalf) and optionally one partial tail block,
  then prefills ONLY the remaining tail. Matching is capped at
  ``len(prompt) - 1`` — the last prompt token is always recomputed,
  because its logits seed the first sampled token;
- **copy-on-write**: full interior blocks are immutable once written
  (decode only ever writes at the growing tail), so the ONLY block a
  sharer can collide on is a matched *partial* tail block — the
  scheduler copies it to a fresh block before its first scatter lands
  (``dl4j_prefixcache_cow_copies_total``) and drops the shared
  reference, which is why "preempt a sharer" frees only its private
  tail;
- **deterministic eviction, unified with the free list**: the cache
  registers itself as the pool's reclaimer — when ``alloc`` finds the
  free list short it evicts cached-but-UNREFERENCED leaf blocks in
  LRU order (a logical clock that only ticks on cache operations, so
  replayed schedules evict identically; ties break on node id) and the
  freed ids rejoin the sorted lowest-id-first free list. A block some
  live sequence still references is never an eviction candidate — the
  ``ModelRegistry`` memory-budget discipline applied to KV. An
  optional ``capacity_blocks`` budget bounds the cache independently
  of pool pressure.

Everything here is host-side accounting; the device-side halves (the
tail prefill that gathers cached blocks, the COW block copy) live in
``nn/generate.py`` and are driven by the scheduler.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.monitor import (
    KVTIER_DEMOTIONS_COUNTER,
    PREFIXCACHE_CACHED_BLOCKS_GAUGE,
    PREFIXCACHE_COW_COPIES_COUNTER,
    PREFIXCACHE_DEMOTIONS_COUNTER,
    PREFIXCACHE_EVICTIONS_COUNTER,
    PREFIXCACHE_HITS_COUNTER,
    PREFIXCACHE_MISSES_COUNTER,
    PREFIXCACHE_SAVED_TOKENS_COUNTER,
    PREFIXCACHE_SHARED_BLOCKS_GAUGE,
    get_registry,
)
from deeplearning4j_tpu.nn.kvpool import PagedKVCachePool


class _Node:
    """One cached block: a full block-run radix node (``fill ==
    block_size``) or a partial tail (``fill < block_size``, kept under
    its parent's ``partials``). The cache holds exactly ONE pool
    reference per node."""

    __slots__ = ("nid", "lane", "block", "tokens", "fill", "parent",
                 "pkey", "partial", "children", "partials", "last_used",
                 "host")

    def __init__(self, nid: int, lane, block: Optional[int],
                 tokens: Tuple[int, ...], fill: int,
                 parent: Optional["_Node"], partial: bool):
        self.nid = nid
        self.lane = lane
        self.block = block          # None for roots and host-resident nodes
        self.host = None            # host-tier handle when demoted
        self.tokens = tokens
        self.fill = fill
        self.parent = parent
        self.pkey = tokens          # key in the parent's child dict
        self.partial = partial
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.partials: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0

    def leaf(self) -> bool:
        return not self.children and not self.partials


class PrefixCache:
    """Token-prefix → KV-block index over one :class:`PagedKVCachePool`
    (one cache per pool; lanes sharing the pool get separate radix
    roots keyed by their ``(model, version)``)."""

    def __init__(self, pool: PagedKVCachePool,
                 capacity_blocks: Optional[int] = None,
                 register: bool = True):
        self.pool = pool
        self.block_size = pool.block_size
        self.capacity_blocks = (None if capacity_blocks is None
                                else max(0, int(capacity_blocks)))
        self._roots: Dict[Tuple, _Node] = {}
        self._nodes = 0             # live node count (cached blocks)
        self._nid = 0               # node id allotter (eviction ties)
        self._clock = 0             # logical LRU clock: cache ops only
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._demotions = 0
        self._host_nodes = 0        # nodes resident in the host tier
        self._cow_copies = 0
        self._saved_tokens = 0
        self._inserted_runs = 0
        self._lock = threading.RLock()
        if register:
            # the exhaustion ladder, pinned by registration order:
            # cache-DEMOTE to the host tier first (nothing is lost),
            # cache-DROP second, and only then does alloc fail. The
            # demote rung no-ops on pools without a host tier.
            pool.register_reclaimer(self.reclaim_demote)
            pool.register_reclaimer(self.reclaim)

    # ------------------------------------------------------------ probe

    def match(self, lane: Tuple, tokens) -> Tuple[int, List[int],
                                                  Optional[int]]:
        """Longest cached prefix of ``tokens`` for ``lane``: returns
        ``(matched_tokens, full_block_ids, partial_block_id)``. The
        cache takes one pool reference per returned block ON THE
        CALLER'S BEHALF — the sequence frees them like its own blocks
        (refcounted, so "free" just drops its hold). Matching walks
        whole blocks, then the best (longest, oldest-id tie-break)
        partial child; it is capped at ``len(tokens) - 1`` so the last
        prompt token is always recomputed for its logits."""
        toks = [int(t) for t in tokens]
        bs = self.block_size
        with self._lock:
            self._clock += 1
            root = self._roots.get(lane)
            usable = toks[:max(0, len(toks) - 1)]
            full_ids: List[int] = []
            partial_id: Optional[int] = None
            m = 0
            if root is not None:
                cur = root
                i = 0
                # blocks are shared AS THE WALK MATCHES THEM (not at
                # the end): a host-resident node's promotion below
                # allocates device blocks, which may run the reclaimer
                # chain — an already-matched block at refcount 1 could
                # be evicted out from under us; at refcount 2 it is
                # pinned by the caller's share and untouchable
                while i + bs <= len(usable):
                    child = cur.children.get(tuple(usable[i:i + bs]))
                    if child is None:
                        break
                    child.last_used = self._clock
                    if child.block is None \
                            and not self._promote_locked(child):
                        break  # host-resident, device full: match ends
                    self.pool.share_blocks([child.block])
                    full_ids.append(child.block)
                    cur = child
                    i += bs
                best: Optional[_Node] = None
                best_len = 0
                rest = usable[i:]
                for ptoks, pnode in cur.partials.items():
                    cl = 0
                    for a, b in zip(ptoks, rest):
                        if a != b:
                            break
                        cl += 1
                    if cl >= 1 and (cl > best_len or
                                    (cl == best_len and best is not None
                                     and pnode.nid < best.nid)):
                        best, best_len = pnode, cl
                if best is not None and (
                        best.block is not None
                        or self._promote_locked(best)):
                    best.last_used = self._clock
                    self.pool.share_blocks([best.block])
                    partial_id = best.block
                    m = i + best_len
                else:
                    m = i
        self._publish()
        return m, full_ids, partial_id

    def note_admitted(self, matched_tokens: int) -> None:
        """Record one COMMITTED admission probe (hit/miss + saved
        prefill tokens). Separate from :meth:`match` because the
        scheduler may probe and roll a candidate back (group-signature
        mismatch) — only admissions that actually clone the table
        count."""
        m = int(matched_tokens)
        reg = get_registry()
        with self._lock:
            if m > 0:
                self._hits += 1
                self._saved_tokens += m
            else:
                self._misses += 1
        if m > 0:
            reg.counter(PREFIXCACHE_HITS_COUNTER,
                        "Admissions that matched a cached prefix",
                        pool=self.pool.name).inc()
            reg.counter(PREFIXCACHE_SAVED_TOKENS_COUNTER,
                        "Prompt tokens whose prefill was skipped because "
                        "their KV blocks were already cached",
                        pool=self.pool.name).inc(m)
        else:
            reg.counter(PREFIXCACHE_MISSES_COUNTER,
                        "Admissions that matched nothing",
                        pool=self.pool.name).inc()

    # ----------------------------------------------------------- insert

    def insert(self, lane: Tuple, tokens, blocks: List[int]) -> int:
        """Insert-on-retire: pin the retiring sequence's written token
        run (``tokens`` = every position its blocks actually hold) into
        the lane's radix chain. Full blocks extend the chain; a
        trailing partial block becomes a partial child carrying its
        fill. Chains that already exist are touched, not re-pinned —
        the sequence's own duplicate blocks then free normally. Returns
        the number of blocks newly pinned."""
        toks = [int(t) for t in tokens]
        bs = self.block_size
        pinned = 0
        with self._lock:
            self._clock += 1
            self._inserted_runs += 1
            root = self._roots.get(lane)
            if root is None:
                self._nid += 1
                root = self._roots[lane] = _Node(
                    self._nid, lane, None, (), 0, None, False)
            cur = root
            full = len(toks) // bs
            for i in range(min(full, len(blocks))):
                bt = tuple(toks[i * bs:(i + 1) * bs])
                child = cur.children.get(bt)
                if child is None:
                    self.pool.share_blocks([blocks[i]])
                    self._nid += 1
                    child = _Node(self._nid, lane, int(blocks[i]), bt,
                                  bs, cur, False)
                    cur.children[bt] = child
                    self._nodes += 1
                    pinned += 1
                child.last_used = self._clock
                cur = child
            fill = len(toks) % bs
            if fill and full < len(blocks):
                pt = tuple(toks[full * bs:])
                pnode = cur.partials.get(pt)
                if pnode is None:
                    self.pool.share_blocks([blocks[full]])
                    self._nid += 1
                    pnode = _Node(self._nid, lane, int(blocks[full]), pt,
                                  fill, cur, True)
                    cur.partials[pt] = pnode
                    self._nodes += 1
                    pinned += 1
                pnode.last_used = self._clock
            if self.capacity_blocks is not None \
                    and self._nodes > self.capacity_blocks:
                self._evict_locked(self._nodes - self.capacity_blocks)
        self._publish()
        return pinned

    def note_cow(self, n: int = 1) -> None:
        """Account ``n`` copy-on-write block duplications (the
        scheduler performs the device copy; the cache owns the
        metric)."""
        with self._lock:
            self._cow_copies += int(n)
        get_registry().counter(
            PREFIXCACHE_COW_COPIES_COUNTER,
            "Copy-on-write KV block duplications (a writer's shared "
            "partial tail block copied before its scatter landed)",
            pool=self.pool.name).inc(int(n))
        self._publish()

    # --------------------------------------------------------- eviction

    def _promote_locked(self, node: _Node) -> bool:
        """Swap a host-resident node's block back onto the device so a
        match can share it. False (node untouched, handle still valid)
        when the device pool cannot cover it even after reclaim."""
        got = self.pool.swap_in([node.host])
        if got is None:
            return False
        node.block = int(got[0])
        node.host = None
        self._host_nodes -= 1
        self._nodes += 1
        return True

    def _pick_victim_locked(self) -> Optional[_Node]:
        """Deterministic LRU victim: the device-resident node with NO
        device-resident descendant (so the on-device radix chain never
        dangles — host-resident children ride along) whose only
        reference is the cache's; ties break on node id."""
        victim: Optional[_Node] = None

        def walk(node: _Node) -> bool:
            nonlocal victim
            has_dev = False
            for ch in list(node.children.values()) \
                    + list(node.partials.values()):
                has_dev |= walk(ch)
            if node.block is None:
                return has_dev
            if not has_dev and self.pool.ref_count(node.block) == 1 \
                    and (victim is None or (node.last_used, node.nid)
                         < (victim.last_used, victim.nid)):
                victim = node
            return True

        for root in self._roots.values():
            for ch in list(root.children.values()) \
                    + list(root.partials.values()):
                walk(ch)
        return victim

    def reclaim_demote(self, n: int) -> int:
        """First rung of the exhaustion ladder: demote up to ``n``
        cached-but-unreferenced blocks to the HOST tier (contents
        preserved; the node stays in the radix tree and is matchable —
        a later match swaps it back in). No-ops when the pool has no
        host tier or its budget is full, letting the drop rung run."""
        if not getattr(self.pool, "host_enabled", False):
            return 0
        with self._lock:
            demoted = self._demote_locked(int(n))
        self._publish()
        return demoted

    def _demote_locked(self, n: int) -> int:
        demoted = 0
        while demoted < n:
            victim = self._pick_victim_locked()
            if victim is None:
                break
            handles = self.pool.swap_out([victim.block])
            if handles is None:
                break  # host budget exhausted: the drop rung is next
            victim.block = None
            victim.host = handles[0]
            self._nodes -= 1
            self._host_nodes += 1
            self._demotions += 1
            demoted += 1
        if demoted:
            reg = get_registry()
            reg.counter(
                PREFIXCACHE_DEMOTIONS_COUNTER,
                "Cached-but-unreferenced KV blocks demoted to the host "
                "tier instead of dropped (contents preserved)",
                pool=self.pool.name).inc(demoted)
            reg.counter(
                KVTIER_DEMOTIONS_COUNTER,
                "KV blocks demoted device→host by exhaustion pressure "
                "(the reclaimer chain's first rung)",
                pool=self.pool.name).inc(demoted)
        return demoted

    def reclaim(self, n: int) -> int:
        """The pool's reclaimer seam: evict up to ``n`` cached blocks
        whose ONLY reference is the cache's (deterministic LRU —
        logical clock, node-id tie-break, leaves first so the radix
        chain never dangles). Returns how many blocks were freed."""
        with self._lock:
            freed = self._evict_locked(int(n))
        self._publish()
        return freed

    def _drop_hosts_locked(self, node: _Node) -> None:
        stack = list(node.children.values()) + list(node.partials.values())
        node.children.clear()
        node.partials.clear()
        while stack:
            ch = stack.pop()
            stack.extend(ch.children.values())
            stack.extend(ch.partials.values())
            if ch.host is not None:
                self.pool.free_host([ch.host])
                self._host_nodes -= 1

    def _evict_locked(self, n: int) -> int:
        freed = 0
        while freed < n:
            victim = self._pick_victim_locked()
            if victim is None:
                break  # everything left is referenced or interior
            parent = victim.parent
            if victim.partial:
                parent.partials.pop(victim.pkey, None)
            else:
                parent.children.pop(victim.pkey, None)
            # host-resident descendants leave the tree with the victim
            # — their handles free, or they would leak the host budget
            self._drop_hosts_locked(victim)
            self._nodes -= 1
            self._evictions += 1
            self.pool.free_blocks([victim.block])
            freed += 1
        if freed:
            get_registry().counter(
                PREFIXCACHE_EVICTIONS_COUNTER,
                "Cached-but-unreferenced KV blocks evicted back to the "
                "pool free list", pool=self.pool.name).inc(freed)
        return freed

    def clear(self) -> int:
        """Release every cache-held block reference (drain-time
        accounting audits call this: after ``clear()`` a quiesced
        pool's free count must equal its total). Returns the number of
        blocks released."""
        with self._lock:
            released = 0
            for root in self._roots.values():
                stack = list(root.children.values()) \
                    + list(root.partials.values())
                while stack:
                    node = stack.pop()
                    stack.extend(node.children.values())
                    stack.extend(node.partials.values())
                    if node.host is not None:
                        self.pool.free_host([node.host])
                    else:
                        self.pool.free_blocks([node.block])
                    released += 1
            self._roots.clear()
            self._nodes = 0
            self._host_nodes = 0
        self._publish()
        return released

    # ------------------------------------------------------------ state

    def cached_blocks(self) -> int:
        with self._lock:
            return self._nodes

    def stats(self) -> Dict[str, float]:
        with self._lock:
            hits, misses = self._hits, self._misses
            out = {
                "cached_blocks": self._nodes,
                "cached_bytes": self._nodes * self.pool.block_bytes(),
                "hits": hits,
                "misses": misses,
                "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
                "evictions": self._evictions,
                "demotions": self._demotions,
                "host_blocks": self._host_nodes,
                "cow_copies": self._cow_copies,
                "saved_prefill_tokens": self._saved_tokens,
                "inserted_runs": self._inserted_runs,
                "capacity_blocks": self.capacity_blocks,
            }
        out["shared_blocks"] = self.pool.shared_count()
        return out

    def _publish(self) -> None:
        reg = get_registry()
        with self._lock:
            nodes = self._nodes
        reg.gauge(PREFIXCACHE_CACHED_BLOCKS_GAUGE,
                  "KV blocks currently pinned by the prefix cache",
                  pool=self.pool.name).set(nodes)
        reg.gauge(PREFIXCACHE_SHARED_BLOCKS_GAUGE,
                  "KV blocks currently referenced by more than one "
                  "holder (live prefix sharing)",
                  pool=self.pool.name).set(self.pool.shared_count())
