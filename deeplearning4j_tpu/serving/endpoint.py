"""Engine endpoints — the units the router dispatches over.

An :class:`EngineEndpoint` is one serving engine the
:class:`~deeplearning4j_tpu.serving.router.InferenceRouter` can send
classify / generate requests to:

- :class:`LocalEndpoint` wraps an in-process
  :class:`~deeplearning4j_tpu.parallel.inference.ParallelInference`
  (stats are live, liveness is trivially the engine being up);
- :class:`RemoteEndpoint` reaches an engine process behind a
  ``MessageBroker`` request/reply channel (``serving/wire.py`` frames
  with correlation ids) and tracks health from its heartbeat stream —
  a worker that stops heartbeating is *dead*, positively, without a
  single request having to time out first.

Both expose the same surface: ``submit`` / ``submit_generate``
returning Futures, ``stats()`` (latest engine snapshot), ``alive()``
and ``last_seen`` for the health plane. Remote futures that outlive
``request_timeout_s`` fail with :class:`EndpointTimeout` — the router
treats that exactly like an endpoint error and fails over, so a killed
engine process never strands a caller's future.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu.monitor import reqtrace
from deeplearning4j_tpu.serving import wire
from deeplearning4j_tpu.streaming.broker import MessageBroker

logger = logging.getLogger("deeplearning4j_tpu")


class EndpointError(RuntimeError):
    """A request failed on the endpoint (engine error reply, transport
    death, or endpoint shutdown)."""


class EndpointTimeout(EndpointError):
    """No reply within the endpoint's ``request_timeout_s`` — the
    worker is gone or wedged; the router fails the request over."""


class EngineEndpoint:
    """SPI one serving engine presents to the router. ``model=`` /
    ``version=`` / ``session=`` route multi-model engines; a
    single-model engine ignores them (None)."""

    name: str

    def submit(self, x: np.ndarray,
               timeout_s: Optional[float] = None,
               model: Optional[str] = None,
               version: Optional[int] = None,
               session: Optional[str] = None) -> "Future[np.ndarray]":
        raise NotImplementedError

    def submit_generate(self, prompt_ids: np.ndarray, max_new_tokens: int,
                        timeout_s: Optional[float] = None,
                        model: Optional[str] = None,
                        version: Optional[int] = None,
                        session: Optional[str] = None,
                        on_tokens=None,
                        prefix: Optional[np.ndarray] = None,
                        **kwargs) -> "Future[np.ndarray]":
        """``on_tokens(offset, tokens)`` streams incremental decode
        chunks (wire v2); ``prefix`` resumes a migrated stream from
        prompt + already-delivered tokens. Both optional — a plain
        endpoint serves whole replies."""
        raise NotImplementedError

    def submit_prefill(self, prompt_ids: np.ndarray,
                       timeout_s: Optional[float] = None
                       ) -> "Future[Dict[str, Any]]":
        """Disaggregated prefill (wire v3): compute the prompt's KV and
        last-token logits on THIS endpoint and resolve to the
        ``{"kv", "logits", "t_in"}`` state a decode endpoint admits the
        session from (``submit_generate(kv_state=...)``)."""
        raise NotImplementedError

    def stats(self) -> Dict[str, Any]:
        """Latest known engine ``stats()`` snapshot (may be stale for a
        remote endpoint — ``last_seen`` dates it)."""
        raise NotImplementedError

    def alive(self) -> bool:
        raise NotImplementedError

    @property
    def last_seen(self) -> float:
        """Monotonic timestamp of the endpoint's last proof of life."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class LocalEndpoint(EngineEndpoint):
    """An in-process ``ParallelInference`` as a fleet endpoint."""

    def __init__(self, engine, name: str = "local"):
        self.engine = engine
        self.name = name

    def submit(self, x, timeout_s=None, model=None, version=None,
               session=None):
        kw = {k: v for k, v in (("model", model), ("version", version),
                                ("session", session)) if v is not None}
        return self.engine.submit(x, **kw)

    def submit_generate(self, prompt_ids, max_new_tokens,
                        timeout_s=None, model=None, version=None,
                        session=None, on_tokens=None, prefix=None,
                        kv_state=None, hibernate=False,
                        on_hibernate=None, **kwargs):
        kw = {k: v for k, v in (("model", model), ("version", version),
                                ("session", session),
                                ("on_tokens", on_tokens),
                                ("prefix", prefix),
                                ("kv_state", kv_state)) if v is not None}
        if hibernate:
            kw["hibernate"] = True
        fut = self.engine.submit_generate(prompt_ids, max_new_tokens,
                                          **kw, **kwargs)
        if hibernate and on_hibernate is not None and session is not None:
            # mirror the wire contract: the durable handle reaches the
            # router when the turn retires, so the session survives
            # even an in-process engine being shut down
            def _ship(f):
                if f.exception() is not None:
                    return
                try:
                    hp = self.engine.hibernate_export(session)
                except BaseException:
                    return
                if hp is not None:
                    try:
                        on_hibernate(hp)
                    except BaseException:
                        pass  # consumer bug; the turn already resolved
            fut.add_done_callback(_ship)
        return fut

    def submit_prefill(self, prompt_ids, timeout_s=None):
        fut: "Future[Dict[str, Any]]" = Future()
        try:
            fut.set_result(self.engine.prefill_export(prompt_ids))
        except BaseException as e:
            fut.set_exception(e)
        return fut

    def stats(self):
        return self.engine.stats()

    def alive(self):
        return not self.engine._closed

    @property
    def last_seen(self) -> float:
        return time.monotonic()  # in-process: always fresh

    def close(self):
        self.engine.shutdown()


class _Pending:
    __slots__ = ("future", "deadline", "timeout", "on_tokens", "tensors",
                 "on_hibernate")

    def __init__(self, future: Future, deadline: float, timeout: float,
                 on_tokens=None, tensors=None, on_hibernate=None):
        self.future = future
        self.deadline = deadline
        self.timeout = timeout   # per-chunk silence budget (streams)
        self.on_tokens = on_tokens
        # tagged tensor chunks assembled so far (wire v3 prefill: the
        # "kv" chunk lands here, the terminal reply completes the dict)
        self.tensors = tensors
        # receives the durable hibernation handle a hibernate=True turn
        # ships before its terminal reply
        self.on_hibernate = on_hibernate


class RemoteEndpoint(EngineEndpoint):
    """A broker-reached engine worker as a fleet endpoint.

    ``broker`` carries this endpoint's publishes; the reply and
    heartbeat consumers each get their own connection via
    ``broker_factory`` when given (recommended for ``TcpBroker``, whose
    long-poll holds the connection lock), else they share ``broker``
    (fine for ``InMemoryBroker``).

    The reply consumer matches replies to futures by correlation id
    and sweeps expired entries every poll — a pending future ALWAYS
    resolves: with the reply, with the worker's error, or with
    :class:`EndpointTimeout` after ``request_timeout_s``.
    """

    def __init__(self, broker: MessageBroker, service: str,
                 name: Optional[str] = None,
                 broker_factory=None,
                 request_timeout_s: float = 10.0,
                 heartbeat_timeout_s: float = 2.0,
                 poll_s: float = 0.05,
                 wire_version: int = wire.WIRE_VERSION):
        """``wire_version`` pins the wire ceiling this endpoint SPEAKS
        (the rolling-upgrade test seam: pin 3 and the endpoint encodes
        every request exactly like a pre-v4 router build would). The
        EFFECTIVE framing per request is ``min(ours, peer's)`` where the
        peer's ceiling arrives on its heartbeats (``wire`` field; absent
        = pre-v4 = 3) — before the first heartbeat the endpoint stays
        conservatively legacy, so a rolling upgrade never sends a v4
        frame to a worker that cannot serve it."""
        self.name = name or service
        self.service = service
        self.request_timeout = float(request_timeout_s)
        self.heartbeat_timeout = float(heartbeat_timeout_s)
        self.wire_version = int(wire_version)
        self._peer_wire: Optional[int] = None
        self._poll = float(poll_s)
        self._broker = broker
        self._reply_broker = broker_factory() if broker_factory else broker
        self._hb_broker = broker_factory() if broker_factory else broker
        self.reply_topic = f"{service}.rsp.{uuid.uuid4().hex[:12]}"
        self._pending: Dict[str, _Pending] = {}
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._closed = False
        self._hb: Dict[str, Any] = {}
        self._hb_at: Optional[float] = None
        self._threads = [
            threading.Thread(target=self._reply_loop, daemon=True,
                             name=f"dl4j-tpu-ep-{self.name}-rsp"),
            threading.Thread(target=self._hb_loop, daemon=True,
                             name=f"dl4j-tpu-ep-{self.name}-hb"),
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------ submit

    def negotiated_wire(self) -> int:
        """The wire version this endpoint may SEND: ``min`` of its own
        ceiling and the peer's advertised one (3 until a heartbeat
        proves better — conservative through a rolling upgrade)."""
        peer = self._peer_wire if self._peer_wire is not None else 3
        return min(self.wire_version, peer)

    def _submit_frame(self, kind: str, x: np.ndarray,
                      gen: Optional[Dict[str, Any]],
                      timeout_s: Optional[float],
                      model: Optional[str] = None,
                      version: Optional[int] = None,
                      session: Optional[str] = None,
                      on_tokens=None,
                      tensors=None,
                      send_tensors=None,
                      wire_v: Optional[int] = None,
                      on_hibernate=None) -> "Future[np.ndarray]":
        """``tensors`` is the INBOUND assembly dict (tagged chunks land
        there — prefill kv); ``send_tensors`` are OUTBOUND extra tensor
        segments, only meaningful when the negotiated framing is v4."""
        if self._closed:
            raise EndpointError(f"endpoint {self.name} is closed")
        corr = f"{self.name}-{next(self._ids)}"
        fut: "Future[np.ndarray]" = Future()
        timeout = (timeout_s if timeout_s is not None
                   else self.request_timeout)
        deadline = time.monotonic() + timeout
        with self._lock:
            self._pending[corr] = _Pending(fut, deadline, timeout, on_tokens,
                                           tensors, on_hibernate)
        # propagate the caller's request-trace context across the wire
        # (thread-local → optional header field; older workers ignore it)
        tctx = reqtrace.current_trace()
        neg = self.negotiated_wire() if wire_v is None else int(wire_v)
        trace = None if tctx is None else tctx.wire()
        if neg >= 4:
            payload = wire.pack_request_v4(
                corr, self.reply_topic, kind, x, gen, model=model,
                version=version, session=session, trace=trace,
                tensors=send_tensors)
        else:
            payload = wire.pack_request(
                corr, self.reply_topic, kind, x, gen, model=model,
                version=version, session=session, trace=trace,
                wire_v=neg)
        try:
            self._broker.publish(self.service + wire.REQ_SUFFIX, payload)
        except BaseException as e:
            with self._lock:
                self._pending.pop(corr, None)
            fut.set_exception(EndpointError(
                f"publish to {self.name} failed: {type(e).__name__}: {e}"))
        return fut

    def submit(self, x, timeout_s=None, model=None, version=None,
               session=None):
        return self._submit_frame(wire.KIND_CLASSIFY, np.asarray(x), None,
                                  timeout_s, model, version, session)

    def submit_generate(self, prompt_ids, max_new_tokens, timeout_s=None,
                        temperature: float = 0.0, top_k: int = 0,
                        top_p: float = 0.0, eos_token: Optional[int] = None,
                        seed: int = 0, model=None, version=None,
                        session=None, on_tokens=None, prefix=None,
                        kv_state=None, hibernate=False, on_hibernate=None):
        gen = {"max_new": int(max_new_tokens), "temperature": temperature,
               "top_k": top_k, "top_p": top_p, "eos_token": eos_token,
               "seed": seed}
        if on_tokens is not None:
            # wire v2: ask the worker for chunked token deltas; each
            # chunk also refreshes this request's silence deadline, so
            # a long stream never times out WHILE it is progressing
            gen["stream"] = True
        neg = self.negotiated_wire()
        if hibernate:
            gen["hibernate"] = True
        send_tensors: Optional[Dict[str, np.ndarray]] = None
        body = np.asarray(prompt_ids)
        if isinstance(kv_state, dict) and "blocks" in kv_state:
            # shipped hibernation payload (cross-endpoint resume): the
            # host-tier blocks ride raw v4 segments back to the target
            # worker; a v3 peer cannot carry them — drop the payload
            # and let the prefix resume re-prefill (still exact, just
            # the journal rung instead of swap-in)
            if neg >= 4:
                hib, hsegs = wire.hibernation_segments(kv_state)
                gen["hib"] = hib
                send_tensors = dict(hsegs)
            kv_state = None
        if prefix is not None:
            if neg >= 4:
                # v4: the resume prefix is a raw binary segment
                send_tensors = dict(send_tensors or {})
                send_tensors["prefix"] = np.asarray(prefix, np.int64)
            else:
                # resume request: the worker re-prefills prompt + prefix
                # and continues the stream's PRNG clock (no
                # re-generation of delivered tokens, no re-emission of
                # their offsets)
                gen["prefix"] = [int(t) for t in
                                 np.asarray(prefix).reshape(-1)]
        if kv_state is not None:
            gen["kv"] = True
            if neg >= 4:
                # v4 handoff: prompt stays the x segment; the shipped
                # KV and logits ride raw segments — byte-exact by
                # construction, no npz container, no JSON float lists
                body = np.asarray(prompt_ids, np.int32).reshape(1, -1)
                send_tensors = dict(send_tensors or {})
                send_tensors["kv"] = np.asarray(kv_state["kv"])
                send_tensors["logits"] = np.asarray(
                    kv_state["logits"], np.float32).reshape(1, -1)
            else:
                # v3 handoff: the shipped KV tensor IS the frame body;
                # the (small) prompt ids and last-token logits ride the
                # header (json floats round-trip f32 exactly — the
                # handoff stays bit-exact across the wire)
                gen["prompt"] = [int(t) for t in
                                 np.asarray(prompt_ids).reshape(-1)]
                gen["logits"] = [float(v) for v in
                                 np.asarray(kv_state["logits"]).reshape(-1)]
                body = np.asarray(kv_state["kv"])
        return self._submit_frame(wire.KIND_GENERATE,
                                  body, gen, timeout_s,
                                  model, version, session, on_tokens,
                                  send_tensors=send_tensors, wire_v=neg,
                                  on_hibernate=on_hibernate)

    def submit_prefill(self, prompt_ids, timeout_s=None):
        """Wire-v3 disaggregated prefill: the worker replies with one
        tagged ``kv`` tensor chunk then the terminal logits frame; the
        future resolves to the assembled ``{"kv", "logits", "t_in"}``
        handoff state."""
        prompt = np.asarray(prompt_ids)
        return self._submit_frame(
            wire.KIND_PREFILL, prompt, None, timeout_s,
            tensors={"t_in": int(prompt.shape[-1])})

    # ----------------------------------------------------------- health

    def stats(self):
        with self._lock:
            return dict(self._hb.get("stats") or {})

    def state(self) -> Optional[str]:
        with self._lock:
            return self._hb.get("state")

    def alive(self) -> bool:
        with self._lock:
            hb_at, state = self._hb_at, self._hb.get("state")
        if hb_at is None or state == wire.STATE_STOPPED:
            return False
        return time.monotonic() - hb_at < self.heartbeat_timeout

    @property
    def last_seen(self) -> float:
        with self._lock:
            return self._hb_at if self._hb_at is not None else float("-inf")

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    # ------------------------------------------------------------ loops

    def _reply_loop(self):
        while not self._closed:
            try:
                msg = self._reply_broker.consume(self.reply_topic,
                                                 timeout=self._poll)
            except BaseException:
                if self._closed:
                    return
                msg = None
            if msg is not None:
                try:
                    # framing-agnostic: one legacy frame is one event; a
                    # coalesced v4 burst frame fans out into several
                    events = wire.decode_reply_events(msg)
                except wire.WireFrameError as e:
                    logger.warning(
                        "endpoint %s: damaged v4 frame rejected "
                        "(WireFrameError: %s)", self.name, e)
                    continue
                except Exception as e:
                    logger.warning("endpoint %s: undecodable reply (%s)",
                                   self.name, e)
                    continue
                for ev in events:
                    self._handle_event(ev)
            self._sweep_expired()

    def _handle_event(self, ev: Dict[str, Any]) -> None:
        kind = ev["type"]
        if kind == "hibernation":
            # the session's durable handle, shipped before the terminal
            # reply: hand it up (the router parks it) and refresh the
            # silence deadline — the frame is proof of progress
            with self._lock:
                p = self._pending.get(ev.get("id"))
                if p is not None:
                    self._hb_at = time.monotonic()
                    p.deadline = time.monotonic() + p.timeout
            if p is not None and p.on_hibernate is not None:
                try:
                    p.on_hibernate(ev["payload"])
                except BaseException as e:
                    logger.warning(
                        "endpoint %s: on_hibernate callback failed "
                        "(%s: %s)", self.name, type(e).__name__, e)
            return
        if kind == "tensor":
            # tagged tensor chunk (prefill kv): assemble WITHOUT
            # resolving, refresh the silence deadline
            with self._lock:
                p = self._pending.get(ev.get("id"))
                if p is not None:
                    self._hb_at = time.monotonic()
                    p.deadline = time.monotonic() + p.timeout
                    if p.tensors is not None and ev.get("tensor") is not None:
                        p.tensors[ev["tag"]] = ev["tensor"]
            return
        if kind == "chunk":
            # incremental decode chunk: deliver WITHOUT resolving the
            # future, and refresh the request's silence deadline —
            # visible progress is proof the stream is alive, so only a
            # stalled stream can time out. A chunk for an already-swept
            # request is dropped here (the caller migrated past it).
            with self._lock:
                p = self._pending.get(ev.get("id"))
                if p is not None:
                    self._hb_at = time.monotonic()
                    p.deadline = time.monotonic() + p.timeout
            if p is not None and p.on_tokens is not None \
                    and ev.get("tokens") is not None:
                try:
                    p.on_tokens(int(ev.get("off", 0)), ev["tokens"])
                except BaseException as e:
                    logger.warning(
                        "endpoint %s: on_tokens callback failed "
                        "(%s: %s)", self.name, type(e).__name__, e)
            return
        header, result = ev["header"], ev["result"]
        with self._lock:
            p = self._pending.pop(ev.get("id"), None)
            if p is not None:
                self._hb_at = time.monotonic()  # proof of life
        if p is not None and not p.future.done():
            if header.get("ok"):
                if p.tensors is not None:
                    # prefill reply: terminal logits complete the
                    # assembled handoff state
                    p.future.set_result(dict(p.tensors, logits=result))
                else:
                    p.future.set_result(result)
            elif header.get("etype"):
                # typed engine error: reconstruct the SAME exception
                # class a LocalEndpoint would raise (shed / quarantine
                # isolation contract)
                p.future.set_exception(wire.typed_error(
                    header, fallback=EndpointError))
            else:
                p.future.set_exception(EndpointError(
                    f"{self.name}: {header.get('error')}"))

    def _sweep_expired(self):
        now = time.monotonic()
        expired = []
        with self._lock:
            for corr, p in list(self._pending.items()):
                if now >= p.deadline:
                    expired.append(self._pending.pop(corr))
        for p in expired:
            if not p.future.done():
                p.future.set_exception(EndpointTimeout(
                    f"no reply from {self.name} within "
                    f"{self.request_timeout}s"))

    def _hb_loop(self):
        topic = self.service + wire.HB_SUFFIX
        while not self._closed:
            try:
                msg = self._hb_broker.consume(topic, timeout=self._poll)
            except BaseException:
                if self._closed:
                    return
                msg = None
            if msg is None:
                continue
            try:
                hb = wire.unpack_heartbeat(msg)
            except Exception:
                continue
            with self._lock:
                # seq guards against out-of-order delivery after a
                # worker restart resets the counter: accept resets too
                if (not self._hb or hb.get("seq", 0) >= self._hb.get("seq", 0)
                        or hb.get("state") == wire.STATE_SERVING):
                    self._hb = hb
                    # negotiation: the peer's wire ceiling rides its
                    # heartbeats (absent = a pre-v4 build = 3)
                    self._peer_wire = int(hb.get("wire", 3))
                self._hb_at = time.monotonic()

    def close(self):
        self._closed = True
        err = EndpointError(f"endpoint {self.name} closed")
        with self._lock:
            pending, self._pending = list(self._pending.values()), {}
        for p in pending:
            if not p.future.done():
                p.future.set_exception(err)
        for t in self._threads:
            t.join(timeout=2)
