"""Horizontal serving tier: router, fleet, SLO-aware admission.

PRs 3–5 built ONE fault-tolerant serving engine; this package is the
tier above it — the difference between "a serving engine" and "a
serving system" (ROADMAP item 5): an
:class:`~deeplearning4j_tpu.serving.router.InferenceRouter` dispatches
over a fleet of engine endpoints (in-process
:class:`~deeplearning4j_tpu.serving.endpoint.LocalEndpoint` or
broker-reached :class:`~deeplearning4j_tpu.serving.endpoint.
RemoteEndpoint` / :class:`~deeplearning4j_tpu.serving.worker.
EngineWorker` pairs), with heartbeat health, outlier ejection +
half-open reinstatement, failover/hedging, deadline-aware admission
control (:class:`~deeplearning4j_tpu.serving.router.RetryAfter`
sheds), decode session affinity, and
:class:`~deeplearning4j_tpu.serving.policy.ScalePolicy`-driven
autoscaling applied by :class:`~deeplearning4j_tpu.serving.fleet.
LocalFleet`.

Decode streams are DURABLE: ``submit_generate(on_tokens=...)`` streams
wire-v2 token chunks, the router journals them per stream, and an
engine death mid-generation migrates the stream (re-pin + resume from
prompt + journaled prefix) with append-only delivery — no lost, no
duplicated token, output equal to an uninterrupted run.

Prompts are CACHED across requests: with ``prefix_cache=True`` the
continuous scheduler indexes retired sequences' KV blocks in a
:class:`~deeplearning4j_tpu.serving.prefixcache.PrefixCache` radix
tree (per model-version lane, copy-on-write shared blocks,
deterministic LRU eviction unified with the pool free list), so an
admitted prompt clones its longest matched prefix's block table and
prefills only the tail — bitwise-identical output at a fraction of
the prefill FLOPs, and warm-cache migrations degrade to a table clone.
"""

from deeplearning4j_tpu.serving.continuous import (  # noqa: F401
    ContinuousDecodeScheduler,
    DecodeBurstError,
    KVPoolExhausted,
)
from deeplearning4j_tpu.serving.endpoint import (  # noqa: F401
    EndpointError,
    EndpointTimeout,
    EngineEndpoint,
    LocalEndpoint,
    RemoteEndpoint,
)
from deeplearning4j_tpu.serving.fleet import LocalFleet  # noqa: F401
from deeplearning4j_tpu.serving.registry import (  # noqa: F401
    ModelQuarantined,
    ModelRegistry,
    ModelUnavailable,
    ModelVersion,
)
from deeplearning4j_tpu.serving.policy import (  # noqa: F401
    ScaleDecision,
    ScalePolicy,
)
from deeplearning4j_tpu.serving.prefixcache import PrefixCache  # noqa: F401
from deeplearning4j_tpu.serving.router import (  # noqa: F401
    InferenceRouter,
    RetryAfter,
)
from deeplearning4j_tpu.serving.wire import (  # noqa: F401
    WIRE_VERSION,
    WireFrameError,
    WireVersionError,
)
from deeplearning4j_tpu.serving.worker import EngineWorker  # noqa: F401
