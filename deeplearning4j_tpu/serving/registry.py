"""ModelRegistry — N models × versions behind one serving engine, with
a crash-safe, zero-downtime lifecycle.

Production traffic is many models (zoo variants, fine-tunes, A/B arms —
the DL4J ModelZoo / TransferLearning shape), not one, and the hard part
is the robustness contract, the model-lifecycle discipline of
TF-Serving's version manager and Clipper's model-container isolation:

- **Registry**: named models with integer-versioned params. A version
  is backed by a live net, a ``util/model_serializer`` zip, or a PR-4
  ``sharded_checkpoint`` unit — checkpoint-backed versions load lazily
  and can be dropped from host memory under pressure and reloaded on
  demand, so the registry can hold more models than fit at once.
- **Device-memory budget** with LRU/priority eviction: parameters are
  pinned per device on first dispatch and accounted by size; when a pin
  would exceed ``memory_budget_bytes`` the least-recently-used,
  lowest-priority pins are evicted (``dl4j_model_evictions_total``).
  An evicted checkpoint-backed version reloads lazily from disk.
- **Zero-downtime deploy**: :meth:`deploy` integrity-checks the new
  version FIRST (``verify_model_file`` — a
  :class:`~deeplearning4j_tpu.util.model_serializer.
  CheckpointCorruptError` rejects the deploy while the old version
  keeps serving), AOT-warms it off the hot path on every replica, then
  atomically cuts over: requests resolved after the swap get the new
  version, in-flight ones finish on the version they resolved.
  :meth:`rollback` is instant — prior versions are retained
  (``keep_versions``), exactly the ``ckpt-<step>`` history discipline.
- **Canary**: ``deploy(..., canary_fraction=f)`` keeps the old version
  active and routes a deterministic ``f`` of traffic to the new one;
  the watch plane (the PR-4 supervisor/watchdog discipline applied to
  versions) auto-rolls-back on error-rate, NaN-output, or p99
  regression against the stable version; :meth:`promote` cuts over.
- **Isolation**: a per-model circuit breaker. A model whose dispatches
  fault on more than one replica is *model*-poisoned, not
  replica-poisoned — the breaker opens
  (``dl4j_model_breaker_open{model=...}``), its submits fail fast with
  :class:`ModelQuarantined`, and the engine probes it with a known-good
  one-row dispatch until it heals — cotenant models never stop
  serving and no replica is taken out for a model's own fault.

The registry itself never dispatches; the multi-model
:class:`~deeplearning4j_tpu.parallel.inference.ParallelInference`
(``registry=`` mode) resolves versions at submit time, pins params
through :meth:`acquire` inside its workers, and reports outcomes back
through :meth:`note_result` / :meth:`note_error`.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitor import (
    MODEL_ACTIVE_VERSION_GAUGE,
    MODEL_BREAKER_OPEN_GAUGE,
    MODEL_DEPLOYS_COUNTER,
    MODEL_ERRORS_COUNTER,
    MODEL_EVICTIONS_COUNTER,
    MODEL_LATENCY_HISTOGRAM,
    MODEL_PINNED_BYTES_GAUGE,
    MODEL_REQUESTS_COUNTER,
    MODEL_ROLLBACKS_COUNTER,
    get_registry,
    mark,
    record_fault,
)
from deeplearning4j_tpu.util.model_serializer import (CheckpointCorruptError,
                                                      restore_model,
                                                      verify_model_file)


class ModelUnavailable(RuntimeError):
    """The named model (or version) cannot serve: unknown, retired, or
    its parameters are gone and cannot be reloaded."""


class ModelQuarantined(ModelUnavailable):
    """The model's circuit breaker is open: its recent dispatches
    faulted across replicas, so it is isolated from the serving pool
    (cotenant models keep serving) until a probe heals it."""


class QualityGateFailed(RuntimeError):
    """A deploy's ``quality_gate`` (the nn/quantize.py accuracy-delta
    harness, or any ``(stable_net, new_net) -> verdict`` callable)
    measured the candidate outside its quality bound: the deploy is
    rejected BEFORE any traffic shifts — the stable version never
    stopped serving (the canary auto-rollback discipline, applied at
    deploy time with a measured verdict). ``verdict`` carries the
    harness numbers."""

    def __init__(self, msg: str, verdict=None):
        super().__init__(msg)
        self.verdict = verdict


# version lifecycle states
STATE_STAGED = "staged"      # loaded + warmed, not yet taking traffic
STATE_ACTIVE = "active"      # the version new requests resolve to
STATE_CANARY = "canary"      # taking canary_fraction of traffic
STATE_RETIRED = "retired"    # superseded; retained for rollback
STATE_REJECTED = "rejected"  # failed deploy/canary; never serves again


def _tree_nbytes(tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        total += int(np.asarray(leaf).nbytes) if not hasattr(leaf, "nbytes") \
            else int(leaf.nbytes)
    return total


class ModelVersion:
    """One (model, version) — its params source, lazily-built programs,
    per-device pins, and the per-version serving stats the canary watch
    consumes."""

    def __init__(self, name: str, version: int, net=None,
                 path: Optional[str] = None, draft=None):
        if net is None and path is None:
            raise ValueError("a version needs a net or a checkpoint path")
        self.name = name
        self.version = int(version)
        self.path = path
        self.state = STATE_STAGED
        self.warmed = False
        self._net = net
        # draft/target pairing for speculative decoding: a net, or the
        # "self" sentinel (int8 self-speculation — quantize(net) built
        # lazily on first draft() call), or None (unpaired; a
        # speculative scheduler then self-quantizes on its own).  The
        # pairing is a VERSION attribute: session pins and canary
        # routing resolve the version first, so a mid-stream cutover
        # can never switch a stream's draft out from under it.
        self._draft_src = draft
        self._draft_net = None
        # quality-gate verdict persisted at deploy time (satellite of
        # PR 17): accuracy_gate's greedy_match_rate doubles as the
        # speculation acceptance-rate prior surfaced in stats().
        self.quality: Optional[Dict[str, Any]] = None
        self._lock = threading.Lock()
        self._nbytes: Optional[int] = None
        # devkey -> (params, states); managed under the REGISTRY lock
        self.pins: Dict[str, Tuple[Any, Any]] = {}
        # devkey -> bytes actually charged for that pin (the REAL
        # nbytes of the pinned params+states pytree — an int8-quantized
        # version charges its int8 footprint, not an assumed-fp32 one)
        self.pin_bytes: Dict[str, int] = {}
        self.last_used = 0.0  # registry LRU tick
        # serving stats (under the registry lock)
        self.requests = 0
        self.errors = 0
        self.nans = 0
        self.ewma_ms: Optional[float] = None
        self.latencies: deque = deque(maxlen=256)

    # ------------------------------------------------------------- load

    def net(self):
        """The live net, loading (and integrity-checking) from the
        checkpoint path when the host copy was dropped or never built."""
        with self._lock:
            if self._net is None:
                self._net = self._load()
            if self._net.params is None:
                self._net.init()
            return self._net

    def _load(self):
        if self.path is None:
            raise ModelUnavailable(
                f"{self.name} v{self.version}: parameters were dropped and "
                "there is no checkpoint path to reload from")
        if os.path.isdir(self.path):
            from deeplearning4j_tpu.util.sharded_checkpoint import (
                restore_checkpoint, verify_checkpoint)
            problems = verify_checkpoint(self.path)
            if problems:
                raise CheckpointCorruptError("; ".join(problems))
            return restore_checkpoint(self.path)
        return restore_model(self.path)  # verify_model_file runs inside

    def drop_host(self) -> bool:
        """Release the host copy (evicted past the device pins); only
        checkpoint-backed versions can — others must keep their params.
        Returns True when dropped."""
        with self._lock:
            if self.path is None:
                return False
            self._net = None
            return True

    # ---------------------------------------------------------- derived

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.net()._dtype)

    def nbytes(self) -> int:
        if self._nbytes is None:
            self._nbytes = _tree_nbytes(self.net().params)
        return self._nbytes

    def fn(self):
        """The version's jit-cached batched output program (each
        version owns its net, so jit caches never mix versions)."""
        return self.net().infer_output_fn()

    def generator(self):
        net = self.net()
        gen = getattr(net, "_registry_gen", None)
        if gen is None:
            from deeplearning4j_tpu.nn.generate import build_generator
            gen = net._registry_gen = build_generator(net)
        return gen

    def draft(self):
        """The paired draft net for speculative decoding, or None.

        ``deploy(draft="self")`` (alias ``"quantize"``) resolves lazily
        to ``quantize(self.net(), "int8")`` — the PR-14 zero-training
        draft whose measured greedy-match rate IS the acceptance prior.
        An explicit net is returned as-is. Built once and cached; the
        scheduler holds the resolved net for the lane's lifetime."""
        with self._lock:
            if self._draft_net is not None:
                return self._draft_net
            src = self._draft_src
            if src is None:
                return None
            if isinstance(src, str):
                if src not in ("self", "quantize"):
                    raise ValueError(
                        f"unknown draft sentinel {src!r}: expected "
                        "'self'/'quantize' or a net")
                from deeplearning4j_tpu.nn.quantize import quantize
                if self._net is None:
                    self._net = self._load()
                if self._net.params is None:
                    self._net.init()
                self._draft_net = quantize(self._net, "int8")
            else:
                self._draft_net = src
            return self._draft_net

    def p99_ms(self) -> Optional[float]:
        if not self.latencies:
            return None
        lats = sorted(self.latencies)
        return lats[min(len(lats) - 1, int(len(lats) * 0.99))]

    def serving(self) -> bool:
        return self.state in (STATE_ACTIVE, STATE_CANARY)


class _CanaryWatch:
    """Auto-rollback policy state for one in-flight canary."""

    __slots__ = ("fraction", "min_requests", "max_error_rate", "p99_factor",
                 "counter")

    def __init__(self, fraction: float, min_requests: int,
                 max_error_rate: float, p99_factor: float):
        self.fraction = min(1.0, max(0.0, float(fraction)))
        self.min_requests = max(1, int(min_requests))
        self.max_error_rate = float(max_error_rate)
        self.p99_factor = float(p99_factor)
        self.counter = 0  # deterministic routing: every k-th request


class _ModelEntry:
    """Registry-side bookkeeping for one named model."""

    def __init__(self, name: str, priority: int, weight: float,
                 buckets: Optional[Sequence[int]],
                 warm_shapes: Optional[Sequence[Tuple[int, ...]]]):
        self.name = name
        self.priority = int(priority)
        self.weight = float(weight)
        self.buckets = tuple(sorted(buckets)) if buckets else None
        self.warm_shapes = [tuple(s) for s in (warm_shapes or [])]
        self.versions: Dict[int, ModelVersion] = {}
        self.active: Optional[int] = None
        self.canary: Optional[int] = None
        self.canary_watch: Optional[_CanaryWatch] = None
        # circuit breaker: consecutive cross-replica batch faults
        self.breaker_failures = 0
        self.breaker_open = False
        # last shape that served successfully — the probe program
        self.probe_shape: Optional[Tuple[int, ...]] = None
        self.coalesce = True  # batch_statistics models dispatch alone


class ModelRegistry:
    """Named models × versions with lifecycle, budget, and isolation.

    ``memory_budget_bytes`` bounds the registry-accounted device pins
    (None = unbounded). ``keep_versions`` retired versions are retained
    per model for instant rollback. ``breaker_threshold`` consecutive
    cross-replica batch faults open a model's circuit breaker. The
    ``canary_*`` knobs are the auto-rollback policy defaults
    (overridable per :meth:`deploy`)."""

    def __init__(self, memory_budget_bytes: Optional[int] = None,
                 keep_versions: int = 3,
                 breaker_threshold: int = 2,
                 canary_min_requests: int = 8,
                 canary_max_error_rate: float = 0.25,
                 canary_p99_factor: float = 3.0):
        self.memory_budget = (None if memory_budget_bytes is None
                              else int(memory_budget_bytes))
        self.keep_versions = max(1, int(keep_versions))
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.canary_min_requests = int(canary_min_requests)
        self.canary_max_error_rate = float(canary_max_error_rate)
        self.canary_p99_factor = float(canary_p99_factor)
        self._models: Dict[str, _ModelEntry] = {}
        self._lock = threading.RLock()
        self._tick = 0
        self._pinned_bytes = 0
        self._engines: List[Any] = []

    # ----------------------------------------------------------- metrics

    def _reg(self):
        return get_registry()

    def _gauge_active(self, name: str, version: Optional[int]) -> None:
        self._reg().gauge(
            MODEL_ACTIVE_VERSION_GAUGE,
            "Active (traffic-taking) version per registered model",
            model=name).set(float(version if version is not None else -1))

    def _gauge_breaker(self, name: str, is_open: bool) -> None:
        self._reg().gauge(
            MODEL_BREAKER_OPEN_GAUGE,
            "Per-model circuit breaker (1 = quarantined, being probed)",
            model=name).set(1.0 if is_open else 0.0)

    def _gauge_pinned(self) -> None:
        self._reg().gauge(
            MODEL_PINNED_BYTES_GAUGE,
            "Device-pinned parameter bytes accounted against the "
            "registry memory budget").set(float(self._pinned_bytes))

    def _count_deploy(self, name: str, outcome: str) -> None:
        self._reg().counter(
            MODEL_DEPLOYS_COUNTER,
            "Model version deploys by outcome",
            model=name, outcome=outcome).inc()

    def _count_rollback(self, name: str, reason: str) -> None:
        self._reg().counter(
            MODEL_ROLLBACKS_COUNTER,
            "Model version rollbacks by reason",
            model=name, reason=reason).inc()
        from deeplearning4j_tpu.monitor.reqtrace import flight_event
        flight_event("rollback", model=name, reason=reason)

    # -------------------------------------------------------- membership

    def attach(self, engine) -> None:
        """Register a serving engine so deploys can AOT-warm new
        versions on its replicas before cutover."""
        with self._lock:
            if engine not in self._engines:
                self._engines.append(engine)

    def detach(self, engine) -> None:
        with self._lock:
            if engine in self._engines:
                self._engines.remove(engine)

    def register(self, name: str, net=None, path: Optional[str] = None,
                 version: int = 1, priority: int = 0, weight: float = 1.0,
                 buckets: Optional[Sequence[int]] = None,
                 warm_shapes: Optional[Sequence[Tuple[int, ...]]] = None
                 ) -> int:
        """Add a model with its first version (immediately active).
        ``priority`` orders evictions (higher survives longer),
        ``weight`` is the fair-scheduling share, ``buckets`` overrides
        the engine's row-bucket ladder for this model, ``warm_shapes``
        are the per-example shapes deploys warm with."""
        with self._lock:
            if name in self._models:
                raise ValueError(f"model {name!r} already registered")
            entry = _ModelEntry(name, priority, weight, buckets, warm_shapes)
            ver = ModelVersion(name, version, net=net, path=path)
            if net is not None and hasattr(net, "_pad_tail_safe"):
                entry.coalesce = bool(net._pad_tail_safe())
            ver.state = STATE_ACTIVE
            entry.versions[ver.version] = ver
            entry.active = ver.version
            self._models[name] = entry
        self._gauge_active(name, version)
        self._gauge_breaker(name, False)
        mark("model_registered", model=name, version=version)
        return ver.version

    def unregister(self, name: str) -> None:
        with self._lock:
            entry = self._models.pop(name, None)
            if entry is None:
                return
            for ver in entry.versions.values():
                self._unpin_all(ver)
        self._gauge_active(name, None)

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def _entry(self, name: str) -> _ModelEntry:
        entry = self._models.get(name)
        if entry is None:
            raise ModelUnavailable(f"unknown model {name!r}")
        return entry

    def entry(self, name: str) -> _ModelEntry:
        with self._lock:
            return self._entry(name)

    def version(self, name: str, version: int) -> ModelVersion:
        with self._lock:
            entry = self._entry(name)
            ver = entry.versions.get(int(version))
            if ver is None:
                raise ModelUnavailable(
                    f"model {name!r} has no version {version}")
            return ver

    def active_version(self, name: str) -> int:
        with self._lock:
            entry = self._entry(name)
            if entry.active is None:
                raise ModelUnavailable(f"model {name!r} has no active version")
            return entry.active

    def versions(self, name: str) -> Dict[int, str]:
        with self._lock:
            return {v: ver.state
                    for v, ver in sorted(self._entry(name).versions.items())}

    def weight(self, name: Optional[str]) -> float:
        if name is None:
            return 1.0
        with self._lock:
            entry = self._models.get(name)
            return entry.weight if entry is not None else 1.0

    # --------------------------------------------------------- resolve

    def resolve(self, name: str, version: Optional[int] = None) -> int:
        """Pick the version a fresh request serves on: the explicit ask,
        else the canary (every k-th request, deterministically — k from
        ``canary_fraction``), else the active version. Fails fast with
        :class:`ModelQuarantined` while the model's breaker is open —
        isolation means a poisoned model rejects at admission instead
        of burning replica dispatches."""
        with self._lock:
            entry = self._entry(name)
            if entry.breaker_open:
                raise ModelQuarantined(
                    f"model {name!r} is quarantined (circuit breaker open "
                    f"after {entry.breaker_failures} cross-replica faults)")
            if version is not None:
                ver = entry.versions.get(int(version))
                if ver is None or ver.state == STATE_REJECTED:
                    raise ModelUnavailable(
                        f"model {name!r} version {version} is not servable")
                return int(version)
            watch = entry.canary_watch
            if entry.canary is not None and watch is not None \
                    and watch.fraction > 0.0:
                watch.counter += 1
                period = max(1, round(1.0 / watch.fraction))
                if watch.counter % period == 0:
                    return entry.canary
            if entry.active is None:
                raise ModelUnavailable(f"model {name!r} has no active version")
            return entry.active

    # ----------------------------------------------------- device pins

    @staticmethod
    def _devkey(device) -> str:
        return str(device)

    def acquire(self, name: str, version: int, device):
        """(fn, params, states) for one dispatch: params pinned on
        ``device``, LRU-touched, budget-accounted (evicting colder pins
        when needed). Called from engine workers — the returned refs
        stay valid even if the pin is evicted mid-dispatch."""
        import jax

        ver = self.version(name, version)
        key = self._devkey(device)
        with self._lock:
            self._tick += 1
            ver.last_used = self._tick
            pinned = ver.pins.get(key)
        if pinned is not None:
            return ver.fn(), pinned[0], pinned[1]
        # pin outside the lock (device_put + possible lazy reload are
        # slow); racing workers may both pin — the second install wins
        # accounting-wise and the loser's copy is garbage collected
        net = ver.net()
        params = jax.device_put(net.params, device)
        states = jax.device_put(net.states, device)
        # charge the ACTUAL nbytes of the pinned pytree (params AND
        # states): the serialized/fp32-shaped estimate overcharged
        # quantized versions — an int8 model now admits ~4x the
        # cotenants its fp32 twin would under the same budget
        size = _tree_nbytes(params) + _tree_nbytes(states)
        with self._lock:
            if key not in ver.pins:
                self._evict_for(size, exclude=ver)
                ver.pins[key] = (params, states)
                ver.pin_bytes[key] = size
                self._pinned_bytes += size
        self._gauge_pinned()
        return ver.fn(), params, states

    def _evict_for(self, size: int, exclude: ModelVersion) -> None:
        """Free budget for ``size`` new bytes: drop the least-recently
        used, lowest-priority pins first (never the version being
        pinned). Checkpoint-backed versions also drop their host copy.
        Holds the registry lock."""
        if self.memory_budget is None:
            return
        candidates = []
        for entry in self._models.values():
            for ver in entry.versions.values():
                if ver is exclude or not ver.pins:
                    continue
                candidates.append((entry.priority, ver.last_used, ver))
        candidates.sort(key=lambda t: (t[0], t[1]))
        for _, _, ver in candidates:
            if self._pinned_bytes + size <= self.memory_budget:
                return
            freed = self._unpin_all(ver)
            if freed:
                ver.drop_host()
                self._reg().counter(
                    MODEL_EVICTIONS_COUNTER,
                    "Model versions evicted from the device-memory budget",
                    model=ver.name).inc()
                mark("model_evicted", model=ver.name, version=ver.version,
                     bytes=freed)
        # over budget with nothing left to evict: serve anyway — a
        # model the budget cannot fit is better served than refused

    def _unpin_all(self, ver: ModelVersion) -> int:
        # release exactly what each pin was charged (pin_bytes — the
        # actual pinned-pytree sizes, not a per-version estimate)
        freed = sum(ver.pin_bytes.get(k, 0) for k in ver.pins)
        ver.pins.clear()
        ver.pin_bytes.clear()
        self._pinned_bytes = max(0, self._pinned_bytes - freed)
        self._gauge_pinned()
        return freed

    def pinned_bytes(self) -> int:
        with self._lock:
            return self._pinned_bytes

    # ---------------------------------------------------------- deploy

    def _next_version(self, entry: _ModelEntry) -> int:
        return (max(entry.versions) + 1) if entry.versions else 1

    def deploy(self, name: str, net=None, path: Optional[str] = None,
               version: Optional[int] = None, canary_fraction: float = 0.0,
               warm: bool = True,
               canary_min_requests: Optional[int] = None,
               canary_max_error_rate: Optional[float] = None,
               canary_p99_factor: Optional[float] = None,
               quality_gate=None, draft=None) -> int:
        """Zero-downtime deploy of a new version.

        Order of operations is the whole contract: (1) integrity-check
        — a corrupt checkpoint raises :class:`CheckpointCorruptError`
        HERE and the old version never stops serving; (2) load + AOT-
        warm the staged version on every attached engine's replicas,
        off the hot path; (2b) run ``quality_gate(stable_net, new_net)``
        when given (the nn/quantize.py accuracy-delta harness via
        ``make_quality_gate`` is the canonical one — a quantized canary
        ships with a measured quality bound): a failing verdict rejects
        the deploy typed :class:`QualityGateFailed` before ANY traffic
        shifts, counted as a ``quality_gate`` rollback; (3) atomically
        cut over (or enter canary — ``canary_fraction > 0`` keeps the
        old version active and routes the fraction to the new one until
        :meth:`promote` or the watch rolls it back). Returns the new
        version number.

        ``draft=`` pairs a speculative-decoding draft with this version
        — a net, or ``"self"``/``"quantize"`` for lazy int8
        self-speculation. The pairing rides the version record through
        canary, rollback, and session pinning: a stream keeps its
        resolved draft for its whole life."""
        entry = self.entry(name)
        if net is None and path is None:
            raise ValueError("deploy needs a net or a checkpoint path")
        if isinstance(draft, str) and draft not in ("self", "quantize"):
            raise ValueError(
                f"deploy draft={draft!r}: expected 'self'/'quantize' "
                "or a net")
        if path is not None and not os.path.isdir(path):
            problems = verify_model_file(path)
            if problems:
                self._count_deploy(name, "rejected_corrupt")
                record_fault("deploy")
                mark("model_deploy_rejected", model=name,
                     reason="corrupt_checkpoint")
                raise CheckpointCorruptError("; ".join(problems))
        with self._lock:
            new_v = self._next_version(entry) if version is None else int(version)
            if new_v in entry.versions:
                raise ValueError(f"model {name!r} already has version {new_v}")
            ver = ModelVersion(name, new_v, net=net, path=path, draft=draft)
            entry.versions[new_v] = ver
        try:
            ver.net()  # force the load (and its integrity check) now
            if warm:
                self._warm(entry, ver)
        except BaseException:
            with self._lock:
                entry.versions.pop(new_v, None)
            self._count_deploy(name, "rejected_corrupt")
            record_fault("deploy")
            mark("model_deploy_rejected", model=name, version=new_v)
            raise
        if quality_gate is not None:
            self._run_quality_gate(entry, ver, quality_gate)
        with self._lock:
            if canary_fraction > 0.0:
                entry.canary = new_v
                ver.state = STATE_CANARY
                entry.canary_watch = _CanaryWatch(
                    canary_fraction,
                    self.canary_min_requests if canary_min_requests is None
                    else canary_min_requests,
                    self.canary_max_error_rate if canary_max_error_rate is None
                    else canary_max_error_rate,
                    self.canary_p99_factor if canary_p99_factor is None
                    else canary_p99_factor)
                outcome = "canary"
            else:
                self._cutover(entry, new_v)
                outcome = "accepted"
            active_now = entry.active
            breaker_now = entry.breaker_open
        self._count_deploy(name, outcome)
        self._gauge_active(name, active_now)
        self._gauge_breaker(name, breaker_now)
        mark("model_deployed", model=name, version=new_v, outcome=outcome)
        return new_v

    def _run_quality_gate(self, entry: _ModelEntry,
                          ver: ModelVersion, quality_gate) -> None:
        """Arbitrate a staged version by measured quality: the gate
        sees (stable net or None, candidate net) and returns either an
        accuracy-harness verdict dict (``{"passed": bool, ...}``) or a
        bare bool. Fail → the candidate is removed (it never served),
        the outcome is counted like a canary auto-rollback, and
        :class:`QualityGateFailed` carries the numbers. Pass or fail,
        the verdict is persisted on the version record — the
        ``greedy_match_rate`` a quantized candidate measured here is
        exactly the speculative-decoding acceptance-rate prior, so
        discarding it would throw away the one number capacity planning
        for speculation needs (stats()/healthz surface it)."""
        with self._lock:
            stable_ver = (entry.versions.get(entry.active)
                          if entry.active is not None else None)
        stable_net = stable_ver.net() if stable_ver is not None else None
        verdict = quality_gate(stable_net, ver.net())
        passed = (bool(verdict.get("passed", False))
                  if isinstance(verdict, dict) else bool(verdict))
        with self._lock:
            ver.quality = (dict(verdict) if isinstance(verdict, dict)
                           else {"passed": passed})
        if passed:
            return
        with self._lock:
            ver.state = STATE_REJECTED
            entry.versions.pop(ver.version, None)
            self._unpin_all(ver)
        self._count_deploy(entry.name, "rejected_quality")
        self._count_rollback(entry.name, "quality_gate")
        record_fault("deploy")
        mark("model_deploy_rejected", model=entry.name,
             version=ver.version, reason="quality_gate")
        detail = verdict if isinstance(verdict, dict) else "gate False"
        raise QualityGateFailed(
            f"model {entry.name!r} v{ver.version} failed its quality "
            f"gate: {detail} — the stable version never stopped serving",
            verdict=verdict)

    def _warm(self, entry: _ModelEntry, ver: ModelVersion) -> None:
        """AOT-compile the staged version's program set on every
        attached engine — the deploy pays the XLA compiles, not the
        first post-cutover request."""
        shapes = entry.warm_shapes
        with self._lock:
            engines = list(self._engines)
        for engine in engines:
            engine.warmup_model(entry.name, version=ver.version,
                                shapes=shapes or None)
        ver.warmed = True

    def _cutover(self, entry: _ModelEntry, new_v: int) -> None:
        """Atomic pointer swap + retention pruning (registry lock held).
        In-flight requests hold their resolved ModelVersion and finish
        on it; the retired version stays rollback-able."""
        prev = entry.active
        if prev is not None and prev != new_v:
            entry.versions[prev].state = STATE_RETIRED
        entry.versions[new_v].state = STATE_ACTIVE
        entry.active = new_v
        entry.canary = None
        entry.canary_watch = None
        # a fresh version gets a fresh chance: cutover resets the
        # breaker (deploying a fixed version IS the recovery path for a
        # quarantined model)
        entry.breaker_open = False
        entry.breaker_failures = 0
        # prune beyond the retention window (never the active version)
        retired = sorted(v for v, mv in entry.versions.items()
                         if mv.state == STATE_RETIRED)
        for stale in retired[:-self.keep_versions]:
            mv = entry.versions.pop(stale)
            self._unpin_all(mv)

    def promote(self, name: str) -> int:
        """Cut the canary over to active (the healthy end of a canary)."""
        with self._lock:
            entry = self._entry(name)
            if entry.canary is None:
                raise ModelUnavailable(f"model {name!r} has no canary")
            new_v = entry.canary
            self._cutover(entry, new_v)
        self._gauge_active(name, new_v)
        self._gauge_breaker(name, False)
        self._count_deploy(name, "promoted")
        mark("model_promoted", model=name, version=new_v)
        return new_v

    def rollback(self, name: str, reason: str = "manual") -> int:
        """Instant rollback. With a live canary: reject the canary (the
        active version never stopped serving). Otherwise: reactivate the
        newest retired version — versions are retained exactly so this
        is a pointer swap, not a reload."""
        with self._lock:
            entry = self._entry(name)
            if entry.canary is not None:
                bad = entry.versions[entry.canary]
                bad.state = STATE_REJECTED
                entry.canary = None
                entry.canary_watch = None
                self._unpin_all(bad)
                active = entry.active
            else:
                retired = sorted(v for v, mv in entry.versions.items()
                                 if mv.state == STATE_RETIRED)
                if not retired:
                    raise ModelUnavailable(
                        f"model {name!r} has no version to roll back to")
                prev = entry.active
                active = retired[-1]
                entry.versions[active].state = STATE_ACTIVE
                entry.active = active
                if prev is not None:
                    entry.versions[prev].state = STATE_REJECTED
        self._count_rollback(name, reason)
        self._gauge_active(name, active)
        record_fault("deploy")
        mark("model_rollback", model=name, reason=reason, active=active)
        return active

    # ------------------------------------------------- serving feedback

    def wants_nan_check(self, name: str, version: int) -> bool:
        """Only canary versions pay the host-side NaN scan — the watch
        plane needs the signal; steady-state traffic stays cheap."""
        with self._lock:
            entry = self._models.get(name)
            return entry is not None and entry.canary == int(version)

    def note_result(self, name: str, version: int, latency_ms: float,
                    rows: int = 1, nan: bool = False,
                    shape: Optional[Tuple[int, ...]] = None) -> None:
        """One successful batch dispatch for (model, version): closes
        the breaker, feeds the canary watch (NaN output = immediate
        rollback), updates the per-model metric family."""
        rollback_reason = None
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                return
            ver = entry.versions.get(int(version))
            if ver is None:
                return
            entry.breaker_failures = 0
            if shape is not None:
                entry.probe_shape = tuple(shape)
            ver.requests += rows
            ver.latencies.append(latency_ms)
            ver.ewma_ms = (latency_ms if ver.ewma_ms is None
                           else 0.8 * ver.ewma_ms + 0.2 * latency_ms)
            if nan:
                ver.nans += 1
            if entry.canary == int(version):
                rollback_reason = self._canary_verdict(entry, ver)
        reg = self._reg()
        reg.counter(MODEL_REQUESTS_COUNTER,
                    "Requests served per model", model=name).inc(rows)
        reg.histogram(MODEL_LATENCY_HISTOGRAM,
                      "Per-batch dispatch latency per model",
                      model=name).observe(latency_ms)
        if rollback_reason is not None:
            self.rollback(name, reason=rollback_reason)

    def _canary_verdict(self, entry: _ModelEntry,
                        ver: ModelVersion) -> Optional[str]:
        """The auto-rollback decision (registry lock held): NaN output
        kills a canary immediately; error-rate and p99-regression need
        ``min_requests`` of evidence first."""
        watch = entry.canary_watch
        if watch is None:
            return None
        if ver.nans > 0:
            return "canary_nan"
        served = ver.requests + ver.errors
        if served < watch.min_requests:
            return None
        if ver.errors / max(1, served) > watch.max_error_rate:
            return "canary_error_rate"
        stable = entry.versions.get(entry.active) if entry.active else None
        if stable is not None:
            base = stable.p99_ms()
            canary_p99 = ver.p99_ms()
            if base is not None and canary_p99 is not None and base > 0 \
                    and canary_p99 > watch.p99_factor * base:
                return "canary_p99"
        return None

    def note_error(self, name: str, version: int) -> str:
        """One failed batch (same-replica retries already exhausted) for
        (model, version). Returns the isolation verdict the engine acts
        on:

        - ``"model_open"`` — the model's circuit breaker just opened
          (``breaker_threshold`` consecutive cross-replica faults on a
          serving version): fail the batch model-scoped, do NOT
          quarantine the replica;
        - ``"version_rejected"`` — the faulting version was a canary
          and the watch just rolled it back: fail the batch (callers
          retry onto the stable version), do NOT quarantine the
          replica — the stable version never stopped serving;
        - ``"retry"`` — not yet attributable to the model: follow the
          replica-quarantine/redispatch path."""
        rollback_reason = None
        opened = False
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                return "retry"
            ver = entry.versions.get(int(version))
            if ver is not None:
                ver.errors += 1
            is_canary = entry.canary == int(version)
            if is_canary and ver is not None:
                # a canary's faults indict the canary, never the model:
                # the stable version is healthy by construction
                rollback_reason = self._canary_error_verdict(entry, ver)
            else:
                entry.breaker_failures += 1
                opened = (not entry.breaker_open
                          and entry.breaker_failures >= self.breaker_threshold)
                if opened:
                    entry.breaker_open = True
                elif entry.breaker_open:
                    opened = True  # already open: still model-scoped
        self._reg().counter(MODEL_ERRORS_COUNTER,
                            "Failed dispatches per model", model=name).inc()
        if opened:
            self._gauge_breaker(name, True)
            record_fault("serving")
            mark("model_breaker_open", model=name, version=version)
            return "model_open"
        if rollback_reason is not None:
            self.rollback(name, reason=rollback_reason)
            return "version_rejected"
        return "retry"

    def _canary_error_verdict(self, entry: _ModelEntry,
                              ver: ModelVersion) -> Optional[str]:
        """Registry lock held. A deterministically-failing canary dies
        after ``breaker_threshold`` faults (no need for min_requests of
        pain); a flaky one dies when its error rate is provably above
        the bar even granting it ``min_requests`` of clean traffic."""
        watch = entry.canary_watch
        if watch is None:
            return None
        if ver.errors >= self.breaker_threshold:
            return "canary_error_rate"
        served = ver.requests + ver.errors
        worst_possible = ver.errors / max(1, max(served, watch.min_requests))
        if worst_possible > watch.max_error_rate:
            return "canary_error_rate"
        return None

    def breaker_open(self, name: str) -> bool:
        with self._lock:
            entry = self._models.get(name)
            return bool(entry is not None and entry.breaker_open)

    def open_models(self) -> List[str]:
        with self._lock:
            return sorted(n for n, e in self._models.items() if e.breaker_open)

    def close_breaker(self, name: str) -> None:
        """A probe passed: the model rejoins the serving pool."""
        with self._lock:
            entry = self._entry(name)
            entry.breaker_open = False
            entry.breaker_failures = 0
        self._gauge_breaker(name, False)
        mark("model_breaker_closed", model=name)

    def probe_info(self, name: str):
        """(version, shape, np_dtype) for a one-row known-good probe of
        an open-breaker model; shape may be None when nothing has ever
        served (the caller reinstates optimistically)."""
        with self._lock:
            entry = self._entry(name)
            version = entry.active if entry.canary is None else entry.canary
            shape = entry.probe_shape
            if shape is None and entry.warm_shapes:
                shape = entry.warm_shapes[0]
        if version is None:
            return None, None, None
        return version, shape, self.version(name, version).np_dtype

    # ------------------------------------------------------------ state

    def attribution(self) -> Dict[str, Any]:
        """Per-model resource attribution aggregated across every
        attached engine's decode scheduler: the token/queue-time
        accumulators merge per owner lane (``model[@vN]`` — a canary
        version meters under its own key, so a cutover's cost split is
        visible), and the per-pool KV byte-second meters concatenate
        (each pool is its own conservation domain — merging them would
        hide a meter that stopped adding up)."""
        with self._lock:
            engines = list(self._engines)
        models: Dict[str, Dict[str, float]] = {}
        pools: List[Dict[str, Any]] = []
        for eng in engines:
            sched = getattr(eng, "_scheduler", None)
            attr_fn = getattr(sched, "attribution", None)
            if attr_fn is None:
                continue
            attr = attr_fn()
            for owner, d in (attr.get("models") or {}).items():
                o = models.setdefault(
                    owner, {"prefill_tokens": 0, "decode_tokens": 0,
                            "queue_ms": 0.0})
                o["prefill_tokens"] += int(d.get("prefill_tokens", 0))
                o["decode_tokens"] += int(d.get("decode_tokens", 0))
                o["queue_ms"] += float(d.get("queue_ms", 0.0))
            pools.extend(attr.get("kv_pools") or [])
        return {"models": models, "kv_pools": pools}

    def stats(self) -> Dict[str, Any]:
        """Per-model snapshot: what ``engine.stats()["models"]`` and
        ``/healthz`` serve."""
        out: Dict[str, Any] = {}
        with self._lock:
            for name, entry in sorted(self._models.items()):
                versions = {}
                for v, ver in sorted(entry.versions.items()):
                    gmr = (ver.quality.get("greedy_match_rate")
                           if ver.quality else None)
                    versions[str(v)] = {
                        "state": ver.state,
                        "warmed": ver.warmed,
                        "requests": ver.requests,
                        "errors": ver.errors,
                        "nans": ver.nans,
                        "ewma_ms": (None if ver.ewma_ms is None
                                    else round(ver.ewma_ms, 3)),
                        "p99_ms": (None if ver.p99_ms() is None
                                   else round(ver.p99_ms(), 3)),
                        "pinned_devices": len(ver.pins),
                        "quality_gate": ver.quality,
                        # accuracy_gate's greedy-match rate = the prior
                        # on speculative-decoding acceptance rate
                        "spec_accept_prior": (None if gmr is None
                                              else round(float(gmr), 4)),
                        "draft_paired": ver._draft_src is not None,
                    }
                active = entry.versions.get(entry.active) \
                    if entry.active is not None else None
                out[name] = {
                    "active_version": entry.active,
                    "canary_version": entry.canary,
                    "canary_fraction": (entry.canary_watch.fraction
                                        if entry.canary_watch else 0.0),
                    "breaker_open": entry.breaker_open,
                    "priority": entry.priority,
                    "weight": entry.weight,
                    "ready": bool(active is not None
                                  and not entry.breaker_open),
                    "warmed": bool(active is not None and active.warmed),
                    "versions": versions,
                }
        return out
