"""Request/reply + heartbeat wire format for the serving tier.

The fleet speaks over any :class:`~deeplearning4j_tpu.streaming.broker.
MessageBroker` (in-memory in tests, ``TcpBroker`` across hosts), so the
router ↔ engine-worker channel is framed *inside* broker payloads:

- request / reply: u32 big-endian header length + JSON header + binary
  body (npz via ``streaming/serde.py`` — self-describing dtype+shape).
  The header carries the correlation id (``id``), the caller's private
  reply topic (``reply``), the request kind (``classify`` /
  ``generate`` with its sampler params), the multi-model routing
  fields (``model`` / ``version`` / ``session`` — absent for a
  single-model engine), and an optional propagated request-trace
  context (``trace`` — ``monitor/reqtrace.py``; ignored by consumers
  that predate it, no version bump needed: version-skew safe by the
  same discipline as wire v2/v3). Correlation ids make the channel
  safe for
  pipelining: replies may arrive out of order and the endpoint matches
  them back to futures by id, never by position.

Error replies are TYPED: the reply header carries ``etype`` (the
exception class name) plus any wire-safe payload fields
(``retry_after_s``), and :func:`typed_error` reconstructs the SAME
exception type on the caller's side for the registered engine-error
family (backpressure sheds, model quarantine, corrupt-checkpoint
deploys, router ``RetryAfter``) — a remote worker's shed surfaces to
the router caller exactly like an in-process ``LocalEndpoint``'s
would, for both classify and generate paths. Unregistered types
degrade to :class:`~deeplearning4j_tpu.serving.endpoint.
EndpointError` with the message preserved.
- heartbeat: plain JSON — worker name, monotonically increasing
  ``seq``, lifecycle ``state`` (serving / draining / stopped) and the
  engine's ``stats()`` snapshot. The router's health plane consumes
  these instead of inferring engine death from reply timeouts alone;
  the ``resolved`` / scheduler ``bursts`` counters riding in the stats
  double as PROGRESS proof — a heartbeat proves liveness, the counters
  prove the worker is actually advancing its queued work.
- v2: decode replies may be CHUNKED — per-burst
  :func:`pack_chunk` frames carry token deltas tagged with sequence
  offsets, and the terminal :func:`pack_reply` still carries the full
  payload; ``gen.prefix`` on a request makes it a RESUME (the engine
  re-prefills prompt + prefix and continues the stream's PRNG clock).
  Version skew fails typed: :func:`check_version` raises
  :class:`WireVersionError` instead of serving a newer frame garbled.
- v4 (``WIRE_VERSION``): ZERO-COPY BINARY framing for the hot path. A
  v4 frame opens with a struct-packed fixed prologue (magic, version,
  frame kind, meta length, segment count), then a small JSON meta
  block (correlation id, reply topic, request kind, model / session /
  trace routing fields — everything the legacy header carried except
  tensors), then length-prefixed RAW tensor segments (tag + dtype +
  shape + contiguous bytes) written into one preallocated buffer via
  ``memoryview`` — no npz, no base64, no per-tensor allocation churn
  on the hot path. ``np.frombuffer`` re-materializes each segment as
  a zero-copy (read-only) view of the received payload. The first
  customer is the disagg shipped-KV path (:func:`pack_tensor_chunk_v4`
  — byte-exact, dtype-exact), plus COALESCED token-chunk frames
  (:func:`pack_chunks_v4` — ONE frame per retiring burst per endpoint
  carrying every cotenant stream's delta, not one frame per stream).
  npz framing stays for cold control frames and for v3 peers:
  negotiation rides the heartbeat (``wire`` field) and the rolling
  upgrade downgrades framing per peer instead of failing — a typed
  :class:`WireVersionError` is reserved for frames NEWER than the
  receiver, and a structurally damaged binary frame (truncation,
  garbage lengths) surfaces as a typed :class:`WireFrameError`, never
  a garbled tensor.

Topic layout for a worker serving ``service``::

    <service>.req          requests (worker consumes)
    <service>.hb           heartbeats (router consumes)
    <reply topic from the request header>   replies (router consumes;
        one private topic per router/client, so N routers can share a
        worker without stealing each other's replies)
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.monitor import (WIRE_BYTES_COUNTER,
                                        WIRE_COALESCED_COUNTER,
                                        WIRE_FRAMES_COUNTER, get_registry)
from deeplearning4j_tpu.streaming.serde import (ndarray_from_bytes,
                                                ndarray_to_bytes)

REQ_SUFFIX = ".req"
HB_SUFFIX = ".hb"

KIND_CLASSIFY = "classify"
KIND_GENERATE = "generate"
KIND_PREFILL = "prefill"

STATE_SERVING = "serving"
STATE_DRAINING = "draining"
STATE_STOPPED = "stopped"

#: Wire protocol version, carried as ``v`` in every request header.
#: v1: request/reply + heartbeat. v2: chunked decode replies (per-burst
#: token deltas tagged with sequence offsets; the terminal frame still
#: carries the final payload) and resume requests (``gen.prefix`` — the
#: already-generated tokens a migrated stream re-prefills instead of
#: re-generating). v3: disaggregated prefill/decode — ``prefill``
#: request kind (the reply ships the prompt's KV as a TAGGED tensor
#: chunk, :func:`pack_tensor_chunk`, then the terminal frame carries
#: the last-token logits), and ``generate`` requests whose body is a
#: shipped KV tensor (``gen.kv`` set; the prompt ids ride the header as
#: ``gen.prompt``). v4: zero-copy binary framing — struct-packed
#: prologue + JSON meta + length-prefixed raw tensor segments (see the
#: module docstring); negotiated per peer via the heartbeat ``wire``
#: field, so v3 workers keep serving legacy npz frames through a
#: rolling upgrade. A worker receiving a frame NEWER than it speaks
#: rejects it with a typed :class:`WireVersionError` rather than
#: serving it garbled.
WIRE_VERSION = 4

#: first two bytes of every v4+ binary frame. 0xD4 can never open a
#: legacy frame (whose first byte is the high byte of a u32 JSON-header
#: length — a ≥3.3 GB header would exceed the transport's frame cap),
#: so :func:`is_binary_frame` sniffs the framing unambiguously.
#: ``streaming/broker.py`` mirrors these values for its transport-level
#: ping header (PING_MAGIC / PING_VERSION — it sits below serving in
#: the import graph); the pairing is test-pinned.
WIRE_MAGIC = b"\xd4\x0a"

#: v4 frame kinds (the prologue's ``kind`` byte).
FRAME_REQUEST = 1
FRAME_REPLY = 2
FRAME_CHUNKS = 3   # coalesced token-chunk frame (1..n streams)
FRAME_TENSOR = 4   # tagged tensor chunk (disagg shipped KV)

#: prologue: magic (2s) + version (B) + kind (B) + meta length (I) +
#: segment count (B).
_PROLOGUE = struct.Struct(">2sBBIB")
#: per-segment fixed head: tag length (B) + dtype-str length (B) +
#: ndim (B); the shape dims (u32 each) and the u64 payload length
#: follow, then the raw contiguous bytes.
_SEG_HEAD = struct.Struct(">BBB")
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")


class WireVersionError(RuntimeError):
    """The peer speaks a newer wire protocol than this worker: the
    request was rejected typed (never half-parsed) — upgrade the worker
    or drop the client's feature set."""


class WireFrameError(RuntimeError):
    """A binary frame is structurally damaged — truncated mid-segment,
    impossible lengths, unparseable meta. The frame is rejected TYPED
    and whole: no partially-parsed tensor ever reaches an engine (the
    half-written-frame chaos drill pins this)."""


def check_version(header: Dict[str, Any],
                  cap: Optional[int] = None) -> None:
    """``cap`` overrides the ceiling this receiver speaks (the
    rolling-upgrade seam: a worker pinned to v3 rejects v4 frames the
    same typed way a real v3 build would)."""
    limit = WIRE_VERSION if cap is None else int(cap)
    v = int(header.get("v", 1))
    if v > limit:
        raise WireVersionError(
            f"frame speaks wire v{v}; this worker speaks v{limit}")


def _note_frame(nbytes: int, transport: str) -> None:
    reg = get_registry()
    reg.counter(WIRE_FRAMES_COUNTER,
                "Wire frames packed for the broker channel, by framing "
                "(legacy = u32+JSON+npz, v4 = binary prologue + raw "
                "tensor segments)", transport=transport).inc()
    reg.counter(WIRE_BYTES_COUNTER,
                "Wire payload bytes packed for the broker channel, by "
                "framing", transport=transport).inc(float(nbytes))


def is_binary_frame(payload: bytes) -> bool:
    return bytes(payload[:2]) == WIRE_MAGIC


def pack_frame(header: Dict[str, Any], body: bytes = b"") -> bytes:
    h = json.dumps(header, separators=(",", ":")).encode()
    out = struct.pack(">I", len(h)) + h + body
    _note_frame(len(out), "legacy")
    return out


def unpack_frame(payload: bytes) -> Tuple[Dict[str, Any], bytes]:
    if len(payload) < 4:
        raise ValueError(f"short frame ({len(payload)} bytes)")
    (hlen,) = struct.unpack(">I", payload[:4])
    if 4 + hlen > len(payload):
        raise ValueError("header length exceeds frame")
    header = json.loads(payload[4:4 + hlen])
    return header, payload[4 + hlen:]


# --- v4 binary framing ------------------------------------------------------

def _seg_header_size(tag: bytes, arr: np.ndarray) -> int:
    return _SEG_HEAD.size + len(tag) + len(arr.dtype.str) \
        + 4 * arr.ndim + 8


def pack_frame_v4(meta: Dict[str, Any],
                  segments: Sequence[Tuple[str, np.ndarray]] = (),
                  kind: int = FRAME_REPLY) -> bytes:
    """One v4 binary frame: the whole frame size is computed up front,
    ONE buffer is allocated, and every piece — prologue, meta, segment
    headers, raw tensor bytes — is written into it through a
    ``memoryview`` (tensor bytes via the array's own buffer: zero
    serialization, zero intermediate copies beyond the single
    wire-buffer write)."""
    m = json.dumps(meta, separators=(",", ":")).encode()
    arrs: List[Tuple[bytes, np.ndarray]] = []
    for tag, a in segments:
        arr = np.ascontiguousarray(a)
        arrs.append((str(tag).encode(), arr))
    if len(arrs) > 255:
        raise ValueError(f"too many segments ({len(arrs)})")
    total = _PROLOGUE.size + len(m) + sum(
        _seg_header_size(t, a) + a.nbytes for t, a in arrs)
    buf = bytearray(total)
    view = memoryview(buf)
    _PROLOGUE.pack_into(buf, 0, WIRE_MAGIC, WIRE_VERSION, int(kind),
                        len(m), len(arrs))
    off = _PROLOGUE.size
    view[off:off + len(m)] = m
    off += len(m)
    for tag, arr in arrs:
        dt = arr.dtype.str.encode()
        _SEG_HEAD.pack_into(buf, off, len(tag), len(dt), arr.ndim)
        off += _SEG_HEAD.size
        view[off:off + len(tag)] = tag
        off += len(tag)
        view[off:off + len(dt)] = dt
        off += len(dt)
        for dim in arr.shape:
            _U32.pack_into(buf, off, int(dim))
            off += 4
        _U64.pack_into(buf, off, arr.nbytes)
        off += 8
        if arr.nbytes:
            view[off:off + arr.nbytes] = \
                memoryview(arr).cast("B")  # raw bytes, no npz
            off += arr.nbytes
    _note_frame(total, "v4")
    return bytes(buf)


def unpack_frame_v4(payload: bytes
                    ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """Decode one v4 binary frame into (meta, {tag: tensor}). Tensors
    are ZERO-COPY read-only views over ``payload``
    (``np.frombuffer``). Structural damage — truncation, lengths past
    the frame end, unparseable meta — raises the typed
    :class:`WireFrameError`; a version byte NEWER than this build is
    surfaced through the meta (``v``) for :func:`check_version`, so the
    receiver can still reply typed using the frame's correlation id."""
    mv = memoryview(payload)
    if len(mv) < _PROLOGUE.size:
        raise WireFrameError(
            f"short v4 frame ({len(mv)} bytes < prologue)")
    magic, ver, kind, mlen, nseg = _PROLOGUE.unpack_from(payload, 0)
    if magic != WIRE_MAGIC:
        raise WireFrameError(f"bad v4 magic {magic!r}")
    off = _PROLOGUE.size
    if off + mlen > len(mv):
        raise WireFrameError("v4 meta length exceeds frame")
    try:
        meta = json.loads(bytes(mv[off:off + mlen]))
    except ValueError as e:
        raise WireFrameError(f"undecodable v4 meta: {e}") from None
    off += mlen
    meta.setdefault("v", int(ver))
    meta["_kind"] = int(kind)
    segs: Dict[str, np.ndarray] = {}
    for _ in range(nseg):
        if off + _SEG_HEAD.size > len(mv):
            raise WireFrameError("truncated v4 segment header")
        tlen, dlen, ndim = _SEG_HEAD.unpack_from(payload, off)
        off += _SEG_HEAD.size
        need = tlen + dlen + 4 * ndim + 8
        if off + need > len(mv):
            raise WireFrameError("truncated v4 segment descriptor")
        tag = bytes(mv[off:off + tlen]).decode()
        off += tlen
        dt = bytes(mv[off:off + dlen]).decode()
        off += dlen
        shape = []
        for _ in range(ndim):
            shape.append(_U32.unpack_from(payload, off)[0])
            off += 4
        (nbytes,) = _U64.unpack_from(payload, off)
        off += 8
        if off + nbytes > len(mv):
            raise WireFrameError(
                f"truncated v4 segment {tag!r} (payload cut mid-tensor)")
        try:
            arr = np.frombuffer(
                mv[off:off + nbytes], dtype=np.dtype(dt)).reshape(shape)
        except (TypeError, ValueError) as e:
            raise WireFrameError(
                f"v4 segment {tag!r} descriptor invalid: {e}") from None
        segs[tag] = arr
        off += nbytes
    return meta, segs


def pack_request_v4(corr_id: str, reply_topic: str, kind: str,
                    x: np.ndarray,
                    gen: Optional[Dict[str, Any]] = None,
                    model: Optional[str] = None,
                    version: Optional[int] = None,
                    session: Optional[str] = None,
                    trace: Optional[Dict[str, str]] = None,
                    tensors: Optional[Dict[str, np.ndarray]] = None
                    ) -> bytes:
    """The v4 request frame: the meta block carries exactly the legacy
    header's routing fields; ``x`` and any extra ``tensors`` (shipped
    ``kv`` / ``logits``, resume ``prefix``) ride as raw binary
    segments instead of npz bodies / JSON float lists."""
    meta: Dict[str, Any] = {"id": corr_id, "reply": reply_topic,
                            "kind": kind, "v": WIRE_VERSION}
    if gen is not None:
        meta["gen"] = gen
    if model is not None:
        meta["model"] = model
    if version is not None:
        meta["version"] = int(version)
    if session is not None:
        meta["session"] = session
    if trace is not None:
        meta["trace"] = trace
    segments: List[Tuple[str, np.ndarray]] = [("x", np.asarray(x))]
    for tag in sorted(tensors or ()):
        segments.append((tag, np.asarray(tensors[tag])))
    return pack_frame_v4(meta, segments, FRAME_REQUEST)


def unpack_request_any(payload: bytes
                       ) -> Tuple[Dict[str, Any], np.ndarray,
                                  Dict[str, np.ndarray]]:
    """Decode a request in EITHER framing: (header, x, extra tensor
    segments). Legacy npz frames yield an empty segment dict — the
    negotiation seam a rolling upgrade rides (a v4 worker keeps
    serving v3 routers)."""
    if is_binary_frame(payload):
        meta, segs = unpack_frame_v4(payload)
        x = segs.pop("x", None)
        if x is None:
            raise WireFrameError("v4 request frame without an x segment")
        return meta, x, segs
    header, body = unpack_frame(payload)
    return header, ndarray_from_bytes(body), {}


def pack_reply_v4(corr_id: str, result: Optional[np.ndarray] = None,
                  error=None) -> bytes:
    """v4 terminal reply. Errors stay meta-only (cold path, same typed
    ``etype`` fields as legacy so :func:`typed_error` reconstructs the
    exception class unchanged)."""
    if error is not None:
        meta = {"id": corr_id, "ok": False}
        meta.update(_error_fields(error))
        return pack_frame_v4(meta, (), FRAME_REPLY)
    meta = {"id": corr_id, "ok": True}
    segs = [] if result is None else [("r", np.asarray(result))]
    return pack_frame_v4(meta, segs, FRAME_REPLY)


def pack_tensor_chunk_v4(corr_id: str, tag: str,
                         tensor: np.ndarray) -> bytes:
    """The v3 tagged tensor chunk on v4 framing — the disagg shipped-KV
    hot path's first zero-copy customer. Raw dtype+shape+bytes: the
    handoff is byte-exact by construction (no npz container, no float
    round-trip)."""
    meta = {"id": corr_id, "ok": True, "chunk": True, "tag": str(tag),
            "v": WIRE_VERSION}
    return pack_frame_v4(meta, [("t", np.asarray(tensor))], FRAME_TENSOR)


#: tag marking a v4 tensor frame as a session-hibernation payload
#: (host-tier KV blocks + token journal) rather than a plain tensor.
HIBERNATE_TAG = "hib"


def hibernation_segments(payload: Dict[str, Any]
                         ) -> Tuple[Dict[str, Any],
                                    List[Tuple[str, np.ndarray]]]:
    """Flatten a hibernation payload (``hibernate_export`` layout:
    per-block flat ``{"k0": ..., "v0": ..., "k_scale0": ...}`` dicts +
    covered token journal) into (wire meta, raw tensor segments). The
    block tensors ship as raw dtype-exact segments — quantized values
    and their per-token scales ship quantized, so the restore is
    bit-identical by construction."""
    meta: Dict[str, Any] = {"covered": int(payload["covered"]),
                            "nblocks": len(payload["blocks"])}
    if payload.get("model") is not None:
        meta["model"] = payload["model"]
    if payload.get("version") is not None:
        meta["version"] = int(payload["version"])
    segs: List[Tuple[str, np.ndarray]] = [
        ("tokens", np.asarray(payload["tokens"], np.int64))]
    if payload.get("prompt") is not None:
        segs.append(("prompt", np.asarray(payload["prompt"])))
    if payload.get("generated") is not None:
        segs.append(("gen", np.asarray(payload["generated"], np.int64)))
    for i, blk in enumerate(payload["blocks"]):
        for key in sorted(blk):
            segs.append((f"b{i}.{key}", np.asarray(blk[key])))
    return meta, segs


def hibernation_from_segments(hib: Dict[str, Any],
                              segs: Dict[str, np.ndarray]
                              ) -> Dict[str, Any]:
    """Reassemble :func:`hibernation_segments` output into the payload
    dict ``hibernate_import`` / ``submit_generate(kv_state=...)``
    consume. Tensors are COPIED out of the (zero-copy, read-only)
    frame views — the payload outlives the frame buffer."""
    blocks: List[Dict[str, np.ndarray]] = [
        {} for _ in range(int(hib["nblocks"]))]
    for tag, arr in segs.items():
        if tag.startswith("b") and "." in tag:
            idx, key = tag[1:].split(".", 1)
            blocks[int(idx)][key] = np.array(arr)
    payload: Dict[str, Any] = {
        "blocks": blocks, "covered": int(hib["covered"]),
        "tokens": np.array(segs["tokens"]),
        "model": hib.get("model"), "version": hib.get("version")}
    if "prompt" in segs:
        payload["prompt"] = np.array(segs["prompt"])
    if "gen" in segs:
        payload["generated"] = np.array(segs["gen"])
    return payload


def pack_hibernation_v4(corr_id: str, payload: Dict[str, Any]) -> bytes:
    """The hibernation-handle frame a worker ships AFTER a
    ``hibernate=True`` turn retires (non-terminal, before the terminal
    reply): the router parks it as the session's durable handle, so the
    session survives this endpoint's death — resume on a survivor ships
    the same segments back as request tensors. v4-only (multi-segment);
    a v3 peer never receives one and falls back to journaled-prefix
    resume. Raises ``ValueError`` when the session spans more blocks
    than one frame's 255-segment budget — the caller skips shipping
    and the journal rung covers resume."""
    hib, segs = hibernation_segments(payload)
    meta = {"id": corr_id, "ok": True, "chunk": True,
            "tag": HIBERNATE_TAG, "hib": hib, "v": WIRE_VERSION}
    return pack_frame_v4(meta, segs, FRAME_TENSOR)


def pack_chunks_v4(entries: Sequence[Tuple[str, int, np.ndarray]]
                   ) -> bytes:
    """The COALESCED token-chunk frame: every (corr_id, offset,
    tokens) delta a retiring burst produced for one endpoint rides ONE
    frame — the per-stream frame fan-out (and its per-frame npz + JSON
    + broker round-trip cost) collapses by the burst's cotenancy."""
    meta = {"ok": True, "chunk": True, "v": WIRE_VERSION,
            "streams": [[str(c), int(off)] for c, off, _ in entries]}
    segs = [(str(i), np.asarray(toks, np.int64))
            for i, (_, _, toks) in enumerate(entries)]
    out = pack_frame_v4(meta, segs, FRAME_CHUNKS)
    get_registry().counter(
        WIRE_COALESCED_COUNTER,
        "Per-stream token-chunk deltas that rode a coalesced v4 burst "
        "frame instead of a frame of their own").inc(float(len(entries)))
    return out


def decode_reply_events(payload: bytes) -> List[Dict[str, Any]]:
    """Uniform reply decoding over BOTH framings, as a list of events:

    - ``{"type": "chunk", "id", "off", "tokens"}`` — one per stream
      delta (a coalesced v4 frame yields several);
    - ``{"type": "tensor", "id", "tag", "tensor"}`` — tagged tensor
      chunk (disagg kv);
    - ``{"type": "hibernation", "id", "payload"}`` — the durable
      session handle a ``hibernate=True`` turn ships before its
      terminal reply (host-tier KV blocks + token journal, reassembled
      into the ``hibernate_import`` payload layout);
    - ``{"type": "terminal", "id", "header", "result"}`` — resolves
      the request (``header`` carries ok / typed-error fields).

    The consumer loop stays framing-agnostic: a rolling upgrade mixes
    v3 and v4 workers behind one endpoint pool."""
    if is_binary_frame(payload):
        meta, segs = unpack_frame_v4(payload)
        if meta.get("chunk"):
            tag = meta.get("tag")
            if tag == HIBERNATE_TAG and meta.get("hib") is not None:
                return [{"type": "hibernation", "id": meta.get("id"),
                         "payload": hibernation_from_segments(
                             meta["hib"], segs)}]
            if tag is not None:
                return [{"type": "tensor", "id": meta.get("id"),
                         "tag": tag, "tensor": segs.get("t")}]
            out = []
            for i, (corr, off) in enumerate(meta.get("streams") or ()):
                out.append({"type": "chunk", "id": corr, "off": int(off),
                            "tokens": segs.get(str(i))})
            return out
        return [{"type": "terminal", "id": meta.get("id"),
                 "header": meta, "result": segs.get("r")}]
    header, body = unpack_frame(payload)
    result = ndarray_from_bytes(body) if header.get("ok") and body \
        else None
    if is_chunk(header):
        tag = chunk_tag(header)
        if tag is not None:
            return [{"type": "tensor", "id": header.get("id"),
                     "tag": tag, "tensor": result}]
        return [{"type": "chunk", "id": header.get("id"),
                 "off": int(header.get("off", 0)), "tokens": result}]
    return [{"type": "terminal", "id": header.get("id"),
             "header": header, "result": result}]


def pack_request(corr_id: str, reply_topic: str, kind: str, x: np.ndarray,
                 gen: Optional[Dict[str, Any]] = None,
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 session: Optional[str] = None,
                 trace: Optional[Dict[str, str]] = None,
                 wire_v: int = 3) -> bytes:
    """``trace`` is the OPTIONAL propagated request-trace context
    (``monitor/reqtrace.py`` ``TraceContext.wire()``: ``{"id", "span"}``
    strings). It rides the header WITHOUT a wire-version bump — the
    same discipline as every other optional header field: a consumer
    that predates it never reads the key, so a newer router tracing
    against an older worker serves correctly (the merged trace is
    merely gappy on that hop, never corrupt). ``wire_v`` stamps the
    header's protocol version — a v4 endpoint that negotiated DOWN to
    a v3 worker stamps 3, so the worker's skew check accepts the frame
    it is in fact able to serve."""
    header = {"id": corr_id, "reply": reply_topic, "kind": kind,
              "v": int(wire_v)}
    if gen is not None:
        header["gen"] = gen
    if model is not None:
        header["model"] = model
    if version is not None:
        header["version"] = int(version)
    if session is not None:
        header["session"] = session
    if trace is not None:
        header["trace"] = trace
    return pack_frame(header, ndarray_to_bytes(x))


def unpack_request(payload: bytes) -> Tuple[Dict[str, Any], np.ndarray]:
    header, body = unpack_frame(payload)
    return header, ndarray_from_bytes(body)


def _error_fields(error) -> Dict[str, Any]:
    """Wire encoding of an error reply: message + type name + any
    wire-safe payload the reconstructed exception needs."""
    if isinstance(error, BaseException):
        fields: Dict[str, Any] = {"error": str(error),
                                  "etype": type(error).__name__}
        retry = getattr(error, "retry_after_s", None)
        if retry is not None:
            fields["retry_after_s"] = float(retry)
        return fields
    return {"error": str(error)}


def pack_reply(corr_id: str, result: Optional[np.ndarray] = None,
               error=None) -> bytes:
    """``error`` may be a string (legacy) or an exception instance —
    the latter ships typed so :func:`typed_error` can reconstruct it."""
    if error is not None:
        header = {"id": corr_id, "ok": False}
        header.update(_error_fields(error))
        return pack_frame(header)
    return pack_frame({"id": corr_id, "ok": True},
                      ndarray_to_bytes(result))


def pack_chunk(corr_id: str, offset: int, tokens: np.ndarray) -> bytes:
    """A v2 incremental decode chunk: ``tokens`` are the stream's
    generated ids at sequence offsets ``[offset, offset + len)`` (offset
    0 = the first GENERATED token, prompt excluded). Chunks are
    advisory progress — the terminal :func:`pack_reply` still carries
    the full payload, so a consumer that drops chunks stays correct and
    a consumer that dedupes by offset never double-delivers."""
    return pack_frame(
        {"id": corr_id, "ok": True, "chunk": True, "off": int(offset),
         "v": WIRE_VERSION},
        ndarray_to_bytes(np.asarray(tokens, np.int64)))


def is_chunk(header: Dict[str, Any]) -> bool:
    return bool(header.get("chunk"))


def pack_tensor_chunk(corr_id: str, tag: str, tensor: np.ndarray) -> bytes:
    """A v3 TAGGED tensor chunk: a non-terminal frame carrying a named
    tensor payload (``tag`` — e.g. ``"kv"`` for a prefill reply's
    shipped cache). Like token chunks, tensor chunks never resolve the
    request — the terminal :func:`pack_reply` still does — and a
    consumer that cannot use the tag drops the chunk and stays
    correct."""
    return pack_frame(
        {"id": corr_id, "ok": True, "chunk": True, "tag": str(tag),
         "v": WIRE_VERSION},
        ndarray_to_bytes(np.asarray(tensor)))


def chunk_tag(header: Dict[str, Any]) -> Optional[str]:
    return header.get("tag")


def _typed_error_registry() -> Dict[str, Any]:
    """The engine-error family that crosses the wire typed. Imported
    lazily — wire.py sits below router/registry in the import graph."""
    from deeplearning4j_tpu.parallel.inference import (EngineShutdown,
                                                       InferenceBackpressure,
                                                       SliceDegraded)
    from deeplearning4j_tpu.serving.continuous import (DecodeBurstError,
                                                       KVPoolExhausted)
    from deeplearning4j_tpu.nn.kvpool import KVHostTierError
    from deeplearning4j_tpu.serving.registry import (ModelQuarantined,
                                                     ModelUnavailable)
    from deeplearning4j_tpu.serving.router import RetryAfter
    from deeplearning4j_tpu.util.model_serializer import \
        CheckpointCorruptError
    return {
        "InferenceBackpressure": InferenceBackpressure,
        "ModelUnavailable": ModelUnavailable,
        "ModelQuarantined": ModelQuarantined,
        "CheckpointCorruptError": CheckpointCorruptError,
        "RetryAfter": RetryAfter,
        "DecodeBurstError": DecodeBurstError,
        "KVPoolExhausted": KVPoolExhausted,
        "KVHostTierError": KVHostTierError,
        "WireVersionError": WireVersionError,
        "WireFrameError": WireFrameError,
        "SliceDegraded": SliceDegraded,
        "EngineShutdown": EngineShutdown,
    }


def typed_error(header: Dict[str, Any],
                fallback=None) -> BaseException:
    """Reconstruct a reply header's error as the SAME exception type
    the remote engine raised, when it is one of the registered
    wire-safe types; otherwise build ``fallback(message)`` (default
    ``RuntimeError``). The contract the router depends on: a remote
    worker's shed/quarantine is indistinguishable, by type, from a
    local engine's."""
    msg = str(header.get("error", "remote error"))
    etype = header.get("etype")
    cls = _typed_error_registry().get(etype) if etype else None
    if cls is not None:
        if etype == "RetryAfter":
            return cls(msg, float(header.get("retry_after_s", 0.0)))
        return cls(msg)
    return (fallback or RuntimeError)(msg)


def unpack_reply(payload: bytes) -> Tuple[Dict[str, Any],
                                          Optional[np.ndarray]]:
    header, body = unpack_frame(payload)
    return header, (ndarray_from_bytes(body) if header.get("ok") else None)


def pack_heartbeat(name: str, seq: int, state: str,
                   stats: Dict[str, Any],
                   wire_version: int = WIRE_VERSION) -> bytes:
    """Heartbeats stay plain JSON (cold control plane). ``wire`` is the
    worker's advertised wire-version ceiling — the NEGOTIATION signal:
    an endpoint only sends v4 binary frames to a worker whose
    heartbeats advertise ``wire >= 4`` (absent = a pre-v4 build = 3),
    so a rolling upgrade downgrades framing per peer instead of
    failing."""
    return json.dumps({"name": name, "seq": seq, "state": state,
                       "wire": int(wire_version), "stats": stats},
                      separators=(",", ":")).encode()


def unpack_heartbeat(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload)
