"""Request/reply + heartbeat wire format for the serving tier.

The fleet speaks over any :class:`~deeplearning4j_tpu.streaming.broker.
MessageBroker` (in-memory in tests, ``TcpBroker`` across hosts), so the
router ↔ engine-worker channel is framed *inside* broker payloads:

- request / reply: u32 big-endian header length + JSON header + binary
  body (npz via ``streaming/serde.py`` — self-describing dtype+shape).
  The header carries the correlation id (``id``), the caller's private
  reply topic (``reply``), the request kind (``classify`` /
  ``generate`` with its sampler params), the multi-model routing
  fields (``model`` / ``version`` / ``session`` — absent for a
  single-model engine), and an optional propagated request-trace
  context (``trace`` — ``monitor/reqtrace.py``; ignored by consumers
  that predate it, no version bump needed: version-skew safe by the
  same discipline as wire v2/v3). Correlation ids make the channel
  safe for
  pipelining: replies may arrive out of order and the endpoint matches
  them back to futures by id, never by position.

Error replies are TYPED: the reply header carries ``etype`` (the
exception class name) plus any wire-safe payload fields
(``retry_after_s``), and :func:`typed_error` reconstructs the SAME
exception type on the caller's side for the registered engine-error
family (backpressure sheds, model quarantine, corrupt-checkpoint
deploys, router ``RetryAfter``) — a remote worker's shed surfaces to
the router caller exactly like an in-process ``LocalEndpoint``'s
would, for both classify and generate paths. Unregistered types
degrade to :class:`~deeplearning4j_tpu.serving.endpoint.
EndpointError` with the message preserved.
- heartbeat: plain JSON — worker name, monotonically increasing
  ``seq``, lifecycle ``state`` (serving / draining / stopped) and the
  engine's ``stats()`` snapshot. The router's health plane consumes
  these instead of inferring engine death from reply timeouts alone;
  the ``resolved`` / scheduler ``bursts`` counters riding in the stats
  double as PROGRESS proof — a heartbeat proves liveness, the counters
  prove the worker is actually advancing its queued work.
- v2 (``WIRE_VERSION``): decode replies may be CHUNKED — per-burst
  :func:`pack_chunk` frames carry token deltas tagged with sequence
  offsets, and the terminal :func:`pack_reply` still carries the full
  payload; ``gen.prefix`` on a request makes it a RESUME (the engine
  re-prefills prompt + prefix and continues the stream's PRNG clock).
  Version skew fails typed: :func:`check_version` raises
  :class:`WireVersionError` instead of serving a newer frame garbled.

Topic layout for a worker serving ``service``::

    <service>.req          requests (worker consumes)
    <service>.hb           heartbeats (router consumes)
    <reply topic from the request header>   replies (router consumes;
        one private topic per router/client, so N routers can share a
        worker without stealing each other's replies)
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.streaming.serde import (ndarray_from_bytes,
                                                ndarray_to_bytes)

REQ_SUFFIX = ".req"
HB_SUFFIX = ".hb"

KIND_CLASSIFY = "classify"
KIND_GENERATE = "generate"
KIND_PREFILL = "prefill"

STATE_SERVING = "serving"
STATE_DRAINING = "draining"
STATE_STOPPED = "stopped"

#: Wire protocol version, carried as ``v`` in every request header.
#: v1: request/reply + heartbeat. v2: chunked decode replies (per-burst
#: token deltas tagged with sequence offsets; the terminal frame still
#: carries the final payload) and resume requests (``gen.prefix`` — the
#: already-generated tokens a migrated stream re-prefills instead of
#: re-generating). v3: disaggregated prefill/decode — ``prefill``
#: request kind (the reply ships the prompt's KV as a TAGGED tensor
#: chunk, :func:`pack_tensor_chunk`, then the terminal frame carries
#: the last-token logits), and ``generate`` requests whose body is a
#: shipped KV tensor (``gen.kv`` set; the prompt ids ride the header as
#: ``gen.prompt``). A worker receiving a frame NEWER than it speaks
#: rejects it with a typed :class:`WireVersionError` rather than
#: serving it garbled.
WIRE_VERSION = 3


class WireVersionError(RuntimeError):
    """The peer speaks a newer wire protocol than this worker: the
    request was rejected typed (never half-parsed) — upgrade the worker
    or drop the client's feature set."""


def check_version(header: Dict[str, Any]) -> None:
    v = int(header.get("v", 1))
    if v > WIRE_VERSION:
        raise WireVersionError(
            f"frame speaks wire v{v}; this worker speaks v{WIRE_VERSION}")


def pack_frame(header: Dict[str, Any], body: bytes = b"") -> bytes:
    h = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack(">I", len(h)) + h + body


def unpack_frame(payload: bytes) -> Tuple[Dict[str, Any], bytes]:
    if len(payload) < 4:
        raise ValueError(f"short frame ({len(payload)} bytes)")
    (hlen,) = struct.unpack(">I", payload[:4])
    if 4 + hlen > len(payload):
        raise ValueError("header length exceeds frame")
    header = json.loads(payload[4:4 + hlen])
    return header, payload[4 + hlen:]


def pack_request(corr_id: str, reply_topic: str, kind: str, x: np.ndarray,
                 gen: Optional[Dict[str, Any]] = None,
                 model: Optional[str] = None,
                 version: Optional[int] = None,
                 session: Optional[str] = None,
                 trace: Optional[Dict[str, str]] = None) -> bytes:
    """``trace`` is the OPTIONAL propagated request-trace context
    (``monitor/reqtrace.py`` ``TraceContext.wire()``: ``{"id", "span"}``
    strings). It rides the header WITHOUT a wire-version bump — the
    same discipline as every other optional header field: a consumer
    that predates it never reads the key, so a newer router tracing
    against an older worker serves correctly (the merged trace is
    merely gappy on that hop, never corrupt)."""
    header = {"id": corr_id, "reply": reply_topic, "kind": kind,
              "v": WIRE_VERSION}
    if gen is not None:
        header["gen"] = gen
    if model is not None:
        header["model"] = model
    if version is not None:
        header["version"] = int(version)
    if session is not None:
        header["session"] = session
    if trace is not None:
        header["trace"] = trace
    return pack_frame(header, ndarray_to_bytes(x))


def unpack_request(payload: bytes) -> Tuple[Dict[str, Any], np.ndarray]:
    header, body = unpack_frame(payload)
    return header, ndarray_from_bytes(body)


def _error_fields(error) -> Dict[str, Any]:
    """Wire encoding of an error reply: message + type name + any
    wire-safe payload the reconstructed exception needs."""
    if isinstance(error, BaseException):
        fields: Dict[str, Any] = {"error": str(error),
                                  "etype": type(error).__name__}
        retry = getattr(error, "retry_after_s", None)
        if retry is not None:
            fields["retry_after_s"] = float(retry)
        return fields
    return {"error": str(error)}


def pack_reply(corr_id: str, result: Optional[np.ndarray] = None,
               error=None) -> bytes:
    """``error`` may be a string (legacy) or an exception instance —
    the latter ships typed so :func:`typed_error` can reconstruct it."""
    if error is not None:
        header = {"id": corr_id, "ok": False}
        header.update(_error_fields(error))
        return pack_frame(header)
    return pack_frame({"id": corr_id, "ok": True},
                      ndarray_to_bytes(result))


def pack_chunk(corr_id: str, offset: int, tokens: np.ndarray) -> bytes:
    """A v2 incremental decode chunk: ``tokens`` are the stream's
    generated ids at sequence offsets ``[offset, offset + len)`` (offset
    0 = the first GENERATED token, prompt excluded). Chunks are
    advisory progress — the terminal :func:`pack_reply` still carries
    the full payload, so a consumer that drops chunks stays correct and
    a consumer that dedupes by offset never double-delivers."""
    return pack_frame(
        {"id": corr_id, "ok": True, "chunk": True, "off": int(offset),
         "v": WIRE_VERSION},
        ndarray_to_bytes(np.asarray(tokens, np.int64)))


def is_chunk(header: Dict[str, Any]) -> bool:
    return bool(header.get("chunk"))


def pack_tensor_chunk(corr_id: str, tag: str, tensor: np.ndarray) -> bytes:
    """A v3 TAGGED tensor chunk: a non-terminal frame carrying a named
    tensor payload (``tag`` — e.g. ``"kv"`` for a prefill reply's
    shipped cache). Like token chunks, tensor chunks never resolve the
    request — the terminal :func:`pack_reply` still does — and a
    consumer that cannot use the tag drops the chunk and stays
    correct."""
    return pack_frame(
        {"id": corr_id, "ok": True, "chunk": True, "tag": str(tag),
         "v": WIRE_VERSION},
        ndarray_to_bytes(np.asarray(tensor)))


def chunk_tag(header: Dict[str, Any]) -> Optional[str]:
    return header.get("tag")


def _typed_error_registry() -> Dict[str, Any]:
    """The engine-error family that crosses the wire typed. Imported
    lazily — wire.py sits below router/registry in the import graph."""
    from deeplearning4j_tpu.parallel.inference import (EngineShutdown,
                                                       InferenceBackpressure,
                                                       SliceDegraded)
    from deeplearning4j_tpu.serving.continuous import (DecodeBurstError,
                                                       KVPoolExhausted)
    from deeplearning4j_tpu.serving.registry import (ModelQuarantined,
                                                     ModelUnavailable)
    from deeplearning4j_tpu.serving.router import RetryAfter
    from deeplearning4j_tpu.util.model_serializer import \
        CheckpointCorruptError
    return {
        "InferenceBackpressure": InferenceBackpressure,
        "ModelUnavailable": ModelUnavailable,
        "ModelQuarantined": ModelQuarantined,
        "CheckpointCorruptError": CheckpointCorruptError,
        "RetryAfter": RetryAfter,
        "DecodeBurstError": DecodeBurstError,
        "KVPoolExhausted": KVPoolExhausted,
        "WireVersionError": WireVersionError,
        "SliceDegraded": SliceDegraded,
        "EngineShutdown": EngineShutdown,
    }


def typed_error(header: Dict[str, Any],
                fallback=None) -> BaseException:
    """Reconstruct a reply header's error as the SAME exception type
    the remote engine raised, when it is one of the registered
    wire-safe types; otherwise build ``fallback(message)`` (default
    ``RuntimeError``). The contract the router depends on: a remote
    worker's shed/quarantine is indistinguishable, by type, from a
    local engine's."""
    msg = str(header.get("error", "remote error"))
    etype = header.get("etype")
    cls = _typed_error_registry().get(etype) if etype else None
    if cls is not None:
        if etype == "RetryAfter":
            return cls(msg, float(header.get("retry_after_s", 0.0)))
        return cls(msg)
    return (fallback or RuntimeError)(msg)


def unpack_reply(payload: bytes) -> Tuple[Dict[str, Any],
                                          Optional[np.ndarray]]:
    header, body = unpack_frame(payload)
    return header, (ndarray_from_bytes(body) if header.get("ok") else None)


def pack_heartbeat(name: str, seq: int, state: str,
                   stats: Dict[str, Any]) -> bytes:
    return json.dumps({"name": name, "seq": seq, "state": state,
                       "stats": stats}, separators=(",", ":")).encode()


def unpack_heartbeat(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload)
