"""Request/reply + heartbeat wire format for the serving tier.

The fleet speaks over any :class:`~deeplearning4j_tpu.streaming.broker.
MessageBroker` (in-memory in tests, ``TcpBroker`` across hosts), so the
router ↔ engine-worker channel is framed *inside* broker payloads:

- request / reply: u32 big-endian header length + JSON header + binary
  body (npz via ``streaming/serde.py`` — self-describing dtype+shape).
  The header carries the correlation id (``id``), the caller's private
  reply topic (``reply``), and the request kind (``classify`` /
  ``generate`` with its sampler params). Correlation ids make the
  channel safe for pipelining: replies may arrive out of order and the
  endpoint matches them back to futures by id, never by position.
- heartbeat: plain JSON — worker name, monotonically increasing
  ``seq``, lifecycle ``state`` (serving / draining / stopped) and the
  engine's ``stats()`` snapshot. The router's health plane consumes
  these instead of inferring engine death from reply timeouts alone.

Topic layout for a worker serving ``service``::

    <service>.req          requests (worker consumes)
    <service>.hb           heartbeats (router consumes)
    <reply topic from the request header>   replies (router consumes;
        one private topic per router/client, so N routers can share a
        worker without stealing each other's replies)
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.streaming.serde import (ndarray_from_bytes,
                                                ndarray_to_bytes)

REQ_SUFFIX = ".req"
HB_SUFFIX = ".hb"

KIND_CLASSIFY = "classify"
KIND_GENERATE = "generate"

STATE_SERVING = "serving"
STATE_DRAINING = "draining"
STATE_STOPPED = "stopped"


def pack_frame(header: Dict[str, Any], body: bytes = b"") -> bytes:
    h = json.dumps(header, separators=(",", ":")).encode()
    return struct.pack(">I", len(h)) + h + body


def unpack_frame(payload: bytes) -> Tuple[Dict[str, Any], bytes]:
    if len(payload) < 4:
        raise ValueError(f"short frame ({len(payload)} bytes)")
    (hlen,) = struct.unpack(">I", payload[:4])
    if 4 + hlen > len(payload):
        raise ValueError("header length exceeds frame")
    header = json.loads(payload[4:4 + hlen])
    return header, payload[4 + hlen:]


def pack_request(corr_id: str, reply_topic: str, kind: str, x: np.ndarray,
                 gen: Optional[Dict[str, Any]] = None) -> bytes:
    header = {"id": corr_id, "reply": reply_topic, "kind": kind}
    if gen is not None:
        header["gen"] = gen
    return pack_frame(header, ndarray_to_bytes(x))


def unpack_request(payload: bytes) -> Tuple[Dict[str, Any], np.ndarray]:
    header, body = unpack_frame(payload)
    return header, ndarray_from_bytes(body)


def pack_reply(corr_id: str, result: Optional[np.ndarray] = None,
               error: Optional[str] = None) -> bytes:
    if error is not None:
        return pack_frame({"id": corr_id, "ok": False, "error": error})
    return pack_frame({"id": corr_id, "ok": True},
                      ndarray_to_bytes(result))


def unpack_reply(payload: bytes) -> Tuple[Dict[str, Any],
                                          Optional[np.ndarray]]:
    header, body = unpack_frame(payload)
    return header, (ndarray_from_bytes(body) if header.get("ok") else None)


def pack_heartbeat(name: str, seq: int, state: str,
                   stats: Dict[str, Any]) -> bytes:
    return json.dumps({"name": name, "seq": seq, "state": state,
                       "stats": stats}, separators=(",", ":")).encode()


def unpack_heartbeat(payload: bytes) -> Dict[str, Any]:
    return json.loads(payload)
