"""ScalePolicy — queue-depth / p99-driven fleet sizing decisions.

The router only *observes* load; this policy turns its
``fleet_snapshot()`` into explicit ``add`` / ``remove`` endpoint
decisions a fleet manager (``LocalFleet`` here, a k8s operator in a
real deployment) applies. Decisions are pure functions of the
snapshot + the policy's own hysteresis state, and time is an explicit
argument — the same snapshot sequence always yields the same decision
sequence, so autoscaling is unit-testable without a clock.

Scale-up triggers on EITHER signal (queue backlog per healthy endpoint
above ``target_queue_per_endpoint``, or p99 above ``p99_high_ms``);
scale-down only when BOTH are comfortably low (backlog under
``queue_low`` per endpoint and p99 under half the high-water mark) —
the asymmetry is deliberate: adding capacity late costs SLO, removing
it late costs only money. ``cooldown_s`` gates consecutive decisions
so one burst cannot flap the fleet.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional


class ScaleDecision(NamedTuple):
    action: str               # "add" | "remove"
    endpoint: Optional[str]   # which to remove (None for add)
    reason: str


class ScalePolicy:
    def __init__(self, min_endpoints: int = 1, max_endpoints: int = 8,
                 target_queue_per_endpoint: float = 4.0,
                 queue_low: float = 0.5,
                 p99_high_ms: Optional[float] = None,
                 cooldown_s: float = 5.0):
        self.min_endpoints = int(min_endpoints)
        self.max_endpoints = int(max_endpoints)
        self.target_queue = float(target_queue_per_endpoint)
        self.queue_low = float(queue_low)
        self.p99_high_ms = p99_high_ms
        self.cooldown_s = float(cooldown_s)
        self._last_decision_at: Optional[float] = None

    def decide(self, snapshot: Dict[str, Any],
               now: float) -> List[ScaleDecision]:
        """One add/remove decision (or none) from a router
        ``fleet_snapshot()``. ``now`` is any monotonic clock the caller
        owns — pass a counter in tests for full determinism."""
        if self._last_decision_at is not None and \
                now - self._last_decision_at < self.cooldown_s:
            return []
        # slice fault domain first: an endpoint whose heartbeats carry
        # a DEGRADED slice (a chip inside it died) is rebuilt at a
        # narrower width from the survivors — the mesh-portable-
        # checkpoint 8→4→1 ladder — before any add/remove sizing. Same
        # cooldown discipline: one rebuild decision per window.
        for name in sorted(snapshot.get("endpoints") or {}):
            info = (snapshot.get("endpoints") or {})[name]
            sl = info.get("slice") or (info.get("stats") or {}).get("slice")
            if isinstance(sl, dict) and sl.get("degraded"):
                self._last_decision_at = now
                return [ScaleDecision(
                    "rebuild", name,
                    f"slice degraded (width {sl.get('width')}, devices "
                    f"{sl.get('devices')}) — rebuild from survivors")]
        healthy = max(0, int(snapshot.get("healthy_endpoints", 0)))
        total = int(snapshot.get("total_endpoints", 0))
        backlog = float(snapshot.get("queue_depth", 0.0))
        p99 = snapshot.get("p99_ms")
        per_ep = backlog / healthy if healthy else float("inf")
        decisions: List[ScaleDecision] = []
        if total < self.min_endpoints:
            decisions.append(ScaleDecision(
                "add", None, f"below min_endpoints ({total} < "
                f"{self.min_endpoints})"))
        elif total < self.max_endpoints and (
                per_ep > self.target_queue
                or (self.p99_high_ms is not None and p99 is not None
                    and p99 > self.p99_high_ms)):
            decisions.append(ScaleDecision(
                "add", None,
                f"backlog/endpoint {per_ep:.1f} > {self.target_queue} "
                f"or p99 {p99} > {self.p99_high_ms}"))
        elif total > self.min_endpoints and healthy == total and \
                per_ep < self.queue_low and (
                    self.p99_high_ms is None or p99 is None
                    or p99 < self.p99_high_ms / 2):
            victim = self._pick_victim(snapshot)
            if victim is not None:
                decisions.append(ScaleDecision(
                    "remove", victim,
                    f"backlog/endpoint {per_ep:.2f} < {self.queue_low}"))
        if decisions:
            self._last_decision_at = now
        return decisions

    @staticmethod
    def _pick_victim(snapshot: Dict[str, Any]) -> Optional[str]:
        """Least-loaded endpoint with no pinned sessions preferred;
        stable name order for determinism."""
        eps = snapshot.get("endpoints") or {}
        candidates = sorted(
            (info.get("inflight", 0),
             float(info.get("stats", {}).get("queue_depth", 0) or 0), name)
            for name, info in eps.items() if info.get("in_pool"))
        return candidates[0][2] if candidates else None
