"""Process-mode engine worker: ``python -m
deeplearning4j_tpu.serving.procworker --broker host:port --service s
--model model.zip``.

One OS process = one fleet endpoint: load the model zip, build a
``ParallelInference`` engine, optionally AOT-warm it, and serve the
broker request channel until SIGTERM (drain, then exit 0) or SIGKILL
(the failure mode the router's failover exists for). This is the
deployment shape of :class:`~deeplearning4j_tpu.serving.worker.
EngineWorker`; ``LocalFleet(mode="process")`` spawns it.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--broker", required=True, help="host:port")
    ap.add_argument("--service", required=True)
    ap.add_argument("--model", required=True, help="model zip path")
    ap.add_argument("--heartbeat-s", type=float, default=0.25)
    ap.add_argument("--max-batch-size", type=int, default=32)
    ap.add_argument("--max-latency-ms", type=float, default=5.0)
    ap.add_argument("--replicas", type=int, default=None)
    ap.add_argument("--warmup-shapes", default=None,
                    help='JSON list of per-example shapes, e.g. "[[64]]"')
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving.worker import EngineWorker
    from deeplearning4j_tpu.streaming.broker import TcpBroker
    from deeplearning4j_tpu.util.model_serializer import restore_model

    host, port = args.broker.rsplit(":", 1)
    net = restore_model(args.model)
    engine = ParallelInference(net, max_batch_size=args.max_batch_size,
                               max_latency_ms=args.max_latency_ms,
                               replicas=args.replicas)
    if args.warmup_shapes:
        engine.warmup([tuple(s) for s in json.loads(args.warmup_shapes)])
    worker = EngineWorker(engine, TcpBroker(host, int(port)), args.service,
                          reply_broker=TcpBroker(host, int(port)),
                          hb_broker=TcpBroker(host, int(port)),
                          heartbeat_s=args.heartbeat_s)

    done = threading.Event()

    def _term(signum, frame):
        worker.drain_and_stop(timeout=30.0)
        done.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    done.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
