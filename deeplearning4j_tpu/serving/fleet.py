"""LocalFleet — spawn, kill, restart and autoscale engine endpoints.

The fleet manager the tests and the ``router_slo`` bench drive: it
owns a broker, spawns engine workers (each with its OWN
``ParallelInference`` engine from ``engine_factory``), wires a
``RemoteEndpoint`` per worker, and applies
:class:`~deeplearning4j_tpu.serving.policy.ScalePolicy` decisions.

Endpoint modes:

- ``mode="thread"`` (default): workers run on daemon threads in this
  process, reached through the SAME broker wire protocol remote
  workers use. ``kill()`` stops a worker abruptly — no replies, no
  heartbeats, requests already consumed vanish — which is exactly the
  wire signature of SIGKILL on an engine process, while staying
  deterministic and safe on this box (the conftest notes:
  fork-after-jax segfaults, so tier-1 tests must not spawn compute
  subprocesses).
- ``mode="process"``: workers are real OS processes
  (``python -m deeplearning4j_tpu.serving.procworker``) reached over a
  ``TcpBrokerServer``; ``kill()`` is SIGKILL. The model is shipped as
  a zip via ``util/model_serializer``. For benches/deployments — not
  used by tier-1 tests.
"""

from __future__ import annotations

import itertools
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.serving.endpoint import RemoteEndpoint
from deeplearning4j_tpu.serving.policy import ScaleDecision, ScalePolicy
from deeplearning4j_tpu.serving.worker import EngineWorker
from deeplearning4j_tpu.streaming.broker import (InMemoryBroker,
                                                 MessageBroker, TcpBroker,
                                                 TcpBrokerServer)

logger = logging.getLogger("deeplearning4j_tpu")


class _Member:
    """One fleet slot: endpoint + however it is backed."""

    def __init__(self, name: str, endpoint: RemoteEndpoint,
                 worker: Optional[EngineWorker] = None,
                 proc: Optional[subprocess.Popen] = None):
        self.name = name
        self.endpoint = endpoint
        self.worker = worker
        self.proc = proc


class LocalFleet:
    """Manage a fleet of engine endpoints behind one broker.

    ``engine_factory()`` must return a fresh started
    ``ParallelInference`` (thread mode). ``router=`` (optional) keeps
    an :class:`InferenceRouter` membership in sync with the fleet.
    """

    def __init__(self, engine_factory: Optional[Callable] = None,
                 mode: str = "thread",
                 service_prefix: str = "engine",
                 router=None,
                 heartbeat_s: float = 0.1,
                 request_timeout_s: float = 5.0,
                 heartbeat_timeout_s: float = 1.0,
                 model_path: Optional[str] = None,
                 procworker_args: Optional[List[str]] = None):
        if mode not in ("thread", "process"):
            raise ValueError(f"mode must be thread|process, got {mode!r}")
        if mode == "thread" and engine_factory is None:
            raise ValueError("thread mode needs engine_factory")
        if mode == "process" and model_path is None:
            raise ValueError("process mode needs model_path")
        self.mode = mode
        self.engine_factory = engine_factory
        self.service_prefix = service_prefix
        self.router = router
        self.heartbeat_s = float(heartbeat_s)
        self.request_timeout_s = float(request_timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.model_path = model_path
        self.procworker_args = list(procworker_args or [])
        self._members: Dict[str, _Member] = {}
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._server: Optional[TcpBrokerServer] = None
        if mode == "process":
            self._server = TcpBrokerServer().start()
            self._broker: MessageBroker = self._connect()
        else:
            self._broker = InMemoryBroker()

    def _connect(self) -> MessageBroker:
        if self._server is not None:
            host, port = self._server.address
            return TcpBroker(host, port)
        return self._broker

    # --------------------------------------------------------- members

    def add_endpoint(self, name: Optional[str] = None) -> RemoteEndpoint:
        name = name or f"{self.service_prefix}-{next(self._ids)}"
        service = name
        if self.mode == "thread":
            engine = self.engine_factory()
            worker = EngineWorker(engine, self._broker, service, name=name,
                                  heartbeat_s=self.heartbeat_s)
            proc = None
        else:
            worker = None
            host, port = self._server.address
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearning4j_tpu.serving.procworker",
                 "--broker", f"{host}:{port}", "--service", service,
                 "--model", self.model_path,
                 "--heartbeat-s", str(self.heartbeat_s),
                 *self.procworker_args])
        factory = (self._connect if self._server is not None else None)
        endpoint = RemoteEndpoint(
            self._connect(), service, name=name, broker_factory=factory,
            request_timeout_s=self.request_timeout_s,
            heartbeat_timeout_s=self.heartbeat_timeout_s)
        with self._lock:
            self._members[name] = _Member(name, endpoint, worker, proc)
        if self.router is not None:
            self.router.add_endpoint(endpoint)
        return endpoint

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def endpoint(self, name: str) -> RemoteEndpoint:
        with self._lock:
            return self._members[name].endpoint

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Block until every member heartbeats alive (bounded)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                members = list(self._members.values())
            if members and all(m.endpoint.alive() for m in members):
                return True
            time.sleep(5e-3)
        return False

    # ------------------------------------------------------ fault seams

    def kill(self, name: str) -> None:
        """Abrupt endpoint death (the faultinject process-kill seam):
        thread mode stops the worker without replies or heartbeats;
        process mode SIGKILLs. The endpoint object stays registered —
        the router observes the death through missed heartbeats and
        reply timeouts, exactly as it would a remote host loss."""
        with self._lock:
            m = self._members[name]
        if m.worker is not None:
            m.worker.kill()
            try:  # the process's engine dies with it
                m.worker.engine.shutdown(drain=False)
            except BaseException:
                pass
        if m.proc is not None:
            m.proc.send_signal(signal.SIGKILL)
            m.proc.wait(timeout=10)
        logger.info("fleet: killed %s", name)

    def wedge(self, name: str) -> None:
        """Faultinject seam (thread mode): the member keeps
        heartbeating but silently drops every consumed request — the
        liveness-without-progress failure the router's wedge watchdog
        exists for."""
        with self._lock:
            m = self._members[name]
        if m.worker is None:
            raise RuntimeError("wedge() is a thread-mode seam")
        m.worker.wedge()
        logger.info("fleet: wedged %s", name)

    def unwedge(self, name: str) -> None:
        with self._lock:
            m = self._members[name]
        if m.worker is not None:
            m.worker.unwedge()
        logger.info("fleet: unwedged %s", name)

    def restart(self, name: str) -> None:
        """Bring a killed member back on the SAME service topics (the
        endpoint reconnects through its existing consumer threads)."""
        with self._lock:
            m = self._members[name]
        if self.mode == "thread":
            if m.worker is not None and not m.worker._killed.is_set():
                m.worker.kill()
            engine = self.engine_factory()
            m.worker = EngineWorker(engine, self._broker, name, name=name,
                                    heartbeat_s=self.heartbeat_s)
        else:
            host, port = self._server.address
            m.proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearning4j_tpu.serving.procworker",
                 "--broker", f"{host}:{port}", "--service", name,
                 "--model", self.model_path,
                 "--heartbeat-s", str(self.heartbeat_s),
                 *self.procworker_args])
        logger.info("fleet: restarted %s", name)

    def remove_endpoint(self, name: str,
                        drain_timeout: float = 30.0) -> None:
        """Planned scale-down: drain, stop, deregister — zero lost
        requests."""
        with self._lock:
            m = self._members.pop(name)
        if self.router is not None:
            self.router.remove_endpoint(name)
        if m.worker is not None:
            m.worker.drain_and_stop(timeout=drain_timeout)
        if m.proc is not None:
            m.proc.terminate()  # procworker drains on SIGTERM
            try:
                m.proc.wait(timeout=drain_timeout)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                m.proc.wait(timeout=10)
        m.endpoint.close()

    # -------------------------------------------------------- autoscale

    def apply(self, decisions: List[ScaleDecision]) -> List[str]:
        """Apply ScalePolicy decisions; returns a log of actions."""
        log = []
        for d in decisions:
            if d.action == "add":
                ep = self.add_endpoint()
                log.append(f"add {ep.name}: {d.reason}")
            elif d.action == "remove" and d.endpoint in self._members:
                self.remove_endpoint(d.endpoint)
                log.append(f"remove {d.endpoint}: {d.reason}")
        return log

    def autoscale(self, policy: ScalePolicy,
                  now: Optional[float] = None) -> List[str]:
        """One policy step against the live router snapshot."""
        if self.router is None:
            raise RuntimeError("autoscale needs a router")
        snap = self.router.fleet_snapshot()
        return self.apply(policy.decide(
            snap, time.monotonic() if now is None else now))

    # -------------------------------------------------------- lifecycle

    def shutdown(self, drain: bool = True) -> None:
        for name in self.names():
            try:
                if drain:
                    self.remove_endpoint(name, drain_timeout=10.0)
                else:
                    self.kill(name)
                    with self._lock:
                        m = self._members.pop(name, None)
                    if m is not None:
                        m.endpoint.close()
            except KeyError:
                pass
        if self._server is not None:
            self._server.stop()

    def __enter__(self) -> "LocalFleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(drain=exc_type is None)
