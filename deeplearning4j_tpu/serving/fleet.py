"""LocalFleet — spawn, kill, restart and autoscale engine endpoints.

The fleet manager the tests and the ``router_slo`` bench drive: it
owns a broker, spawns engine workers (each with its OWN
``ParallelInference`` engine from ``engine_factory``), wires a
``RemoteEndpoint`` per worker, and applies
:class:`~deeplearning4j_tpu.serving.policy.ScalePolicy` decisions.

Endpoint modes:

- ``mode="thread"`` (default): workers run on daemon threads in this
  process, reached through the SAME broker wire protocol remote
  workers use. ``kill()`` stops a worker abruptly — no replies, no
  heartbeats, requests already consumed vanish — which is exactly the
  wire signature of SIGKILL on an engine process, while staying
  deterministic and safe on this box (the conftest notes:
  fork-after-jax segfaults, so tier-1 tests must not spawn compute
  subprocesses).
- ``mode="process"``: workers are real OS processes
  (``python -m deeplearning4j_tpu.serving.procworker``) reached over a
  ``TcpBrokerServer``; ``kill()`` is SIGKILL. The model is shipped as
  a zip via ``util/model_serializer``. For benches/deployments — not
  used by tier-1 tests.
"""

from __future__ import annotations

import itertools
import logging
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from deeplearning4j_tpu.serving.endpoint import RemoteEndpoint
from deeplearning4j_tpu.serving.policy import ScaleDecision, ScalePolicy
from deeplearning4j_tpu.serving.worker import EngineWorker
from deeplearning4j_tpu.streaming.broker import (InMemoryBroker,
                                                 MessageBroker, TcpBroker,
                                                 TcpBrokerServer)

logger = logging.getLogger("deeplearning4j_tpu")


class _Member:
    """One fleet slot: endpoint + however it is backed."""

    def __init__(self, name: str, endpoint: RemoteEndpoint,
                 worker: Optional[EngineWorker] = None,
                 proc: Optional[subprocess.Popen] = None,
                 plane=None):
        self.name = name
        self.endpoint = endpoint
        self.worker = worker
        self.proc = proc
        # mesh-slice backing (slice_width mode): the MeshPlane this
        # member's engine is sharded over — rebuild_slice narrows it
        self.plane = plane


class LocalFleet:
    """Manage a fleet of engine endpoints behind one broker.

    ``engine_factory()`` must return a fresh started
    ``ParallelInference`` (thread mode). ``router=`` (optional) keeps
    an :class:`InferenceRouter` membership in sync with the fleet.
    """

    def __init__(self, engine_factory: Optional[Callable] = None,
                 mode: str = "thread",
                 service_prefix: str = "engine",
                 router=None,
                 heartbeat_s: float = 0.1,
                 request_timeout_s: float = 5.0,
                 heartbeat_timeout_s: float = 1.0,
                 model_path: Optional[str] = None,
                 procworker_args: Optional[List[str]] = None,
                 slice_width: Optional[int] = None,
                 slice_devices: Optional[List] = None):
        if mode not in ("thread", "process"):
            raise ValueError(f"mode must be thread|process, got {mode!r}")
        if mode == "thread" and engine_factory is None:
            raise ValueError("thread mode needs engine_factory")
        if mode == "process" and model_path is None:
            raise ValueError("process mode needs model_path")
        if slice_width is not None and mode != "thread":
            raise ValueError("slice_width is a thread-mode feature")
        self.mode = mode
        self.engine_factory = engine_factory
        # mesh-sharded slices: each endpoint's engine runs on a
        # slice_width-chip MeshPlane carved from slice_devices (default:
        # every local device); engine_factory is then called WITH the
        # plane — restore the mesh-portable checkpoint onto it. The
        # device budget is explicit: killing a chip shrinks a member's
        # slice (rebuild_slice), trading width for replica count.
        self.slice_width = None if slice_width is None else int(slice_width)
        self._slice_free: List = []
        if self.slice_width is not None:
            import jax
            self._slice_free = list(
                slice_devices if slice_devices is not None
                else jax.devices())
        self.service_prefix = service_prefix
        self.router = router
        self.heartbeat_s = float(heartbeat_s)
        self.request_timeout_s = float(request_timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.model_path = model_path
        self.procworker_args = list(procworker_args or [])
        self._members: Dict[str, _Member] = {}
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._server: Optional[TcpBrokerServer] = None
        if mode == "process":
            self._server = TcpBrokerServer().start()
            self._broker: MessageBroker = self._connect()
        else:
            self._broker = InMemoryBroker()

    def _connect(self) -> MessageBroker:
        if self._server is not None:
            host, port = self._server.address
            return TcpBroker(host, port)
        return self._broker

    # --------------------------------------------------------- members

    def _carve_slice(self, width: int):
        """Claim ``width`` devices from the free budget and build the
        slice's MeshPlane (via the sanctioned parallel.mesh factory —
        serving code never constructs a raw Mesh)."""
        from deeplearning4j_tpu.parallel.mesh import MeshPlane
        if len(self._slice_free) < width:
            raise RuntimeError(
                f"no device budget for a {width}-chip slice "
                f"({len(self._slice_free)} free)")
        devs, self._slice_free = (self._slice_free[:width],
                                  self._slice_free[width:])
        return MeshPlane.build({"tp": width}, devices=devs)

    def add_endpoint(self, name: Optional[str] = None) -> RemoteEndpoint:
        name = name or f"{self.service_prefix}-{next(self._ids)}"
        service = name
        plane = None
        if self.mode == "thread":
            if self.slice_width is not None:
                plane = self._carve_slice(self.slice_width)
                engine = self.engine_factory(plane)
            else:
                engine = self.engine_factory()
            worker = EngineWorker(engine, self._broker, service, name=name,
                                  heartbeat_s=self.heartbeat_s)
            proc = None
        else:
            worker = None
            host, port = self._server.address
            proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearning4j_tpu.serving.procworker",
                 "--broker", f"{host}:{port}", "--service", service,
                 "--model", self.model_path,
                 "--heartbeat-s", str(self.heartbeat_s),
                 *self.procworker_args])
        factory = (self._connect if self._server is not None else None)
        endpoint = RemoteEndpoint(
            self._connect(), service, name=name, broker_factory=factory,
            request_timeout_s=self.request_timeout_s,
            heartbeat_timeout_s=self.heartbeat_timeout_s)
        with self._lock:
            self._members[name] = _Member(name, endpoint, worker, proc,
                                          plane)
        if self.router is not None:
            self.router.add_endpoint(endpoint)
        return endpoint

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._members)

    def endpoint(self, name: str) -> RemoteEndpoint:
        with self._lock:
            return self._members[name].endpoint

    def timeseries_summary(self) -> Dict[str, Any]:
        """Fleet-wide window answer from the heartbeat-carried
        per-endpoint summaries (engine batch fill ratio, jit-miss
        rate, worker served delta): counts and rates add across
        members, means combine count-weighted, p99 takes the max —
        the same merge :meth:`InferenceRouter.fleet_snapshot`
        reports, available without a router."""
        from deeplearning4j_tpu.monitor import merge_summaries
        with self._lock:
            members = list(self._members.values())
        summaries = []
        for m in members:
            try:
                ts = (m.endpoint.stats() or {}).get("timeseries")
            except Exception:
                continue  # a dead member answers no window queries
            if isinstance(ts, dict):
                summaries.append(ts)
        return merge_summaries(summaries)

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Block until every member heartbeats alive (bounded)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                members = list(self._members.values())
            if members and all(m.endpoint.alive() for m in members):
                return True
            time.sleep(5e-3)
        return False

    # ------------------------------------------------------ fault seams

    def kill(self, name: str) -> None:
        """Abrupt endpoint death (the faultinject process-kill seam):
        thread mode stops the worker without replies or heartbeats;
        process mode SIGKILLs. The endpoint object stays registered —
        the router observes the death through missed heartbeats and
        reply timeouts, exactly as it would a remote host loss."""
        with self._lock:
            m = self._members[name]
        if m.worker is not None:
            m.worker.kill()
            try:  # the process's engine dies with it
                m.worker.engine.shutdown(drain=False)
            except BaseException:
                pass
        if m.proc is not None:
            m.proc.send_signal(signal.SIGKILL)
            m.proc.wait(timeout=10)
        logger.info("fleet: killed %s", name)

    def wedge(self, name: str) -> None:
        """Faultinject seam (thread mode): the member keeps
        heartbeating but silently drops every consumed request — the
        liveness-without-progress failure the router's wedge watchdog
        exists for."""
        with self._lock:
            m = self._members[name]
        if m.worker is None:
            raise RuntimeError("wedge() is a thread-mode seam")
        m.worker.wedge()
        logger.info("fleet: wedged %s", name)

    def unwedge(self, name: str) -> None:
        with self._lock:
            m = self._members[name]
        if m.worker is not None:
            m.worker.unwedge()
        logger.info("fleet: unwedged %s", name)

    def kill_chip(self, name: str, victim: Optional[int] = None,
                  seed: int = 0):
        """Faultinject seam (thread + slice mode): arm a seeded
        :class:`~deeplearning4j_tpu.faultinject.SliceKill` on the
        member's engine — its next dispatch (classify batch or decode
        burst) raises a ``ChipFailure`` naming the slice's survivors,
        the engine poisons the whole slice (typed ``SliceDegraded`` in
        heartbeats, never silence), and the router migrates its
        streams. Returns the injector so the drill can read the victim
        chip it chose."""
        from deeplearning4j_tpu.faultinject import SliceKill
        with self._lock:
            m = self._members[name]
        if m.worker is None or m.plane is None:
            raise RuntimeError("kill_chip() is a thread+slice-mode seam")
        eng = m.worker.engine
        inj = SliceKill(m.plane, victim=victim, seed=seed, fail_at=0)
        eng._poison_hook = inj
        if eng._scheduler is not None:
            eng._scheduler._burst_hook = inj
        else:
            eng._decode_burst_hook = inj
        logger.info("fleet: armed chip kill on %s (victim chip %d)",
                    name, inj.victim)
        return inj

    def rebuild_slice(self, name: str, width: Optional[int] = None) -> int:
        """Elastic recovery: the member's slice died (a chip inside it
        failed) — stop the poisoned worker, rebuild a NARROWER slice
        from the survivors (default: half the old width, the 8→4→1
        mesh-portable-checkpoint ladder), hand the new plane to
        ``engine_factory`` (which restores the checkpoint onto it), and
        bring the worker back on the SAME service topics. Unused
        survivor devices return to the free budget — capacity lost as
        width comes back as replica count through the normal ``add``
        path. Returns the new width."""
        from deeplearning4j_tpu.faultinject import ChipFailure
        from deeplearning4j_tpu.monitor import (SLICE_REBUILDS_COUNTER,
                                                get_registry)
        with self._lock:
            m = self._members[name]
        if m.worker is None or m.plane is None:
            raise RuntimeError("rebuild_slice() is a thread+slice-mode "
                               "seam")
        old_devs = list(m.plane.mesh.devices.flat)
        # the dead chip: named by the engine's ChipFailure when it
        # carries survivor ids, else assume the first chip died
        dead_ids = None
        err = getattr(m.worker.engine, "_slice_dead", None)
        seen = 0
        while err is not None and seen < 8:
            if isinstance(err, ChipFailure):
                dead_ids = {d.id for d in old_devs} \
                    - set(err.survivor_ids)
                break
            err = err.__cause__
            seen += 1
        if dead_ids is None:
            dead_ids = {old_devs[0].id}
        survivors = [d for d in old_devs if d.id not in dead_ids]
        new_width = int(width) if width is not None \
            else max(1, len(old_devs) // 2)
        new_width = min(new_width, max(1, len(survivors)))
        if m.worker is not None and not m.worker._killed.is_set():
            m.worker.kill()
        try:
            m.worker.engine.shutdown(drain=False)
        except BaseException:
            pass
        from deeplearning4j_tpu.parallel.mesh import MeshPlane
        plane = MeshPlane.build({"tp": new_width},
                                devices=survivors[:new_width])
        engine = self.engine_factory(plane)
        with self._lock:
            m.plane = plane
            m.worker = EngineWorker(engine, self._broker, name, name=name,
                                    heartbeat_s=self.heartbeat_s)
            # leftover survivors go back to the budget: width traded
            # for replica count under the ScalePolicy's add path
            self._slice_free.extend(survivors[new_width:])
        get_registry().counter(
            SLICE_REBUILDS_COUNTER,
            "Serving slices rebuilt at a narrower width after a chip "
            "death (mesh-portable checkpoint restored onto survivors)",
            width=str(new_width)).inc()
        from deeplearning4j_tpu.monitor.reqtrace import flight_event
        flight_event("slice_rebuild", endpoint=name, width=new_width,
                     survivors=len(survivors))
        logger.info("fleet: rebuilt %s as a %d-chip slice (%d survivors)",
                    name, new_width, len(survivors))
        return new_width

    def restart(self, name: str) -> None:
        """Bring a killed member back on the SAME service topics (the
        endpoint reconnects through its existing consumer threads)."""
        with self._lock:
            m = self._members[name]
        if self.mode == "thread":
            if m.worker is not None and not m.worker._killed.is_set():
                m.worker.kill()
            engine = (self.engine_factory(m.plane) if m.plane is not None
                      else self.engine_factory())
            m.worker = EngineWorker(engine, self._broker, name, name=name,
                                    heartbeat_s=self.heartbeat_s)
        else:
            host, port = self._server.address
            m.proc = subprocess.Popen(
                [sys.executable, "-m",
                 "deeplearning4j_tpu.serving.procworker",
                 "--broker", f"{host}:{port}", "--service", name,
                 "--model", self.model_path,
                 "--heartbeat-s", str(self.heartbeat_s),
                 *self.procworker_args])
        logger.info("fleet: restarted %s", name)

    def remove_endpoint(self, name: str,
                        drain_timeout: float = 30.0) -> None:
        """Planned scale-down: drain, stop, deregister — zero lost
        requests."""
        with self._lock:
            m = self._members.pop(name)
        if self.router is not None:
            self.router.remove_endpoint(name)
        if m.worker is not None:
            m.worker.drain_and_stop(timeout=drain_timeout)
        if m.proc is not None:
            m.proc.terminate()  # procworker drains on SIGTERM
            try:
                m.proc.wait(timeout=drain_timeout)
            except subprocess.TimeoutExpired:
                m.proc.kill()
                m.proc.wait(timeout=10)
        m.endpoint.close()

    # -------------------------------------------------------- autoscale

    def apply(self, decisions: List[ScaleDecision]) -> List[str]:
        """Apply ScalePolicy decisions; returns a log of actions."""
        log = []
        for d in decisions:
            if d.action == "add":
                ep = self.add_endpoint()
                log.append(f"add {ep.name}: {d.reason}")
            elif d.action == "remove" and d.endpoint in self._members:
                self.remove_endpoint(d.endpoint)
                log.append(f"remove {d.endpoint}: {d.reason}")
            elif d.action == "rebuild" and d.endpoint in self._members:
                w = self.rebuild_slice(d.endpoint)
                log.append(f"rebuild {d.endpoint} width={w}: {d.reason}")
        return log

    def autoscale(self, policy: ScalePolicy,
                  now: Optional[float] = None) -> List[str]:
        """One policy step against the live router snapshot."""
        if self.router is None:
            raise RuntimeError("autoscale needs a router")
        snap = self.router.fleet_snapshot()
        return self.apply(policy.decide(
            snap, time.monotonic() if now is None else now))

    # -------------------------------------------------------- lifecycle

    def shutdown(self, drain: bool = True) -> None:
        for name in self.names():
            try:
                if drain:
                    self.remove_endpoint(name, drain_timeout=10.0)
                else:
                    self.kill(name)
                    with self._lock:
                        m = self._members.pop(name, None)
                    if m is not None:
                        m.endpoint.close()
            except KeyError:
                pass
        if self._server is not None:
            self._server.stop()

    def __enter__(self) -> "LocalFleet":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.shutdown(drain=exc_type is None)
