"""ROC / AUC with thresholded accumulation.

Parity: ``eval/ROC.java:33`` — binary ROC computed over a fixed grid of
``threshold_steps`` thresholds (the reference's streaming-friendly
design, kept because it composes over minibatches without storing all
scores).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


class ROC:
    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self.thresholds = np.linspace(0.0, 1.0, threshold_steps + 1)
        self.tp = np.zeros(threshold_steps + 1, np.int64)
        self.fp = np.zeros(threshold_steps + 1, np.int64)
        self.pos = 0
        self.neg = 0

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        """labels: [b] {0,1} or [b,2] one-hot; predictions: P(class 1)
        as [b] or [b,2]."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 2:
            labels = labels[:, 1]
        if predictions.ndim == 2:
            predictions = predictions[:, 1]
        if mask is not None:
            keep = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[keep], predictions[keep]
        pos = labels > 0.5
        self.pos += int(pos.sum())
        self.neg += int((~pos).sum())
        # predicted positive at threshold t: score >= t. One vectorized
        # pass instead of a per-threshold host loop (r3 VERDICT weak #5):
        # searchsorted(thresholds, p, 'right') counts thresholds <= p —
        # exactly how many grid points this sample is predicted-positive
        # at, with the SAME comparison semantics as the old loop — and a
        # histogram tail-sum turns counts into per-threshold totals.
        def _accumulate(counts: np.ndarray, into: np.ndarray) -> None:
            hist = np.bincount(counts, minlength=self.steps + 2)
            tail = hist[::-1].cumsum()[::-1]  # tail[k] = #samples cnt>=k
            into += tail[1:]  # contributes at index i iff cnt >= i+1
        cnt = np.searchsorted(self.thresholds, predictions, side="right")
        # NaN sorts after every threshold; the old `p >= t` loop counted
        # a NaN score predicted-positive at NO threshold — keep that
        cnt = np.where(np.isnan(predictions), 0, cnt)
        _accumulate(cnt[pos], self.tp)
        _accumulate(cnt[~pos], self.fp)

    def get_roc_curve(self) -> List[Tuple[float, float, float]]:
        """[(threshold, fpr, tpr)]"""
        out = []
        for i, t in enumerate(self.thresholds):
            tpr = self.tp[i] / self.pos if self.pos else 0.0
            fpr = self.fp[i] / self.neg if self.neg else 0.0
            out.append((float(t), float(fpr), float(tpr)))
        return out

    def calculate_auc(self) -> float:
        """Trapezoidal AUC over the threshold grid (``ROC.calculateAUC``)."""
        pts = sorted((fpr, tpr) for _, fpr, tpr in self.get_roc_curve())
        xs = np.array([0.0] + [p[0] for p in pts] + [1.0])
        ys = np.array([0.0] + [p[1] for p in pts] + [1.0])
        return float(np.trapezoid(ys, xs))
