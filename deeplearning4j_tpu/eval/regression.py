"""Regression metrics: MSE / MAE / RMSE / R² / correlation, per column.

Parity: ``eval/RegressionEvaluation.java:26`` — accumulating sufficient
statistics per output column so evaluation streams over minibatches.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


class RegressionEvaluation:
    def __init__(self, num_columns: Optional[int] = None,
                 column_names: Optional[Sequence[str]] = None):
        self.column_names = list(column_names) if column_names else None
        if num_columns is None and column_names is not None:
            num_columns = len(column_names)
        self._n = num_columns
        self._init_done = False

    def _ensure(self, n: int):
        if not self._init_done:
            self._n = self._n or n
            z = lambda: np.zeros(self._n, np.float64)
            self.count = z()
            self.sum_abs_err = z()
            self.sum_sq_err = z()
            self.sum_label = z()
            self.sum_label_sq = z()
            self.sum_pred = z()
            self.sum_pred_sq = z()
            self.sum_label_pred = z()
            self._init_done = True

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            keep = (np.asarray(mask).reshape(-1) > 0) if mask is not None \
                else np.ones(labels.shape[0] * labels.shape[1], bool)
            labels = labels.reshape(-1, labels.shape[-1])[keep]
            predictions = predictions.reshape(-1, predictions.shape[-1])[keep]
        self._ensure(labels.shape[-1])
        err = predictions - labels
        self.count += labels.shape[0]
        self.sum_abs_err += np.abs(err).sum(axis=0)
        self.sum_sq_err += (err ** 2).sum(axis=0)
        self.sum_label += labels.sum(axis=0)
        self.sum_label_sq += (labels ** 2).sum(axis=0)
        self.sum_pred += predictions.sum(axis=0)
        self.sum_pred_sq += (predictions ** 2).sum(axis=0)
        self.sum_label_pred += (labels * predictions).sum(axis=0)

    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_sq_err[col] / self.count[col])

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs_err[col] / self.count[col])

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.mean_squared_error(col)))

    def r_squared(self, col: int) -> float:
        n = self.count[col]
        ss_tot = self.sum_label_sq[col] - self.sum_label[col] ** 2 / n
        ss_res = self.sum_sq_err[col]
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0

    def pearson_correlation(self, col: int) -> float:
        n = self.count[col]
        cov = self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col] / n
        vl = self.sum_label_sq[col] - self.sum_label[col] ** 2 / n
        vp = self.sum_pred_sq[col] - self.sum_pred[col] ** 2 / n
        d = np.sqrt(vl * vp)
        return float(cov / d) if d > 0 else 0.0

    def stats(self) -> str:
        cols = range(self._n)
        lines = ["column  MSE        MAE        RMSE       R^2        corr"]
        for c in cols:
            name = self.column_names[c] if self.column_names else str(c)
            lines.append(f"{name:7s} {self.mean_squared_error(c):.4e} "
                         f"{self.mean_absolute_error(c):.4e} "
                         f"{self.root_mean_squared_error(c):.4e} "
                         f"{self.r_squared(c):.4f}    {self.pearson_correlation(c):.4f}")
        return "\n".join(lines)
