"""Classification evaluation: confusion matrix, accuracy/precision/
recall/F1, per-example metadata attribution.

Parity: ``eval/Evaluation.java:46`` (eval :190-264) +
``eval/ConfusionMatrix.java``. Metric math is host-side numpy over
accumulated confusion counts — evaluation is not the hot path; the
device does only the forward pass.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np


class ConfusionMatrix:
    """Counts[actual][predicted] (``ConfusionMatrix.java``)."""

    def __init__(self, num_classes: int):
        self.num_classes = num_classes
        self.counts = np.zeros((num_classes, num_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.counts[actual, predicted] += count

    def add_batch(self, actual: np.ndarray, predicted: np.ndarray):
        np.add.at(self.counts, (actual, predicted), 1)

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.counts[actual, predicted])

    def __str__(self):
        return str(self.counts)


class Evaluation:
    """Accumulating classification evaluator.

    ``eval(labels, predictions)`` accepts one-hot (or probability) arrays
    of shape [b, C] or time-series [b, T, C] with an optional [b, T] mask
    (the reference reshapes time series to 2d + mask filter).
    """

    def __init__(self, num_classes: Optional[int] = None,
                 labels_list: Optional[Sequence[str]] = None):
        self.labels_list = list(labels_list) if labels_list else None
        if num_classes is None and labels_list is not None:
            num_classes = len(labels_list)
        self._n = num_classes
        self.confusion: Optional[ConfusionMatrix] = None
        self.record_meta: List[Any] = []
        self._meta_by_cell: Dict[tuple, list] = {}

    def _ensure(self, n: int):
        if self.confusion is None:
            self._n = self._n or n
            self.confusion = ConfusionMatrix(self._n)

    def eval(self, labels: np.ndarray, predictions: np.ndarray,
             mask: Optional[np.ndarray] = None,
             meta: Optional[Sequence[Any]] = None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        # sparse integer-id labels (ops/losses.py convention): one dim
        # fewer than predictions; negative ids = ignore-index
        sparse = labels.ndim == predictions.ndim - 1
        if predictions.ndim == 3:  # [b,t,c] time series -> flatten w/ mask
            b, t = predictions.shape[:2]
            keep = (np.asarray(mask).reshape(-1) > 0) if mask is not None \
                else np.ones(b * t, bool)
            predictions = predictions.reshape(-1, predictions.shape[-1])[keep]
            labels = (labels.reshape(-1)[keep] if sparse
                      else labels.reshape(-1, labels.shape[-1])[keep])
            if meta is not None:  # per-example meta -> per-kept-timestep
                meta = np.repeat(np.asarray(meta, dtype=object), t)[keep]
        self._ensure(predictions.shape[-1])
        if sparse:
            actual = labels.astype(np.int64)
            width = predictions.shape[-1]
            if actual.size and actual.max() >= width:
                bad = int(actual.max())
                raise ValueError(
                    f"sparse label id {bad} is out of range for predictions "
                    f"with {width} classes (valid ids: 0..{width - 1}; "
                    f"negative ids mean ignore-index). The training loss "
                    f"clamps out-of-range ids, but evaluation refuses them "
                    f"so a vocabulary/label mismatch is caught loudly.")
            valid = actual >= 0
            actual, predictions = actual[valid], predictions[valid]
            if meta is not None:
                meta = np.asarray(meta, dtype=object)[valid]
        else:
            actual = np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        self.confusion.add_batch(actual, pred)
        if meta is not None:
            for a, p, m in zip(actual, pred, meta):
                self._meta_by_cell.setdefault((int(a), int(p)), []).append(m)

    # ---- metrics (ConfusionMatrix-derived, reference formulas) ----

    def _tp(self) -> np.ndarray:
        return np.diag(self.confusion.counts).astype(np.float64)

    def _fp(self) -> np.ndarray:
        return self.confusion.counts.sum(axis=0) - self._tp()

    def _fn(self) -> np.ndarray:
        return self.confusion.counts.sum(axis=1) - self._tp()

    def accuracy(self) -> float:
        c = self.confusion.counts
        total = c.sum()
        return float(np.diag(c).sum() / total) if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        tp, fp = self._tp(), self._fp()
        if cls is not None:
            d = tp[cls] + fp[cls]
            return float(tp[cls] / d) if d else 0.0
        # macro-average over classes that appear (reference behavior)
        with np.errstate(invalid="ignore", divide="ignore"):
            per = np.where(tp + fp > 0, tp / (tp + fp), np.nan)
        return float(np.nanmean(per))

    def recall(self, cls: Optional[int] = None) -> float:
        tp, fn = self._tp(), self._fn()
        if cls is not None:
            d = tp[cls] + fn[cls]
            return float(tp[cls] / d) if d else 0.0
        with np.errstate(invalid="ignore", divide="ignore"):
            per = np.where(tp + fn > 0, tp / (tp + fn), np.nan)
        return float(np.nanmean(per))

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) > 0 else 0.0

    def false_positive_rate(self, cls: int) -> float:
        c = self.confusion.counts
        fp = c[:, cls].sum() - c[cls, cls]
        tn = c.sum() - c[cls, :].sum() - c[:, cls].sum() + c[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def get_meta(self, actual: int, predicted: int) -> list:
        """Per-example metadata attribution (``eval/meta/``)."""
        return self._meta_by_cell.get((actual, predicted), [])

    def stats(self) -> str:
        """Human-readable report (``Evaluation.stats()``)."""
        lines = [
            "==========================Scores========================================",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "========================================================================",
        ]
        return "\n".join(lines)
