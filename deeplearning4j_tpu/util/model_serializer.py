"""Model checkpointing: zip of config JSON + parameters + updater state.

Parity: ``util/ModelSerializer.java:78-120`` — the reference writes a zip
with ``configuration.json`` + ``coefficients.bin`` (flat param vector) +
``updaterState.bin``. Same three-part logical layout here:

- ``configuration.json`` — MultiLayerConfiguration / CG config JSON
  (+ a ``model_type`` tag)
- ``coefficients.npz``  — the parameter pytree, one array per
  ``layer/param`` key (keeps named structure AND provides the flat view)
- ``updaterState.npz``  — updater state arrays + the step counter
- ``modelState.npz``    — non-trainable state (BN moving stats)

Orbax-style sharded checkpointing for large distributed models rides on
the same pytree (see parallel/); this zip format is the
portable single-file interchange.
"""

from __future__ import annotations

import io
import json
import zipfile
from typing import Any, Dict, Union

import jax
import jax.numpy as jnp
import numpy as np


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(v)
    return tree


def _npz_bytes(tree: Dict[str, Any]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **_flatten(tree))
    return buf.getvalue()


def _npz_tree(data: bytes) -> Dict[str, Any]:
    with np.load(io.BytesIO(data)) as z:
        return _unflatten({k: z[k] for k in z.files})


def config_payload(model) -> dict:
    """{"model_type", "conf"} JSON payload shared by the zip format and
    the sharded orbax format (``sharded_checkpoint.py``)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(type(model))
    return {"model_type": model_type, "conf": json.loads(model.conf.to_json())}


def model_from_payload(payload: dict):
    """Rebuild an UNinitialized model from a ``config_payload`` dict."""
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf_json = json.dumps(payload["conf"])
    if payload["model_type"] == "MultiLayerNetwork":
        return MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    return ComputationGraph(ComputationGraphConfiguration.from_json(conf_json))


def write_model(model, path: str, save_updater: bool = True) -> None:
    """``ModelSerializer.writeModel`` equivalent."""
    from deeplearning4j_tpu.monitor import span

    payload = config_payload(model)
    with span("checkpoint", op="zip_save", path=path):
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("configuration.json", json.dumps(payload, indent=2))
            z.writestr("coefficients.npz", _npz_bytes(model.params))
            z.writestr("modelState.npz", _npz_bytes(model.states))
            if save_updater and model.opt_state is not None:
                z.writestr("updaterState.npz", _npz_bytes(
                    {"step": model.opt_state["step"], "updater": model.opt_state["updater"]}))


def restore_multi_layer_network(path: str, load_updater: bool = True):
    return _restore(path, "MultiLayerNetwork", load_updater)


def restore_computation_graph(path: str, load_updater: bool = True):
    return _restore(path, "ComputationGraph", load_updater)


def restore_model(path: str, load_updater: bool = True):
    return _restore(path, None, load_updater)


def _restore(path: str, expect: Union[str, None], load_updater: bool):
    with zipfile.ZipFile(path) as z:
        payload = json.loads(z.read("configuration.json"))
        model_type = payload["model_type"]
        if expect and model_type != expect:
            raise ValueError(f"checkpoint is a {model_type}, expected {expect}")
        model = model_from_payload(payload).init()
        # merge stored arrays into the freshly-initialized structure: layers
        # without params (pooling, activation, ...) serialize as nothing, so
        # a plain tree_map over both trees would see mismatched keys
        model.params = _merge(model.params, _npz_tree(z.read("coefficients.npz")), path)
        model.states = _merge(model.states, _npz_tree(z.read("modelState.npz")), path)
        if load_updater and "updaterState.npz" in z.namelist():
            upd = _npz_tree(z.read("updaterState.npz"))
            model.opt_state = {
                "step": jnp.asarray(upd["step"], jnp.int32),
                "updater": _merge(model.opt_state["updater"], upd.get("updater", {}), path),
            }
    return model


def _merge(template, stored, path):
    """Overlay ``stored`` arrays onto ``template``'s pytree structure,
    keeping template dtypes; missing-from-template keys are an error."""
    if not isinstance(template, dict):
        return stored.astype(template.dtype)
    extra = set(stored) - set(template)
    if extra:
        raise ValueError(f"checkpoint {path} contains unknown keys {sorted(extra)}")
    return {k: (_merge(v, stored[k], path) if k in stored else v)
            for k, v in template.items()}
