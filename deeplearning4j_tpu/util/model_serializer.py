"""Model checkpointing: zip of config JSON + parameters + updater state.

Parity: ``util/ModelSerializer.java:78-120`` — the reference writes a zip
with ``configuration.json`` + ``coefficients.bin`` (flat param vector) +
``updaterState.bin``. Same three-part logical layout here:

- ``configuration.json`` — MultiLayerConfiguration / CG config JSON
  (+ a ``model_type`` tag)
- ``coefficients.npz``  — the parameter pytree, one array per
  ``layer/param`` key (keeps named structure AND provides the flat view)
- ``updaterState.npz``  — updater state arrays + the step counter
- ``modelState.npz``    — non-trainable state (BN moving stats)

Orbax-style sharded checkpointing for large distributed models rides on
the same pytree (see parallel/); this zip format is the
portable single-file interchange.
"""

from __future__ import annotations

import io
import json
import os
import zipfile
import zlib
from typing import Any, Dict, List, Union

import jax
import jax.numpy as jnp
import numpy as np

_MANIFEST = "manifest.json"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed its integrity check (torn write, flipped
    bits, missing members). Raised instead of a random downstream
    numpy/zip error so callers can fall back to an older checkpoint."""


def _crc32(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def fsync_dir(path: str) -> None:
    """fsync a directory so a rename into it survives power loss (a
    no-op on filesystems that don't support directory fds)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(v)
    return tree


def _npz_bytes(tree: Dict[str, Any]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **_flatten(tree))
    return buf.getvalue()


def _npz_tree(data: bytes) -> Dict[str, Any]:
    with np.load(io.BytesIO(data)) as z:
        return _unflatten({k: z[k] for k in z.files})


def config_payload(model) -> dict:
    """{"model_type", "conf"} JSON payload shared by the zip format and
    the sharded orbax format (``sharded_checkpoint.py``)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(type(model))
    return {"model_type": model_type, "conf": json.loads(model.conf.to_json())}


def model_from_payload(payload: dict):
    """Rebuild an UNinitialized model from a ``config_payload`` dict."""
    from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph, ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf_json = json.dumps(payload["conf"])
    if payload["model_type"] == "MultiLayerNetwork":
        return MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
    return ComputationGraph(ComputationGraphConfiguration.from_json(conf_json))


def write_model(model, path: str, save_updater: bool = True) -> None:
    """``ModelSerializer.writeModel`` equivalent, crash-safe: the zip is
    written to a sibling temp file, fsynced, then ``os.replace``d into
    place — a crash at ANY instant leaves either the previous complete
    file or no file, never a torn one. A ``manifest.json`` member pins a
    CRC32 per logical part for the restore-time integrity check."""
    from deeplearning4j_tpu.monitor import span

    payload = config_payload(model)
    members: Dict[str, bytes] = {
        "configuration.json": json.dumps(payload, indent=2).encode(),
        "coefficients.npz": _npz_bytes(model.params),
        "modelState.npz": _npz_bytes(model.states),
    }
    if save_updater and model.opt_state is not None:
        members["updaterState.npz"] = _npz_bytes(
            {"step": model.opt_state["step"], "updater": model.opt_state["updater"]})
    manifest = {"format": 1,
                "crc32": {n: _crc32(b) for n, b in members.items()}}
    path = os.path.abspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with span("checkpoint", op="zip_save", path=path):
        try:
            with open(tmp, "wb") as f:
                with zipfile.ZipFile(f, "w", zipfile.ZIP_DEFLATED) as z:
                    for name, data in members.items():
                        z.writestr(name, data)
                    z.writestr(_MANIFEST, json.dumps(manifest))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
    fsync_dir(os.path.dirname(path))


def verify_model_file(path: str) -> List[str]:
    """Integrity-check a model zip; returns problems ([] = sound).
    Catches torn writes (bad zip central directory), flipped bits
    (member CRC or manifest CRC mismatch), and missing members.
    Pre-manifest checkpoints are accepted when their zip-internal CRCs
    and required members check out."""
    problems: List[str] = []
    try:
        with zipfile.ZipFile(path) as z:
            bad = z.testzip()
            if bad is not None:
                return [f"{path}: zip CRC mismatch in member {bad!r}"]
            names = set(z.namelist())
            for req in ("configuration.json", "coefficients.npz",
                        "modelState.npz"):
                if req not in names:
                    problems.append(f"{path}: missing member {req!r}")
            if _MANIFEST in names:
                manifest = json.loads(z.read(_MANIFEST))
                for name, crc in manifest.get("crc32", {}).items():
                    if name not in names:
                        problems.append(
                            f"{path}: manifest lists missing member {name!r}")
                    elif _crc32(z.read(name)) != int(crc):
                        problems.append(
                            f"{path}: manifest CRC mismatch for {name!r}")
    except (OSError, zipfile.BadZipFile, zlib.error, json.JSONDecodeError,
            ValueError, KeyError) as e:
        return [f"{path}: unreadable checkpoint ({type(e).__name__}: {e})"]
    return problems


def restore_multi_layer_network(path: str, load_updater: bool = True):
    return _restore(path, "MultiLayerNetwork", load_updater)


def restore_computation_graph(path: str, load_updater: bool = True):
    return _restore(path, "ComputationGraph", load_updater)


def restore_model(path: str, load_updater: bool = True):
    return _restore(path, None, load_updater)


def _restore(path: str, expect: Union[str, None], load_updater: bool):
    problems = verify_model_file(path)
    if problems:
        from deeplearning4j_tpu.monitor import (FAULT_CKPT_INTEGRITY_COUNTER,
                                                get_registry, record_fault)
        get_registry().counter(
            FAULT_CKPT_INTEGRITY_COUNTER,
            "Checkpoint restores that failed the integrity check").inc()
        record_fault("checkpoint")
        raise CheckpointCorruptError("; ".join(problems))
    with zipfile.ZipFile(path) as z:
        payload = json.loads(z.read("configuration.json"))
        model_type = payload["model_type"]
        if expect and model_type != expect:
            raise ValueError(f"checkpoint is a {model_type}, expected {expect}")
        model = model_from_payload(payload).init()
        # merge stored arrays into the freshly-initialized structure: layers
        # without params (pooling, activation, ...) serialize as nothing, so
        # a plain tree_map over both trees would see mismatched keys
        model.params = _merge(model.params, _npz_tree(z.read("coefficients.npz")), path)
        model.states = _merge(model.states, _npz_tree(z.read("modelState.npz")), path)
        if load_updater and "updaterState.npz" in z.namelist():
            upd = _npz_tree(z.read("updaterState.npz"))
            model.opt_state = {
                "step": jnp.asarray(upd["step"], jnp.int32),
                "updater": _merge(model.opt_state["updater"], upd.get("updater", {}), path),
            }
    return model


def _merge(template, stored, path):
    """Overlay ``stored`` arrays onto ``template``'s pytree structure,
    keeping template dtypes; missing-from-template keys are an error."""
    if not isinstance(template, dict):
        return stored.astype(template.dtype)
    extra = set(stored) - set(template)
    if extra:
        raise ValueError(f"checkpoint {path} contains unknown keys {sorted(extra)}")
    return {k: (_merge(v, stored[k], path) if k in stored else v)
            for k, v in template.items()}
