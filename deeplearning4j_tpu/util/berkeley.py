"""Berkeley-NLP utility collections.

Parity: ``deeplearning4j-nn/.../berkeley/`` (13 files — Counter,
CounterMap, PriorityQueue, Pair/Triple and friends vendored from the
Berkeley NLP toolkit; SURVEY.md §2.1 "util + berkeley" row). Under
Python most of that file count IS the standard library, so these are
deliberately thin classes that keep the reference's API surface
(``getCount``/``incrementCount``/``argMax``/``normalize``,
priority-queue ``next``/``peek``) over ``dict``/``heapq`` machinery —
the residual value is API familiarity for ported callers, not data
structures.
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict, Generic, Iterator, List, Optional, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class Counter(Generic[K]):
    """Float-valued counter (``berkeley/Counter.java``)."""

    def __init__(self):
        self._c: Dict[K, float] = {}

    def get_count(self, key: K) -> float:
        return self._c.get(key, 0.0)

    def set_count(self, key: K, count: float) -> None:
        self._c[key] = float(count)

    def increment_count(self, key: K, amount: float = 1.0) -> None:
        self._c[key] = self._c.get(key, 0.0) + amount

    def increment_all(self, keys, amount: float = 1.0) -> None:
        for k in keys:
            self.increment_count(k, amount)

    def total_count(self) -> float:
        return sum(self._c.values())

    def normalize(self) -> None:
        total = self.total_count()
        if total:
            for k in self._c:
                self._c[k] /= total

    def arg_max(self) -> Optional[K]:
        return max(self._c, key=self._c.get) if self._c else None

    def max_count(self) -> float:
        return max(self._c.values()) if self._c else 0.0

    def key_set(self):
        return self._c.keys()

    def items(self):
        return self._c.items()

    def sorted_keys(self) -> List[K]:
        """Keys by descending count (``Counter.getSortedKeys``)."""
        return sorted(self._c, key=self._c.get, reverse=True)

    def __len__(self) -> int:
        return len(self._c)

    def __contains__(self, key: K) -> bool:
        return key in self._c


class CounterMap(Generic[K, V]):
    """Two-level counter (``berkeley/CounterMap.java``)."""

    def __init__(self):
        self._m: Dict[K, Counter[V]] = {}

    def get_counter(self, key: K) -> Counter[V]:
        if key not in self._m:
            self._m[key] = Counter()
        return self._m[key]

    def get_count(self, key: K, value: V) -> float:
        c = self._m.get(key)
        return c.get_count(value) if c else 0.0

    def increment_count(self, key: K, value: V, amount: float = 1.0) -> None:
        self.get_counter(key).increment_count(value, amount)

    def set_count(self, key: K, value: V, count: float) -> None:
        self.get_counter(key).set_count(value, count)

    def total_count(self) -> float:
        return sum(c.total_count() for c in self._m.values())

    def normalize(self) -> None:
        """Row-normalize every inner counter (conditional distribution)."""
        for c in self._m.values():
            c.normalize()

    def key_set(self):
        return self._m.keys()

    def __len__(self) -> int:
        return len(self._m)


class PriorityQueue(Generic[K]):
    """Max-priority queue with ``next``/``peek``/``has_next``
    (``berkeley/PriorityQueue.java`` — descending priority order)."""

    def __init__(self):
        self._heap: List[Tuple[float, int, K]] = []
        self._tie = itertools.count()

    def add(self, item: K, priority: float) -> None:
        heapq.heappush(self._heap, (-priority, next(self._tie), item))

    def has_next(self) -> bool:
        return bool(self._heap)

    def peek(self) -> K:
        return self._heap[0][2]

    def get_priority(self) -> float:
        return -self._heap[0][0]

    def next(self) -> K:
        return heapq.heappop(self._heap)[2]

    def __len__(self) -> int:
        return len(self._heap)

    def __iter__(self) -> Iterator[K]:
        while self.has_next():
            yield self.next()


class Pair(Generic[K, V]):
    """``berkeley/Pair.java`` (a named tuple with the reference's
    accessor names, for ported call sites)."""

    __slots__ = ("first", "second")

    def __init__(self, first: K, second: V):
        self.first = first
        self.second = second

    def get_first(self) -> K:
        return self.first

    def get_second(self) -> V:
        return self.second

    def __iter__(self):
        return iter((self.first, self.second))

    def __eq__(self, other) -> bool:
        return (isinstance(other, Pair) and self.first == other.first
                and self.second == other.second)

    def __hash__(self) -> int:
        return hash((self.first, self.second))

    def __repr__(self) -> str:
        return f"Pair({self.first!r}, {self.second!r})"


class Triple(Generic[K, V]):
    """``berkeley/Triple.java``."""

    __slots__ = ("first", "second", "third")

    def __init__(self, first, second, third):
        self.first = first
        self.second = second
        self.third = third

    def __iter__(self):
        return iter((self.first, self.second, self.third))

    def __eq__(self, other) -> bool:
        return (isinstance(other, Triple) and tuple(self) == tuple(other))

    def __hash__(self) -> int:
        return hash(tuple(self))

    def __repr__(self) -> str:
        return f"Triple({self.first!r}, {self.second!r}, {self.third!r})"
