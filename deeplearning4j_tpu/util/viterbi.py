"""Viterbi decoder + moving-window utility.

Parity: ``deeplearning4j-nn/.../util/Viterbi.java`` (most-likely label
sequence under a transition model) and ``util/MovingWindowMatrix.java``
(sliding sub-windows of a matrix). The DP recurrence is a ``lax.scan``
over time — an XLA while-loop on device, batched over sequences — where
the reference ran a per-step Java loop.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def viterbi_decode(log_emissions: np.ndarray,
                   log_transitions: np.ndarray,
                   log_initial: np.ndarray = None) -> Tuple[np.ndarray, np.ndarray]:
    """Most-likely state path.

    log_emissions: [t, k] (or [b, t, k] batched) per-step state scores;
    log_transitions: [k, k] (from → to); log_initial: [k].
    Returns (path [t] / [b, t] int32, score scalar / [b]).
    """
    e = jnp.asarray(log_emissions, jnp.float32)
    batched = e.ndim == 3
    if not batched:
        e = e[None]
    A = jnp.asarray(log_transitions, jnp.float32)
    k = A.shape[0]
    pi = jnp.zeros((k,), jnp.float32) if log_initial is None \
        else jnp.asarray(log_initial, jnp.float32)

    def decode_one(em):  # em: [t, k]
        def step(alpha, obs):
            # alpha: [k] best score ending in each state
            cand = alpha[:, None] + A          # [from, to]
            best = jnp.max(cand, axis=0) + obs
            back = jnp.argmax(cand, axis=0)
            return best, back

        alpha0 = pi + em[0]
        alpha, backs = jax.lax.scan(step, alpha0, em[1:])
        last = jnp.argmax(alpha)
        score = alpha[last]

        def backtrack(state, back):
            prev = back[state]
            return prev, state

        _, path_rev = jax.lax.scan(backtrack, last, backs, reverse=True)
        path = jnp.concatenate([path_rev, last[None]])
        return path.astype(jnp.int32), score

    paths, scores = jax.jit(jax.vmap(decode_one))(e)
    if not batched:
        return np.asarray(paths[0]), float(scores[0])
    return np.asarray(paths), np.asarray(scores)


def moving_window_matrix(arr: np.ndarray, window_rows: int, window_cols: int,
                         rotate: int = 0) -> np.ndarray:
    """``MovingWindowMatrix`` — all dense [window_rows, window_cols]
    sub-windows of a 2-D array (stride 1), optionally each rotated 90°
    ``rotate`` times. Returns [n_windows, wr, wc]."""
    a = np.asarray(arr)
    r, c = a.shape
    wr, wc = window_rows, window_cols
    if wr > r or wc > c:
        raise ValueError(f"window {wr}x{wc} larger than matrix {r}x{c}")
    wins = np.lib.stride_tricks.sliding_window_view(a, (wr, wc))
    out = wins.reshape(-1, wr, wc).copy()
    if rotate:
        out = np.rot90(out, k=rotate, axes=(1, 2)).copy()
    return out
