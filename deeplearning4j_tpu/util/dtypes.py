"""Mixed-precision policy helpers.

The policy (SURVEY.md §0 north star: bf16 keeps the MXU fed):
parameters, updater state, and layer states (BN moving stats, LSTM
TBPTT carries) live in float32; the layer compute — matmuls, convs,
scans — runs in the configured compute dtype (``bfloat16`` on TPU);
the output layer's score/loss is always evaluated in float32 on
float32-cast inputs. Gradients come out in float32 because the
param→bf16 casts happen inside the traced function (the cast's
transpose casts back), which is the standard mixed-precision recipe.

The reference has no counterpart (ND4J is float-typed per buffer,
``Nd4j.create`` defaults); this is a TPU-first extension exposed as
``NeuralNetConfiguration.builder().compute_dtype("bfloat16")``.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp


def resolve_compute_dtype(name: str) -> Optional[Any]:
    """Config string → cast target; None means "no casting" (float32
    params already are the compute dtype, zero-overhead path)."""
    if name in ("float32", "f32", None, ""):
        return None
    if name in ("bfloat16", "bf16"):
        return jnp.bfloat16
    if name in ("float16", "f16"):
        return jnp.float16
    raise ValueError(f"unknown compute_dtype {name!r}")


def cast_floats(tree: Any, dtype) -> Any:
    """Cast every floating leaf to ``dtype`` (ints/bools untouched)."""
    def cast(v):
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
            return v.astype(dtype)
        return v
    return jax.tree.map(cast, tree)


def cast_like(new_tree: Any, old_tree: Any) -> Any:
    """Cast ``new_tree`` leaves back to the dtypes of ``old_tree`` —
    keeps carried state (lax.scan carries in fit_scan) dtype-stable
    across steps regardless of the compute dtype."""
    def cast(n, o):
        if (hasattr(n, "dtype") and hasattr(o, "dtype")
                and n.dtype != o.dtype
                and jnp.issubdtype(n.dtype, jnp.floating)
                and jnp.issubdtype(o.dtype, jnp.floating)):
            return n.astype(o.dtype)
        return n
    return jax.tree.map(cast, new_tree, old_tree)
