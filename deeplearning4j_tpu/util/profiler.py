"""XLA/TPU profiler hooks.

Parity: SURVEY §5 tracing — the reference has PerformanceListener +
Spark phase timers + StatsListener telemetry (all rebuilt:
``optimize/listeners.py``, ``optimize/training_stats.py``,
``ui/stats.py``); the named TPU equivalent "XLA/TPU profiler traces"
is this module: thin, dependency-tolerant wrappers over
``jax.profiler`` producing TensorBoard-loadable traces of the real
device timeline (compilation, fusion, HBM traffic — the layers Python
timers can't see).
"""

from __future__ import annotations

import contextlib
from typing import Optional


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture a device trace for the enclosed block::

        with profiler.trace("/tmp/jax-trace"):
            net.fit_scan(ds, 512, epochs=1)
        # then: tensorboard --logdir /tmp/jax-trace

    No-ops (with a warning) when the backend can't trace.
    """
    import jax

    try:
        jax.profiler.start_trace(log_dir,
                                 create_perfetto_link=create_perfetto_link)
        started = True
    except Exception as e:  # tunneled/experimental backends may refuse
        import logging
        logging.getLogger(__name__).warning("profiler trace unavailable: %s", e)
        started = False
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass


def start_server(port: int = 9999) -> Optional[object]:
    """Start the on-demand profiling server (connect with TensorBoard's
    capture-profile button). Returns the server or None if unsupported."""
    import jax

    try:
        return jax.profiler.start_server(port)
    except Exception as e:
        import logging
        logging.getLogger(__name__).warning("profiler server unavailable: %s", e)
        return None


def annotate(name: str):
    """TraceAnnotation context manager: names a host-side region in the
    captured timeline (StepTraceAnnotation role for custom phases)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
