"""XLA/TPU profiler hooks.

Parity: SURVEY §5 tracing — the reference has PerformanceListener +
Spark phase timers + StatsListener telemetry (all rebuilt:
``optimize/listeners.py``, ``optimize/training_stats.py``,
``ui/stats.py``); the named TPU equivalent "XLA/TPU profiler traces"
is this module: thin, dependency-tolerant wrappers over
``jax.profiler`` producing TensorBoard-loadable traces of the real
device timeline (compilation, fusion, HBM traffic — the layers Python
timers can't see).
"""

from __future__ import annotations

import contextlib
from typing import Optional

def _shield_tensorflow() -> None:
    """XLA's profiler session tries ``import tensorflow.python.profiler``
    from inside C++ (python_hooks.cc); on this stack loading tensorflow's
    C extensions into a process that already holds jaxlib SEGFAULTS —
    not an ImportError, nothing downstream can catch it. Pre-inserting a
    stub module turns that import into a clean failure XLA logs and
    ignores, and the trace still writes its TensorBoard/Perfetto files
    (the TF hook is optional). No-op when tensorflow is already imported
    (the user made that call) or ``DL4J_TPU_ALLOW_TF=1``."""
    import os
    import sys
    import types

    if os.environ.get("DL4J_TPU_ALLOW_TF") == "1" or "tensorflow" in sys.modules:
        return
    stub = types.ModuleType("tensorflow")
    stub.__getattr__ = lambda name: (_ for _ in ()).throw(ImportError(
        f"tensorflow.{name} unavailable: tensorflow is stubbed out — "
        "loading it alongside jaxlib crashes this process "
        "(set DL4J_TPU_ALLOW_TF=1 to disable the shield)"))
    sys.modules["tensorflow"] = stub


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False,
          python_tracer: bool = False):
    """Capture a device trace for the enclosed block::

        with profiler.trace("/tmp/jax-trace"):
            net.fit_scan(ds, 512, epochs=1)
        # then: tensorboard --logdir /tmp/jax-trace
        # (or load the *.trace.json.gz into https://ui.perfetto.dev)

    No-ops (with a warning) when the backend can't trace.

    The default drives a ProfilerSession directly with the PYTHON tracer
    disabled: the host-side story lives in ``monitor/`` spans already,
    and XLA's python hooks both pull tensorflow into the process and
    crash at session-stop when other threads (async prefetch, UI server)
    are live. ``python_tracer=True`` (or ``create_perfetto_link=True``)
    opts back into the stock ``jax.profiler.start_trace`` path.
    """
    import jax
    import os

    session = None
    started = False
    try:
        if os.environ.get("DL4J_TPU_DISABLE_DEVICE_TRACE") == "1":
            # explicit kill-switch: environments where ProfilerSession is
            # known to crash the process outright (the pytest CPU harness
            # — C++-level segfault, uncatchable) set this and get the
            # documented warn-and-no-op degradation instead
            raise RuntimeError("device tracing disabled by "
                               "DL4J_TPU_DISABLE_DEVICE_TRACE=1")
        _shield_tensorflow()  # session creation may import TF regardless
        if python_tracer or create_perfetto_link:
            jax.profiler.start_trace(log_dir,
                                     create_perfetto_link=create_perfetto_link)
            started = True
        else:
            from jaxlib import xla_client
            opts = xla_client.profiler.ProfileOptions()
            opts.python_tracer_level = 0
            session = xla_client.profiler.ProfilerSession(opts)
    except Exception as e:  # tunneled/experimental backends may refuse
        import logging
        logging.getLogger(__name__).warning("profiler trace unavailable: %s", e)
        session = None
        started = False
    try:
        yield
    finally:
        try:
            if session is not None:
                session.export(session.stop(), str(log_dir))
            elif started:
                jax.profiler.stop_trace()
        except Exception:
            pass


def start_server(port: int = 9999) -> Optional[object]:
    """Start the on-demand profiling server (connect with TensorBoard's
    capture-profile button). Returns the server or None if unsupported."""
    import jax
    import os

    try:
        if os.environ.get("DL4J_TPU_DISABLE_DEVICE_TRACE") == "1":
            raise RuntimeError("device tracing disabled by "
                               "DL4J_TPU_DISABLE_DEVICE_TRACE=1")
        _shield_tensorflow()
        return jax.profiler.start_server(port)
    except Exception as e:
        import logging
        logging.getLogger(__name__).warning("profiler server unavailable: %s", e)
        return None


def annotate(name: str):
    """TraceAnnotation context manager: names a host-side region in the
    captured timeline (StepTraceAnnotation role for custom phases)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
