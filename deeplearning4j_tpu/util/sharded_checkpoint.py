"""Sharded (tensorstore-backed) distributed checkpointing via orbax.

Parity: SURVEY §5 checkpoint/resume — the reference's three-part zip
(``ModelSerializer.java:78-120``: config JSON + flat params + updater
state) is rebuilt host-side in ``model_serializer.py``; this module is
the named TPU equivalent: "the same three-part logical checkpoint in a
tensorstore-style sharded format". Each device writes its own parameter
shards (no host gather of the full model — mandatory once params are
FSDP/TP-sharded past host memory), and restore re-places arrays under
ANY topology: the checkpoint is placement-free, shardings come from the
live model at restore time.

Layout: ``<dir>/state`` (orbax PyTree of params/opt_state/states) +
``<dir>/configuration.json`` (same payload the zip format uses, so the
model can be rebuilt from the checkpoint alone).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(model, directory: str) -> str:
    """Write config + params + updater state + layer states, sharded."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    if isinstance(model, MultiLayerNetwork):
        model_type = "MultiLayerNetwork"
    elif isinstance(model, ComputationGraph):
        model_type = "ComputationGraph"
    else:
        raise TypeError(type(model))
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    state = {"params": model.params, "opt_state": model.opt_state,
             "states": model.states}
    _checkpointer().save(os.path.join(directory, "state"), state, force=True)
    payload = {"model_type": model_type,
               "conf": json.loads(model.conf.to_json())}
    with open(os.path.join(directory, "configuration.json"), "w") as f:
        json.dump(payload, f, indent=2)
    return directory


def restore_checkpoint(directory: str, model=None, shardings=None):
    """Restore a checkpoint.

    ``model=None`` rebuilds the network from the stored config (restore
    on a fresh process). ``shardings``: optional pytree-prefix of
    ``jax.sharding.Sharding`` to place params under (e.g. from
    ``fsdp_specs``); default keeps the restoring model's current
    placements when it has any, else single-device default placement —
    the checkpoint itself is topology-free.
    """
    directory = os.path.abspath(directory)
    if model is None:
        with open(os.path.join(directory, "configuration.json")) as f:
            payload = json.load(f)
        from deeplearning4j_tpu.nn.conf.configuration import MultiLayerConfiguration
        from deeplearning4j_tpu.nn.graph import (
            ComputationGraph, ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

        conf_json = json.dumps(payload["conf"])
        if payload["model_type"] == "MultiLayerNetwork":
            model = MultiLayerNetwork(MultiLayerConfiguration.from_json(conf_json))
        else:
            model = ComputationGraph(ComputationGraphConfiguration.from_json(conf_json))
        model.init()

    # read arrays as host numpy: restore is then valid on ANY topology
    # (orbax's default re-applies the SAVED shardings, which fails when
    # the saving devices aren't all present)
    import numpy as _np
    import orbax.checkpoint as ocp

    template = {"params": model.params, "opt_state": model.opt_state,
                "states": model.states}
    restore_args = jax.tree.map(
        lambda _: ocp.RestoreArgs(restore_type=_np.ndarray), template)
    restored = _checkpointer().restore(os.path.join(directory, "state"),
                                       restore_args=restore_args)

    def _placed(new, old):
        return jax.tree.map(
            lambda n, o: jax.device_put(
                n, o.sharding if hasattr(o, "sharding") else None), new, old)

    if shardings is not None:
        model.params = jax.tree.map(
            lambda n, s: jax.device_put(n, s), restored["params"], shardings)
    else:
        model.params = _placed(restored["params"], model.params)
    model.opt_state = _placed(restored["opt_state"], model.opt_state)
    model.states = _placed(restored["states"], model.states)
    model._jits = {}
    return model
