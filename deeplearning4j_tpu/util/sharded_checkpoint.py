"""Sharded (tensorstore-backed) distributed checkpointing via orbax.

Parity: SURVEY §5 checkpoint/resume — the reference's three-part zip
(``ModelSerializer.java:78-120``: config JSON + flat params + updater
state) is rebuilt host-side in ``model_serializer.py``; this module is
the named TPU equivalent: "the same three-part logical checkpoint in a
tensorstore-style sharded format". Each device writes its own parameter
shards (no host gather of the full model — mandatory once params are
FSDP/TP-sharded past host memory), and restore re-places arrays under
ANY topology: the checkpoint is placement-free, shardings come from the
live model at restore time.

Layout: ``<dir>/state`` (orbax PyTree of params/opt_state/states) +
``<dir>/configuration.json`` (same payload the zip format uses, so the
model can be rebuilt from the checkpoint alone).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import jax

from deeplearning4j_tpu.monitor import span


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


def save_checkpoint(model, directory: str) -> str:
    """Write config + params + updater state + layer states, sharded."""
    from deeplearning4j_tpu.util.model_serializer import config_payload

    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    with span("checkpoint", op="sharded_save", dir=directory):
        state = {"params": model.params, "opt_state": model.opt_state,
                 "states": model.states}
        _checkpointer().save(os.path.join(directory, "state"), state, force=True)
        with open(os.path.join(directory, "configuration.json"), "w") as f:
            json.dump(config_payload(model), f, indent=2)
    return directory


def restore_checkpoint(directory: str, model=None, shardings=None):
    """Restore a checkpoint.

    ``model=None`` rebuilds the network from the stored config (restore
    on a fresh process). ``shardings``: optional pytree-prefix of
    ``jax.sharding.Sharding`` to place params under (e.g. from
    ``fsdp_specs``); default keeps the restoring model's current
    placements when it has any, else single-device default placement —
    the checkpoint itself is topology-free.
    """
    directory = os.path.abspath(directory)
    if model is None:
        with open(os.path.join(directory, "configuration.json")) as f:
            payload = json.load(f)
        from deeplearning4j_tpu.util.model_serializer import model_from_payload

        model = model_from_payload(payload).init()

    # restore each leaf DIRECTLY under its target placement
    # (ArrayRestoreArgs): each process/device reads only its own shards
    # — no full-array host materialization, so models sharded past host
    # memory restore, and the SAVING topology is irrelevant. Leaves
    # without a target sharding (fresh CPU model) come back as numpy.
    import numpy as _np
    import orbax.checkpoint as ocp

    template = {"params": model.params, "opt_state": model.opt_state,
                "states": model.states}
    if shardings is not None:
        template = dict(template)
        template["params"] = shardings

    def _arg(leaf):
        if hasattr(leaf, "sharding"):  # live jax.Array target
            return ocp.ArrayRestoreArgs(sharding=leaf.sharding)
        if isinstance(leaf, jax.sharding.Sharding):  # explicit spec
            return ocp.ArrayRestoreArgs(sharding=leaf)
        return ocp.RestoreArgs(restore_type=_np.ndarray)

    restore_args = jax.tree.map(_arg, template)
    with span("checkpoint", op="sharded_restore", dir=directory):
        restored = _checkpointer().restore(os.path.join(directory, "state"),
                                           restore_args=restore_args)
    model.params = restored["params"]
    model.opt_state = restored["opt_state"]
    model.states = restored["states"]
    model._jits = {}
    return model
