"""Sharded (tensorstore-backed) distributed checkpointing via orbax.

Parity: SURVEY §5 checkpoint/resume — the reference's three-part zip
(``ModelSerializer.java:78-120``: config JSON + flat params + updater
state) is rebuilt host-side in ``model_serializer.py``; this module is
the named TPU equivalent: "the same three-part logical checkpoint in a
tensorstore-style sharded format". Each device writes its own parameter
shards (no host gather of the full model — mandatory once params are
FSDP/TP-sharded past host memory), and restore re-places arrays under
ANY topology: the checkpoint is placement-free, shardings come from the
live model at restore time.

Layout: ``<dir>/state`` (orbax PyTree of params/opt_state/states) +
``<dir>/configuration.json`` (same payload the zip format uses, so the
model can be rebuilt from the checkpoint alone) + ``<dir>/layout.json``
(the ``SpecLayout`` + saving-mesh topology — what makes the unit
MESH-PORTABLE: ``restore_checkpoint(..., mesh=)`` re-lowers the saved
shards onto ANY current mesh, 8 → 4 → 1 chips, restricting each spec to
the axes the new mesh has) + ``<dir>/manifest.json`` (per-file CRC32s,
written LAST — its presence marks a complete unit).

Crash safety: a checkpoint is assembled in a sibling temp directory and
renamed into place, so a preemption at any instant leaves either the
previous complete checkpoint or a sweepable temp — never a torn
directory that restores garbage. ``save_checkpoint(..., keep=K)``
switches to a retained history (``<dir>/ckpt-<step>``) and
``restore_checkpoint`` walks it newest-first, skipping any unit that
fails its manifest check (``dl4j_fault_checkpoint_integrity_failures_total``
ticks per skipped unit).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import zlib
from typing import List, Optional

import jax

from deeplearning4j_tpu.monitor import (FAULT_CKPT_INTEGRITY_COUNTER,
                                        MESH_RESTORE_RELAYOUT_COUNTER,
                                        get_registry, record_fault, span)
from deeplearning4j_tpu.util.model_serializer import (CheckpointCorruptError,
                                                      fsync_dir)

logger = logging.getLogger("deeplearning4j_tpu")

_MANIFEST = "manifest.json"
_LAYOUT = "layout.json"
_STEP_PREFIX = "ckpt-"
_TMP_PREFIX = ".ckpt_tmp_"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer()


# ------------------------------------------------------------- integrity

def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _relative_files(directory: str) -> List[str]:
    out = []
    for root, _, files in os.walk(directory):
        for name in files:
            out.append(os.path.relpath(os.path.join(root, name), directory))
    return sorted(out)


def _write_manifest(directory: str) -> None:
    """CRC32 every file under ``directory`` into ``manifest.json`` —
    written last (tmp + fsync + replace), so its presence certifies a
    complete, bit-exact unit."""
    files = [f for f in _relative_files(directory) if f != _MANIFEST]
    manifest = {"format": 1, "crc32": {
        f: _file_crc32(os.path.join(directory, f)) for f in files}}
    tmp = os.path.join(directory, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, _MANIFEST))
    fsync_dir(directory)


def verify_checkpoint(directory: str) -> List[str]:
    """Integrity-check one checkpoint unit; returns problems ([] = sound).
    A unit without a manifest (pre-fault-tolerance layout) passes when
    its two required parts exist — it cannot be bit-verified."""
    problems: List[str] = []
    if not os.path.isdir(directory):
        return [f"{directory}: not a directory"]
    if not os.path.exists(os.path.join(directory, "configuration.json")):
        problems.append(f"{directory}: missing configuration.json")
    if not os.path.isdir(os.path.join(directory, "state")):
        problems.append(f"{directory}: missing state/ pytree")
    manifest_path = os.path.join(directory, _MANIFEST)
    if not os.path.exists(manifest_path):
        return problems  # legacy unit: structural check only
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        for rel, crc in manifest.get("crc32", {}).items():
            path = os.path.join(directory, rel)
            if not os.path.exists(path):
                problems.append(f"{directory}: manifest lists missing {rel!r}")
            elif _file_crc32(path) != int(crc):
                problems.append(f"{directory}: CRC mismatch in {rel!r}")
    except (OSError, json.JSONDecodeError, ValueError) as e:
        problems.append(f"{directory}: unreadable manifest "
                        f"({type(e).__name__}: {e})")
    return problems


def _note_integrity_failure(problems: List[str]) -> None:
    get_registry().counter(
        FAULT_CKPT_INTEGRITY_COUNTER,
        "Checkpoint restores that failed the integrity check").inc()
    record_fault("checkpoint")
    for p in problems:
        logger.warning("sharded_checkpoint: %s", p)


# ------------------------------------------------------- mesh portability

def _first_sharded_spec(subtree):
    """The PartitionSpec of the first non-replicated NamedSharding leaf
    in ``subtree`` (updater-state mirrors share one spec per param)."""
    from jax.sharding import NamedSharding

    for leaf in jax.tree.leaves(subtree):
        sh = getattr(leaf, "sharding", None)
        if isinstance(sh, NamedSharding) and \
                any(p is not None for p in tuple(sh.spec)):
            return sh.spec
    return None


def _layout_payload(model):
    """The SpecLayout + mesh-topology record a checkpoint unit carries
    (``layout.json``, CRC-sealed by the manifest): whatever sharding the
    live arrays actually hold — params and the updater mirror recorded
    separately so asymmetric placements (ZeRO-1) round-trip — plus the
    saving mesh shape, so a restore onto a different topology knows it
    is re-lowering."""
    from deeplearning4j_tpu.parallel.mesh import SpecLayout

    params_layout = SpecLayout.from_params(model.params)
    upd_layout = SpecLayout()
    for ln, ld in ((model.opt_state or {}).get("updater") or {}).items():
        for pn, st in ld.items():
            spec = _first_sharded_spec(st)
            if spec is not None:
                upd_layout.set(ln, pn, spec)
    mesh_info = None
    plane = getattr(model, "mesh_plane", None)
    if plane is not None:
        mesh_info = plane.topology()
    else:
        from jax.sharding import NamedSharding

        for leaf in jax.tree.leaves((model.params, model.opt_state)):
            sh = getattr(leaf, "sharding", None)
            if isinstance(sh, NamedSharding):
                mesh = sh.mesh
                mesh_info = {
                    "devices": int(mesh.devices.size),
                    "axes": {str(k): int(v) for k, v in mesh.shape.items()},
                    "device_ids": [int(d.id) for d in mesh.devices.flat]}
                break
    return {"format": 1, "mesh": mesh_info,
            "params": params_layout.to_payload(),
            "updater": upd_layout.to_payload()}


def _read_layout(directory: str):
    """(params SpecLayout, updater SpecLayout, mesh info | None) from a
    unit's ``layout.json`` — empty layouts for pre-mesh-plane units."""
    from deeplearning4j_tpu.parallel.mesh import SpecLayout

    path = os.path.join(directory, _LAYOUT)
    if not os.path.exists(path):
        return SpecLayout(), SpecLayout(), None
    with open(path) as f:
        payload = json.load(f)
    return (SpecLayout.from_payload(payload.get("params")),
            SpecLayout.from_payload(payload.get("updater")),
            payload.get("mesh"))


def _mesh_template(model, mesh, params_layout, updater_layout):
    """Target-sharding template for an orbax restore onto ``mesh``:
    every leaf gets a ``NamedSharding`` on the CURRENT mesh, with the
    saved specs re-lowered (axes the mesh lacks dropped, indivisible
    dims replicated). states + step are replicated."""
    import numpy as _np
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    params_sh = params_layout.param_shardings(model.params, mesh)

    def _upd_sh(ln, pn, st):
        return jax.tree.map(
            lambda leaf: NamedSharding(mesh, updater_layout.restricted_spec(
                ln, pn, _np.shape(leaf), mesh)), st)

    upd = (model.opt_state or {}).get("updater") or {}
    opt_sh = {"step": repl,
              "updater": {ln: {pn: _upd_sh(ln, pn, st)
                               for pn, st in ld.items()}
                          for ln, ld in upd.items()}}
    states_sh = jax.tree.map(lambda _: repl, model.states)
    return {"params": params_sh, "opt_state": opt_sh, "states": states_sh}


# ------------------------------------------------------------------ save

def _install_dir(tmp: str, final: str) -> None:
    """Rename ``tmp`` into place as ``final`` keeping the ResumableTrainer
    invariant: at every instant at least one complete unit is visible
    (``final`` or ``final + ".old"``)."""
    old = final + ".old"
    if os.path.isdir(final):
        shutil.rmtree(old, ignore_errors=True)  # final still covers us
        os.rename(final, old)
    os.rename(tmp, final)
    shutil.rmtree(old, ignore_errors=True)
    fsync_dir(os.path.dirname(final))


def _sweep_tmp(parent: str) -> None:
    for name in os.listdir(parent):
        if name.startswith(_TMP_PREFIX):
            shutil.rmtree(os.path.join(parent, name), ignore_errors=True)


def _write_unit(model, directory: str) -> None:
    """Assemble one complete checkpoint unit at ``directory`` (already a
    private temp path) and seal it with the manifest."""
    from deeplearning4j_tpu.util.model_serializer import config_payload

    os.makedirs(directory, exist_ok=True)
    state = {"params": model.params, "opt_state": model.opt_state,
             "states": model.states}
    _checkpointer().save(os.path.join(directory, "state"), state, force=True)
    cfg_tmp = os.path.join(directory, "configuration.json.tmp")
    with open(cfg_tmp, "w") as f:
        json.dump(config_payload(model), f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(cfg_tmp, os.path.join(directory, "configuration.json"))
    lay_tmp = os.path.join(directory, _LAYOUT + ".tmp")
    with open(lay_tmp, "w") as f:
        json.dump(_layout_payload(model), f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(lay_tmp, os.path.join(directory, _LAYOUT))
    _write_manifest(directory)


def save_checkpoint(model, directory: str, keep: Optional[int] = None,
                    step: Optional[int] = None) -> str:
    """Write config + params + updater state + layer states, sharded.

    Default: ``directory`` IS the checkpoint unit (overwritten
    atomically — a crash leaves the previous complete unit). With
    ``keep=K``, ``directory`` becomes a retained history of the last K
    units (``ckpt-<step>`` subdirectories, ``step`` defaulting to the
    model's optimizer step) and older units are pruned; returns the path
    of the unit just written."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    if keep is not None:
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        if step is None:
            step = int(model.opt_state["step"]) if model.opt_state else 0
        final = os.path.join(directory, f"{_STEP_PREFIX}{int(step):010d}")
        parent = directory
    else:
        final = directory
        parent = os.path.dirname(directory)
    _sweep_tmp(parent)
    tmp = os.path.join(parent, _TMP_PREFIX + os.path.basename(final)
                       + f".{os.getpid()}")
    with span("checkpoint", op="sharded_save", dir=final):
        try:
            _write_unit(model, tmp)
            _install_dir(tmp, final)
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
    if keep is not None:
        for stale in checkpoint_steps(directory)[:-keep]:
            shutil.rmtree(os.path.join(
                directory, f"{_STEP_PREFIX}{stale:010d}"), ignore_errors=True)
    return final


def checkpoint_steps(directory: str) -> List[int]:
    """Ascending step numbers of the retained units under ``directory``."""
    steps = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            if name.startswith(_STEP_PREFIX) and not name.endswith(".old"):
                try:
                    steps.append(int(name[len(_STEP_PREFIX):]))
                except ValueError:
                    continue
    return sorted(steps)


# --------------------------------------------------------------- restore

def _restore_candidates(directory: str) -> List[str]:
    """Checkpoint units to try, newest first: the retained history when
    present, else the directory itself (plus its ``.old`` survivor)."""
    steps = checkpoint_steps(directory)
    if steps:
        return [os.path.join(directory, f"{_STEP_PREFIX}{s:010d}")
                for s in reversed(steps)]
    cands = [directory]
    if os.path.isdir(directory + ".old"):
        cands.append(directory + ".old")
    return cands


def restore_checkpoint(directory: str, model=None, shardings=None,
                       mesh=None):
    """Restore a checkpoint, falling back to the newest VALID unit.

    Each candidate (newest first — see ``save_checkpoint(keep=...)``) is
    integrity-checked against its manifest before any array is read; a
    torn or checksum-bad unit is skipped with a warning (and an
    integrity-failure metric tick) instead of crashing the restore.
    Raises :class:`CheckpointCorruptError` only when NO unit survives.

    ``model=None`` rebuilds the network from the stored config (restore
    on a fresh process). ``shardings``: optional pytree-prefix of
    ``jax.sharding.Sharding`` to place params under (e.g. from
    ``fsdp_specs``); default keeps the restoring model's current
    placements when it has any, else single-device default placement —
    the checkpoint itself is topology-free.

    ``mesh``: a ``Mesh`` or ``MeshPlane`` to restore ONTO — the
    mesh-portability path. The unit's recorded ``SpecLayout`` is
    re-lowered onto the given mesh (axes the new mesh lacks are
    dropped; dims that stop dividing fall back to replication), so a
    checkpoint written on 8 chips restores on 4 or 1 without the saving
    topology existing anymore. When the target shape differs from the
    saving shape, ``dl4j_mesh_restore_relayouts_total`` ticks.
    """
    directory = os.path.abspath(directory)
    candidates = _restore_candidates(directory)
    failures: List[str] = []
    for cand in candidates:
        problems = verify_checkpoint(cand)
        if problems:
            _note_integrity_failure(problems)
            failures.extend(problems)
            continue
        try:
            return _restore_unit(cand, model, shardings, mesh)
        except CheckpointCorruptError:
            raise
        except Exception as e:  # torn past what the manifest could see
            problem = [f"{cand}: restore failed ({type(e).__name__}: {e})"]
            _note_integrity_failure(problem)
            failures.extend(problem)
    raise CheckpointCorruptError(
        f"no restorable checkpoint under {directory}: " + "; ".join(failures)
        if failures else f"no checkpoint found under {directory}")


def _restore_unit(directory: str, model=None, shardings=None, mesh=None):
    if model is None:
        with open(os.path.join(directory, "configuration.json")) as f:
            payload = json.load(f)
        from deeplearning4j_tpu.util.model_serializer import model_from_payload

        model = model_from_payload(payload).init()

    # restore each leaf DIRECTLY under its target placement
    # (ArrayRestoreArgs): each process/device reads only its own shards
    # — no full-array host materialization, so models sharded past host
    # memory restore, and the SAVING topology is irrelevant. Leaves
    # without a target sharding (fresh CPU model) come back as numpy.
    import numpy as _np
    import orbax.checkpoint as ocp

    plane = None
    if mesh is not None:
        from deeplearning4j_tpu.parallel.mesh import MeshPlane

        plane = mesh if isinstance(mesh, MeshPlane) else MeshPlane(mesh)
        params_layout, upd_layout, saved_mesh = _read_layout(directory)
        template = _mesh_template(model, plane.mesh, params_layout,
                                  upd_layout)
        saved_axes = (saved_mesh or {}).get("axes")
        cur_axes = {str(k): int(v) for k, v in plane.mesh.shape.items()}
        if saved_axes is not None and saved_axes != cur_axes:
            # the portability path proper: the saved shards are being
            # re-lowered onto a topology the writer never saw
            get_registry().counter(
                MESH_RESTORE_RELAYOUT_COUNTER,
                "Checkpoint restores re-lowered onto a different mesh "
                "shape").inc()
        plane.layout = params_layout
    else:
        template = {"params": model.params, "opt_state": model.opt_state,
                    "states": model.states}
        if shardings is not None:
            template = dict(template)
            template["params"] = shardings

    def _arg(leaf):
        if isinstance(leaf, jax.sharding.Sharding):  # explicit spec
            return ocp.ArrayRestoreArgs(sharding=leaf)
        if hasattr(leaf, "sharding"):  # live jax.Array target
            return ocp.ArrayRestoreArgs(sharding=leaf.sharding)
        return ocp.RestoreArgs(restore_type=_np.ndarray)

    restore_args = jax.tree.map(_arg, template)
    with span("checkpoint", op="sharded_restore", dir=directory):
        restored = _checkpointer().restore(os.path.join(directory, "state"),
                                           restore_args=restore_args)
    model.params = restored["params"]
    model.opt_state = restored["opt_state"]
    model.states = restored["states"]
    if plane is not None:
        model.mesh_plane = plane
    model._jits = {}
    return model
