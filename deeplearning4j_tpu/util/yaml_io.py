"""JSON↔YAML bridging for configuration serialization.

One place for the convention both MultiLayerConfiguration and
ComputationGraphConfiguration use: serialize through the class's
canonical JSON form, re-render as block-style YAML (the reference's
Jackson YAML factory role, ``NeuralNetConfiguration.java:286``).
"""

from __future__ import annotations

import json


def json_to_yaml(json_str: str) -> str:
    import yaml
    return yaml.safe_dump(json.loads(json_str), sort_keys=False)


def yaml_to_json(yaml_str: str) -> str:
    import yaml
    return json.dumps(yaml.safe_load(yaml_str))
