"""Backend-aware ``jax.jit`` wrapper for buffer-donating programs.

On this jaxlib's CPU backend, donated-buffer aliasing corrupts the
process heap (the PR-1/2 hazard family: vmapped donation and queued
donated dispatches scribble over reused pages — symptoms range from a
handful of garbage rows in an otherwise-converged table to double-free
aborts at interpreter exit, and they surface nondeterministically in
whatever code runs NEXT). Every donation site therefore gates on the
backend: ``nn/multilayer.py`` / ``nn/graph.py`` / ``nn/generate.py`` /
``parallel/wrapper.py`` already do it inline at jit-build time; this
helper is the same gate for module-level ``@jax.jit`` decorators, where
the backend must be resolved lazily at the FIRST CALL so importing a
model module never initializes the platform.
"""

from __future__ import annotations

import functools

import jax


def cpu_safe_jit(fn=None, *, donate_argnums=(), **jit_kw):
    """``jax.jit(fn, donate_argnums=..., **jit_kw)`` with donation
    dropped entirely when the default backend is CPU.

    Usable as ``@cpu_safe_jit(donate_argnums=(0, 1))`` (with or without
    extra jit kwargs such as ``static_argnames``). The underlying jit
    object is built on first call and cached; ``jax.clear_caches()``
    still forces a retrace exactly as with a plain ``@jax.jit``.
    """
    if fn is None:
        return functools.partial(cpu_safe_jit,
                                 donate_argnums=donate_argnums, **jit_kw)
    cell = []

    @functools.wraps(fn)
    def call(*args, **kwargs):
        if not cell:
            donate = (donate_argnums
                      if jax.default_backend() != "cpu" else ())
            cell.append(jax.jit(fn, donate_argnums=donate, **jit_kw))
        return cell[0](*args, **kwargs)

    return call
