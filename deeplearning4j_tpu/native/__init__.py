"""ctypes bindings for the native IO kernels, with pure-Python fallback.

The shared library is compiled on first use (g++, baked into the image)
and cached next to the source; environments without a toolchain fall
back to NumPy implementations transparently — the helper-SPI "graceful
CPU fallback" doctrine of the reference's accelerator seam
(``ConvolutionLayer.java:60-67``) applied to the data plane.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("deeplearning4j_tpu")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "io_kernels.cpp")
_LIB = os.path.join(_HERE, "libdl4jtpu_io.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_IDX_DTYPES = {0x08: np.uint8, 0x09: np.int8, 0x0B: np.dtype(">i2"),
               0x0C: np.dtype(">i4"), 0x0D: np.dtype(">f4"), 0x0E: np.dtype(">f8")}


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           "-o", _LIB, _SRC]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=240)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        logger.info("native io build unavailable (%s); using python fallback", e)
        return False
    if proc.returncode != 0:
        logger.warning("native io build failed, using python fallback:\n%s",
                       proc.stderr[-1000:])
        return False
    return True


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None → fallback."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.warning("native io load failed (%s); python fallback", e)
            return None
        lib.dl4j_csv_shape.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.POINTER(ctypes.c_int64),
                                       ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                       ctypes.POINTER(ctypes.c_float),
                                       ctypes.c_int64, ctypes.c_int64, ctypes.c_int]
        lib.dl4j_csv_parse.restype = ctypes.c_int64
        lib.dl4j_idx_header.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_int),
                                        ctypes.POINTER(ctypes.c_int),
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.dl4j_idx_read.argtypes = [ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int64]
        _lib = lib
        return _lib


def csv_read_floats(path: str, skip_rows: int = 0, threads: int = 0,
                    strict: bool = False) -> np.ndarray:
    """Parse a numeric CSV file to a float32 [rows, cols] array via the
    multithreaded native parser; NumPy fallback when unavailable.

    Semantics (identical in both paths): ``skip_rows`` counts physical
    lines, whitespace-only lines are dropped, cells may be quoted.
    Non-numeric cells parse as 0.0 — unless ``strict=True``, which
    raises so mis-pointed files fail loudly instead of training on
    silently-zeroed features."""
    lib = get_lib()
    if lib is None:
        return _csv_read_floats_py(path, skip_rows, strict)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.dl4j_csv_shape(path.encode(), skip_rows,
                            ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        raise IOError(f"csv shape scan failed rc={rc}: {path}")
    out = np.empty((rows.value, cols.value), np.float32)
    bad = lib.dl4j_csv_parse(
        path.encode(), skip_rows,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        rows.value, cols.value, threads)
    if bad < 0:
        raise IOError(f"csv parse failed rc={bad}: {path}")
    if strict and bad > 0:
        raise ValueError(f"{bad} non-numeric cell(s) in {path}; "
                         f"use strict=False to zero-fill them")
    return out


def _csv_read_floats_py(path: str, skip_rows: int,
                        strict: bool = False) -> np.ndarray:
    rows = []
    bad = 0
    with open(path) as f:
        for i, line in enumerate(f):
            if i < skip_rows or not line.strip():
                continue
            vals = []
            for cell in line.rstrip("\n").split(","):
                cell = cell.strip().strip('"')
                try:
                    vals.append(float(cell))
                except ValueError:
                    vals.append(0.0)
                    bad += 1
            rows.append(vals)
    if strict and bad > 0:
        raise ValueError(f"{bad} non-numeric cell(s) in {path}; "
                         f"use strict=False to zero-fill them")
    return np.asarray(rows, np.float32)


def idx_read(path: str) -> Optional[np.ndarray]:
    """Read an (uncompressed) IDX file natively; None → caller falls
    back to its own parser (gz files are not handled here)."""
    lib = get_lib()
    if lib is None or path.endswith(".gz"):
        return None
    dtype = ctypes.c_int()
    ndim = ctypes.c_int()
    dims = (ctypes.c_int64 * 8)()
    rc = lib.dl4j_idx_header(path.encode(), ctypes.byref(dtype),
                             ctypes.byref(ndim), dims)
    if rc != 0:
        return None
    np_dtype = _IDX_DTYPES.get(dtype.value)
    if np_dtype is None:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    nbytes = int(np.prod(shape)) * np.dtype(np_dtype).itemsize
    out = np.empty(nbytes, np.uint8)
    rc = lib.dl4j_idx_read(path.encode(),
                           out.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)),
                           nbytes)
    if rc != 0:
        return None
    arr = out.view(np_dtype).reshape(shape)
    # normalize big-endian multi-byte types to native order
    if np.dtype(np_dtype).byteorder == ">":
        arr = arr.astype(np.dtype(np_dtype).newbyteorder("="))
    return arr
