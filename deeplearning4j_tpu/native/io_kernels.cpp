// Native IO kernels for the host-side data plane.
//
// Role: the reference's data plane bottoms out in native code twice —
// libnd4j's C++ buffer ops behind every INDArray, and DataVec's IO
// stack feeding RecordReaderDataSetIterator (SURVEY.md §1 layer 1/4).
// On TPU the array side is XLA; THIS file is the native side of the
// feed path: parsing host data fast enough that the async prefetch
// queue (AsyncDataSetIterator role) never starves the chip.
//
// Exposed as a plain C ABI consumed via ctypes (the environment has no
// pybind11). Numeric parsing uses std::from_chars — locale-independent
// (strtof misreads '1.5' under comma-decimal locales) and allocation
// free. Line semantics MATCH the python fallback exactly: skip_rows
// counts PHYSICAL lines, whitespace-only lines are not rows.
//
// Build: g++ -O3 -shared -fPIC -pthread -std=c++17 -o libdl4jtpu_io.so io_kernels.cpp

#include <atomic>
#include <charconv>
#include <cstdio>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

bool is_blank(const char* p, const char* end) {
    for (; p < end; p++)
        if (*p != ' ' && *p != '\t' && *p != '\r') return false;
    return true;
}

// Parse one cell: skip quotes/spaces, from_chars, return success.
bool parse_cell(const char* q, const char* cell_end, float* out) {
    while (q < cell_end && (*q == '"' || *q == ' ' || *q == '\t')) q++;
    const char* e = cell_end;
    while (e > q && (*(e - 1) == '"' || *(e - 1) == ' ' || *(e - 1) == '\t'
                     || *(e - 1) == '\r')) e--;
    if (q >= e) { *out = 0.0f; return false; }
    auto res = std::from_chars(q, e, *out);
    if (res.ec != std::errc()) { *out = 0.0f; return false; }
    return true;
}

struct FileBuf {
    std::vector<char> data;
    bool ok = false;
};

FileBuf read_file(const char* path) {
    FileBuf fb;
    FILE* f = fopen(path, "rb");
    if (!f) return fb;
    fseek(f, 0, SEEK_END);
#if defined(_WIN32)
    int64_t n = _ftelli64(f);  // long ftell is 32-bit on LLP64
#else
    int64_t n = ftello(f);
#endif
    fseek(f, 0, SEEK_SET);
    if (n < 0) { fclose(f); return fb; }  // ftell failure: empty buf
    fb.data.resize(n + 1);
    if (n > 0 && fread(fb.data.data(), 1, n, f) != (size_t)n) { fclose(f); return fb; }
    fclose(f);
    fb.data[n] = '\0';
    fb.data.resize(n);
    fb.ok = true;
    return fb;
}

// Collect [start, end) of every data line (after skipping skip_rows
// PHYSICAL lines and dropping blank lines) — shared by shape + parse.
void data_lines(const std::vector<char>& buf, int64_t skip_rows,
                std::vector<const char*>& starts,
                std::vector<const char*>& ends) {
    const char* p = buf.data();
    const char* end = p + buf.size();
    int64_t physical = 0;
    while (p < end) {
        const char* line_end = (const char*)memchr(p, '\n', end - p);
        if (!line_end) line_end = end;
        if (physical >= skip_rows && !is_blank(p, line_end)) {
            starts.push_back(p);
            ends.push_back(line_end);
        }
        physical++;
        p = line_end + 1;
    }
}

}  // namespace

extern "C" {

// ----------------------------------------------------------------- csv

int dl4j_csv_shape(const char* path, int64_t skip_rows, int64_t* rows, int64_t* cols) {
    FileBuf fb = read_file(path);
    if (!fb.ok) return -1;
    std::vector<const char*> starts, ends;
    data_lines(fb.data, skip_rows, starts, ends);
    *rows = (int64_t)starts.size();
    *cols = 0;
    if (!starts.empty()) {
        int64_t c = 1;
        for (const char* q = starts[0]; q < ends[0]; q++)
            if (*q == ',') c++;
        *cols = c;
    }
    return 0;
}

// Parse into a pre-allocated [rows, cols] float32 buffer. Returns the
// number of non-numeric cells (>= 0, parsed as 0.0), or negative on IO
// error — the caller decides whether bad cells are fatal.
int64_t dl4j_csv_parse(const char* path, int64_t skip_rows, float* out,
                    int64_t rows, int64_t cols, int threads) {
    FileBuf fb = read_file(path);
    if (!fb.ok) return -1;
    std::vector<const char*> starts, ends;
    data_lines(fb.data, skip_rows, starts, ends);
    if ((int64_t)starts.size() < rows) return -3;

    std::atomic<int64_t> bad{0};
    auto parse_range = [&](int64_t lo, int64_t hi) {
        int64_t local_bad = 0;
        for (int64_t i = lo; i < hi; i++) {
            const char* q = starts[i];
            const char* line_end = ends[i];
            float* row_out = out + i * cols;
            int64_t col = 0;
            while (col < cols) {
                const char* cell_end = (const char*)memchr(q, ',', line_end - q);
                if (!cell_end) cell_end = line_end;
                if (q >= line_end && col > 0) {
                    row_out[col++] = 0.0f;  // short row: zero-fill
                    local_bad++;
                    continue;
                }
                if (!parse_cell(q, cell_end, &row_out[col])) local_bad++;
                col++;
                q = cell_end < line_end ? cell_end + 1 : line_end;
            }
        }
        bad.fetch_add(local_bad, std::memory_order_relaxed);
    };

    int nt = threads > 0 ? threads : (int)std::thread::hardware_concurrency();
    if (nt < 1) nt = 1;
    if (nt > 16) nt = 16;
    // small files are not worth thread spawns
    int64_t min_rows_per_thread = 4096;
    int64_t useful = rows / min_rows_per_thread + 1;
    if ((int64_t)nt > useful) nt = (int)useful;
    if (nt <= 1) {
        parse_range(0, rows);
    } else {
        int64_t per = (rows + nt - 1) / nt;
        std::vector<std::thread> pool;
        for (int t = 0; t < nt; t++) {
            int64_t lo = t * per;
            int64_t hi = lo + per < rows ? lo + per : rows;
            if (lo >= hi) break;
            pool.emplace_back(parse_range, lo, hi);
        }
        for (auto& th : pool) th.join();
    }
    return bad.load();
}

// ----------------------------------------------------------------- idx

int dl4j_idx_header(const char* path, int* dtype, int* ndim, int64_t* dims) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    unsigned char h[4];
    if (fread(h, 1, 4, f) != 4 || h[0] != 0 || h[1] != 0) { fclose(f); return -2; }
    *dtype = h[2];
    *ndim = h[3];
    if (*ndim > 8) { fclose(f); return -3; }
    for (int i = 0; i < *ndim; i++) {
        unsigned char d[4];
        if (fread(d, 1, 4, f) != 4) { fclose(f); return -4; }
        dims[i] = ((int64_t)d[0] << 24) | ((int64_t)d[1] << 16) | ((int64_t)d[2] << 8) | d[3];
    }
    fclose(f);
    return 0;
}

int dl4j_idx_read(const char* path, unsigned char* out, int64_t nbytes) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    unsigned char h[4];
    if (fread(h, 1, 4, f) != 4) { fclose(f); return -2; }
    int64_t skip = 4 + 4 * h[3];
    fseek(f, skip, SEEK_SET);
    int64_t got = (int64_t)fread(out, 1, nbytes, f);
    fclose(f);
    return got == nbytes ? 0 : -5;
}


// ------------------------------------------------------- batch assembly
//
// The DataVec/AsyncDataSetIterator hot loop on the host side: assemble
// a shuffled minibatch (gather rows by index), optionally fused with
// per-column standardization, and expand integer labels to one-hot —
// all across a thread pool so the prefetch queue never starves the
// chip. Row indices are bounds-checked (returns -2 on the first OOB).

}  // extern "C" (templates below need C++ linkage)

// Minimum per-thread work (floats): below this, thread create/join
// overhead dwarfs the copy — typical 32-row minibatches run inline.
static const int64_t kMinWorkPerThread = 1L << 16;

static int clamp_threads(int threads, int64_t rows, int64_t work_per_row) {
    int nt = threads > 0 ? threads
                         : (int)std::thread::hardware_concurrency();
    if (nt < 1) nt = 1;
    if ((int64_t)nt > rows) nt = (int)(rows > 0 ? rows : 1);
    int64_t total = rows * (work_per_row > 0 ? work_per_row : 1);
    int64_t by_work = total / kMinWorkPerThread;
    if (by_work < 1) by_work = 1;
    if ((int64_t)nt > by_work && threads <= 0) nt = (int)by_work;
    return nt;
}

template <typename Fn>
static void parallel_rows(int64_t rows, int64_t work_per_row, int threads, Fn fn) {
    int nt = clamp_threads(threads, rows, work_per_row);
    if (nt <= 1) { fn(0L, rows); return; }
    int64_t per = (rows + nt - 1) / nt;
    std::vector<std::thread> pool;
    for (int t = 0; t < nt; t++) {
        int64_t lo = t * per;
        int64_t hi = lo + per < rows ? lo + per : rows;
        if (lo >= hi) break;
        pool.emplace_back(fn, lo, hi);
    }
    for (auto& th : pool) th.join();
}

extern "C" {

int64_t dl4j_gather_rows(const float* src, int64_t n_rows, int64_t row_elems,
                      const int64_t* idx, int64_t n_idx, float* out, int threads) {
    for (int64_t i = 0; i < n_idx; i++)
        if (idx[i] < 0 || idx[i] >= n_rows) return -2;
    parallel_rows(n_idx, row_elems, threads, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++)
            std::memcpy(out + i * row_elems, src + idx[i] * row_elems,
                        sizeof(float) * (size_t)row_elems);
    });
    return 0;
}

int64_t dl4j_gather_normalize(const float* src, int64_t n_rows, int64_t row_elems,
                           const int64_t* idx, int64_t n_idx, const float* mean,
                           const float* stdv, float* out, int threads) {
    for (int64_t i = 0; i < n_idx; i++)
        if (idx[i] < 0 || idx[i] >= n_rows) return -2;
    parallel_rows(n_idx, row_elems, threads, [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; i++) {
            const float* row = src + idx[i] * row_elems;
            float* dst = out + i * row_elems;
            for (int64_t j = 0; j < row_elems; j++) {
                float sd = stdv[j];
                dst[j] = (row[j] - mean[j]) / (sd != 0.0f ? sd : 1.0f);
            }
        }
    });
    return 0;
}

int64_t dl4j_onehot(const int64_t* labels, int64_t n, int64_t classes, float* out,
                 int threads) {
    for (int64_t i = 0; i < n; i++)
        if (labels[i] < 0 || labels[i] >= classes) return -2;
    parallel_rows(n, classes, threads, [&](int64_t lo, int64_t hi) {
        std::memset(out + lo * classes, 0,
                    sizeof(float) * (size_t)((hi - lo) * classes));
        for (int64_t i = lo; i < hi; i++)
            out[i * classes + labels[i]] = 1.0f;
    });
    return 0;
}

}  // extern "C"
