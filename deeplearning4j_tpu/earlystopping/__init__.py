from deeplearning4j_tpu.earlystopping.earlystopping import (  # noqa: F401
    EarlyStoppingConfiguration,
    EarlyStoppingResult,
    EarlyStoppingTrainer,
    MaxEpochsTerminationCondition,
    MaxScoreIterationTerminationCondition,
    MaxTimeIterationTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
    InMemoryModelSaver,
    LocalFileModelSaver,
    ShardedCheckpointSaver,
    DataSetLossCalculator,
)
