"""Early stopping: configuration, termination conditions, savers, trainer.

Parity: ``earlystopping/`` (22 files, SURVEY.md §2.1) —
``EarlyStoppingConfiguration``, epoch/iteration termination conditions,
score calculators, model savers (memory/disk), and
``trainer/BaseEarlyStoppingTrainer.java:46`` driving train-epoch →
evaluate → maybe-save-best → maybe-terminate.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, List, Optional

from deeplearning4j_tpu.datasets.iterators import DataSetIterator


# ---------------------------------------------------------------- conditions

class EpochTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def initialize(self):
        pass

    def terminate(self, last_score: float) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score):
        return epoch + 1 >= self.max_epochs


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without (min-delta) improvement."""

    def __init__(self, max_epochs_without_improvement: int, min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self.best = float("inf")
        self.since = 0

    def initialize(self):
        self.best = float("inf")
        self.since = 0

    def terminate(self, epoch, score):
        if score < self.best - self.min_improvement:
            self.best = score
            self.since = 0
        else:
            self.since += 1
        return self.since >= self.patience


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self.start = time.time()

    def initialize(self):
        self.start = time.time()

    def terminate(self, last_score):
        return (time.time() - self.start) > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Terminate if score exceeds a bound (divergence guard)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, last_score):
        return last_score > self.max_score or last_score != last_score  # NaN


# ------------------------------------------------------------------- savers

class InMemoryModelSaver:
    """``saver/InMemoryModelSaver.java``."""

    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, model, score):
        self._best = (model.clone() if hasattr(model, "clone") else model, score)

    def save_latest_model(self, model, score):
        self._latest = (model, score)

    def get_best_model(self):
        return self._best[0] if self._best else None

    def get_latest_model(self):
        return self._latest[0] if self._latest else None


class LocalFileModelSaver:
    """``saver/LocalFileModelSaver.java`` — zip checkpoints on disk."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, name):
        return os.path.join(self.dir, name)

    def save_best_model(self, model, score):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, self._path("bestModel.zip"))

    def save_latest_model(self, model, score):
        from deeplearning4j_tpu.util.model_serializer import write_model
        write_model(model, self._path("latestModel.zip"))

    def get_best_model(self):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(self._path("bestModel.zip"))

    def get_latest_model(self):
        from deeplearning4j_tpu.util.model_serializer import restore_model
        return restore_model(self._path("latestModel.zip"))


# ---------------------------------------------------------- score calculators

class DataSetLossCalculator:
    """``scorecalc/DataSetLossCalculator.java`` — average loss over an
    iterator (eval mode)."""

    def __init__(self, iterator: DataSetIterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, model) -> float:
        total, n = 0.0, 0
        self.iterator.reset()
        for ds in self.iterator:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / n if (self.average and n) else total


# -------------------------------------------------------------- configuration

@dataclasses.dataclass
class EarlyStoppingConfiguration:
    epoch_termination_conditions: List[EpochTerminationCondition] = dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = dataclasses.field(default_factory=list)
    score_calculator: Optional[DataSetLossCalculator] = None
    model_saver: object = dataclasses.field(default_factory=InMemoryModelSaver)
    save_last_model: bool = False
    evaluate_every_n_epochs: int = 1


@dataclasses.dataclass
class EarlyStoppingResult:
    termination_reason: str
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    best_model: object


class EarlyStoppingTrainer:
    """``trainer/BaseEarlyStoppingTrainer.java:46`` driver for
    MultiLayerNetwork and ComputationGraph alike."""

    def __init__(self, config: EarlyStoppingConfiguration, model, train_iterator: DataSetIterator):
        self.config = config
        self.model = model
        self.iterator = train_iterator

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        for c in cfg.epoch_termination_conditions:
            c.initialize()
        for c in cfg.iteration_termination_conditions:
            c.initialize()
        best_score = float("inf")
        best_epoch = -1
        epoch = 0
        reason, details = "EpochTerminationCondition", ""
        while True:
            self.iterator.reset()
            stop_iter = False
            for ds in self.iterator:
                self.model.fit(ds)
                last = self.model.score()
                for c in cfg.iteration_termination_conditions:
                    if c.terminate(last):
                        reason = "IterationTerminationCondition"
                        details = type(c).__name__
                        stop_iter = True
                        break
                if stop_iter:
                    break
            if stop_iter:
                break
            # score/save only every N epochs; termination checked EVERY
            # epoch (reference semantics — MaxEpochs must not overshoot)
            if epoch % cfg.evaluate_every_n_epochs == 0:
                if cfg.score_calculator is not None:
                    score = cfg.score_calculator.calculate_score(self.model)
                else:
                    score = self.model.score()
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best_model(self.model, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.model, score)
            terminated = False
            for c in cfg.epoch_termination_conditions:
                if c.terminate(epoch, score if epoch % cfg.evaluate_every_n_epochs == 0
                               else best_score):
                    reason = "EpochTerminationCondition"
                    details = type(c).__name__
                    terminated = True
                    break
            if terminated:
                break
            epoch += 1
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch + 1, best_model_epoch=best_epoch,
            best_model_score=best_score,
            best_model=cfg.model_saver.get_best_model() or self.model)


class ShardedCheckpointSaver(LocalFileModelSaver):
    """Early-stopping saver backed by the orbax/tensorstore sharded
    checkpoint format (``util/sharded_checkpoint.py``): each device
    writes its own shards, so best-model snapshots of FSDP/TP-sharded
    models never gather to host. Same SPI and directory conventions as
    :class:`LocalFileModelSaver`, with checkpoint DIRECTORIES instead
    of zips."""

    def save_best_model(self, model, score) -> None:
        from deeplearning4j_tpu.util.sharded_checkpoint import save_checkpoint
        save_checkpoint(model, self._path("bestModel"))

    def save_latest_model(self, model, score) -> None:
        from deeplearning4j_tpu.util.sharded_checkpoint import save_checkpoint
        save_checkpoint(model, self._path("latestModel"))

    def _load(self, name: str):
        from deeplearning4j_tpu.util.sharded_checkpoint import restore_checkpoint
        path = self._path(name)
        return restore_checkpoint(path) if os.path.isdir(path) else None

    def get_best_model(self):
        return self._load("bestModel")

    def get_latest_model(self):
        return self._load("latestModel")
