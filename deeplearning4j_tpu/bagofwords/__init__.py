from deeplearning4j_tpu.bagofwords.vectorizer import BagOfWordsVectorizer, TfidfVectorizer  # noqa: F401
