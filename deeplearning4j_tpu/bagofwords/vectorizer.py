"""Bag-of-words + TF-IDF vectorizers over a VocabCache.

Parity: ``bagofwords/vectorizer/BagOfWordsVectorizer.java`` /
``TfidfVectorizer.java`` — fit a vocab over a corpus, then transform
texts to count / tf-idf vectors (optionally labeled DataSets).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory, TokenizerFactory


class BagOfWordsVectorizer:
    def __init__(self, min_word_frequency: int = 1,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 stop_words: Sequence[str] = ()):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = frozenset(stop_words)
        self.vocab: Optional[VocabCache] = None
        self._doc_freq: Optional[np.ndarray] = None
        self._n_docs = 0

    def _tokens(self, text: str) -> List[str]:
        return [t for t in self.tokenizer_factory.create(text).get_tokens()
                if t not in self.stop_words]

    def fit(self, texts: Iterable[str]) -> "BagOfWordsVectorizer":
        token_lists = [self._tokens(t) for t in texts]
        self.vocab = VocabCache.build_from_sentences(token_lists, self.min_word_frequency)
        v = self.vocab.num_words()
        self._doc_freq = np.zeros(v, np.int64)
        self._n_docs = len(token_lists)
        for toks in token_lists:
            for i in {self.vocab.index_of(t) for t in toks if self.vocab.has_token(t)}:
                self._doc_freq[i] += 1
        return self

    def transform(self, text: str) -> np.ndarray:
        vec = np.zeros(self.vocab.num_words(), np.float32)
        for t in self._tokens(text):
            i = self.vocab.index_of(t)
            if i >= 0:
                vec[i] += 1.0
        return vec

    def fit_transform(self, texts: Sequence[str]) -> np.ndarray:
        self.fit(texts)
        return np.stack([self.transform(t) for t in texts])

    def vectorize(self, texts: Sequence[str], labels: Sequence[int],
                  num_classes: Optional[int] = None) -> DataSet:
        x = np.stack([self.transform(t) for t in texts])
        n = num_classes or (max(labels) + 1)
        y = np.eye(n, dtype=np.float32)[np.asarray(labels)]
        return DataSet(x, y)


class TfidfVectorizer(BagOfWordsVectorizer):
    """tf-idf weighting: tf * log(N / df) (``TfidfVectorizer.java``)."""

    def transform(self, text: str) -> np.ndarray:
        tf = super().transform(text)
        idf = np.log(np.maximum(self._n_docs, 1) / np.maximum(self._doc_freq, 1))
        return (tf * idf).astype(np.float32)
