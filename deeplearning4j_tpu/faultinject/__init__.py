"""Deterministic fault injectors — the harness that proves the
fault-tolerance layer.

A recovery path that has never run IS a bug; the only way to trust the
detect → isolate → recover machinery (crash-safe checkpoints, the
training supervisor, replica quarantine, broker reconnect/dead-letter)
is to inject each fault class deliberately. Every injector here is
deterministic: faults fire on explicit schedules (batch/call indices)
or from a SEEDED rng — a failing test replays bit-identically.

Injector ↔ fault domain map:

- :class:`FailingDataSetIterator` — NaN batches / mid-epoch iterator
  exceptions (training domain: supervisor rollback, feed-pipeline
  worker death);
- :class:`FlakyBroker` — scheduled transport errors on publish/consume
  (transport domain: reconnect, ``BrokerUnavailable`` surfacing);
- :func:`tear_file` / :func:`corrupt_file` / :class:`TornWrites` —
  torn and bit-flipped checkpoint artifacts, and a crash *between* the
  tmp write and the atomic install (checkpoint domain);
- :func:`poison_replica` — scheduled device errors on one serving
  replica (serving domain: retry, quarantine, probe reinstatement);
- :func:`poison_model` — scheduled device errors on ONE model across
  every replica (multi-model domain: the per-model circuit breaker
  must quarantine the model, leave the replicas serving its cotenants,
  and probe it back once the poison clears);
- :func:`kill_endpoint` / :class:`NetworkPartition` /
  :class:`WedgeEndpoint` — abrupt engine endpoint death, broker-level
  partitions, and liveness-without-progress wedges (routing domain:
  the InferenceRouter's heartbeat death detection, progress watchdog,
  failover, ejection and half-open reinstatement, and decode-stream
  migration);
- :class:`ChaosSchedule` / :func:`run_chaos_drill`
  (``faultinject/chaos.py``) — the COMPOSED drill: several injectors
  on one seeded event clock against a 3-endpoint fleet under mixed
  decode+classify load, asserting the global invariants (zero
  lost/duplicated tokens, zero stranded futures, zero leaked KV
  blocks, ``/healthz`` converges healthy) after drain;
- :class:`MeshShrink` / :class:`ChipFailure` — chips dying out of the
  mesh plane mid-epoch (mesh domain: checkpoint fallback, MeshPlane
  rebuild from the survivors, ``restore_checkpoint(mesh=...)``
  re-lowering, bitwise-deterministic resume on the smaller mesh);
- :class:`HostTierPressure` / :func:`run_hibernation_drill`
  (``faultinject/chaos.py``) — host-RAM KV-tier budget squeezes and
  the session-hibernation drill (KV-tiering domain: hibernate N
  sessions, kill the pinned endpoint, resume every session on the
  survivors down the host → shipped-blocks → journaled-prefix
  exactness ladder, with the squeeze forcing the refusal/fallback
  paths; zero leaked blocks on BOTH tiers after drain).
"""

from __future__ import annotations

import os
import random
import time
from typing import Iterable, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.streaming.broker import MessageBroker


class InjectedFault(RuntimeError):
    """The marker exception every injector raises — a test that sees a
    different exception type knows recovery swallowed the wrong thing."""


# ------------------------------------------------------------- training

class FailingDataSetIterator(DataSetIterator):
    """Wraps an iterator and injects batch-level faults on a
    deterministic schedule (0-based batch indices, counted across
    resets): ``nan_at`` batches keep their shape but carry all-NaN
    features (the classic diverged-upstream-pipeline batch — scores go
    NaN one step later); ``raise_at`` batches raise
    :class:`InjectedFault` from ``next()`` (a dead data source).
    ``p_nan`` adds seeded random NaN batches on top."""

    def __init__(self, wrapped: DataSetIterator, nan_at: Iterable[int] = (),
                 raise_at: Iterable[int] = (), p_nan: float = 0.0,
                 seed: int = 0):
        self._wrapped = wrapped
        self.nan_at = frozenset(int(i) for i in nan_at)
        self.raise_at = frozenset(int(i) for i in raise_at)
        self._p_nan = float(p_nan)
        self._rng = random.Random(seed)
        self._count = 0  # batches emitted, across resets (deterministic)
        self.injected_nan: list = []
        self.injected_raise: list = []

    def reset(self):
        self._wrapped.reset()

    def has_next(self):
        return self._wrapped.has_next()

    def batch(self):
        return self._wrapped.batch()

    def async_supported(self) -> bool:
        return self._wrapped.async_supported()

    def set_pre_processor(self, pp) -> None:
        self._wrapped.set_pre_processor(pp)

    def pre_processor(self):
        return self._wrapped.pre_processor()

    def _next_impl(self):
        idx = self._count
        self._count += 1
        if idx in self.raise_at:
            self.injected_raise.append(idx)
            raise InjectedFault(f"injected iterator failure at batch {idx}")
        ds = self._wrapped.next()
        if idx in self.nan_at or (self._p_nan > 0
                                  and self._rng.random() < self._p_nan):
            self.injected_nan.append(idx)
            feats = np.full_like(np.asarray(ds.features), np.nan)
            ds = DataSet(feats, ds.labels, ds.features_mask, ds.labels_mask)
        return ds


# ------------------------------------------------------------ transport

class FlakyBroker(MessageBroker):
    """Wraps any ``MessageBroker`` and fails scheduled calls (0-based,
    per operation kind) with ``exc`` — after its schedule is exhausted
    the broker heals. ``p_fail`` adds seeded random failures. The
    wrapped broker is NOT touched on a failed call (the op never
    happened — the at-most-once half of a real dropped connection)."""

    def __init__(self, wrapped: MessageBroker,
                 fail_publishes: Iterable[int] = (),
                 fail_consumes: Iterable[int] = (),
                 p_fail: float = 0.0, seed: int = 0,
                 exc=ConnectionError):
        self._wrapped = wrapped
        self.fail_publishes = frozenset(int(i) for i in fail_publishes)
        self.fail_consumes = frozenset(int(i) for i in fail_consumes)
        self._p_fail = float(p_fail)
        self._rng = random.Random(seed)
        self._exc = exc
        self._publishes = 0
        self._consumes = 0
        self.faults_injected = 0

    def _maybe_fail(self, idx: int, schedule: frozenset, what: str) -> None:
        if idx in schedule or (self._p_fail > 0
                               and self._rng.random() < self._p_fail):
            self.faults_injected += 1
            raise self._exc(f"injected broker failure on {what} #{idx}")

    def publish(self, topic: str, payload: bytes) -> None:
        idx, self._publishes = self._publishes, self._publishes + 1
        self._maybe_fail(idx, self.fail_publishes, "publish")
        self._wrapped.publish(topic, payload)

    def consume(self, topic: str, timeout: Optional[float] = None):
        idx, self._consumes = self._consumes, self._consumes + 1
        self._maybe_fail(idx, self.fail_consumes, "consume")
        return self._wrapped.consume(topic, timeout=timeout)

    def close(self) -> None:
        self._wrapped.close()


# ----------------------------------------------------------- checkpoint

def tear_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate ``path`` to a prefix — the torn write a crash leaves
    behind on a filesystem without the atomic-install discipline."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "rb+") as f:
        f.truncate(keep)


def corrupt_file(path: str, offset: int = -8, flip: int = 0xFF) -> None:
    """XOR one byte of ``path`` (negative offsets count from the end) —
    silent media corruption the CRC manifest must catch."""
    with open(path, "rb+") as f:
        f.seek(offset, os.SEEK_END if offset < 0 else os.SEEK_SET)
        pos = f.tell()
        byte = f.read(1)
        f.seek(pos)
        f.write(bytes([byte[0] ^ (flip & 0xFF)]))


class TornWrites:
    """Context manager that crashes the Nth atomic install (1-based
    count of ``os.replace``/``os.rename`` calls whose destination
    contains ``path_substr``) with :class:`InjectedFault` — simulating a
    preemption BETWEEN writing the temp artifact and renaming it into
    place, the exact window crash-safe persistence must survive."""

    def __init__(self, crash_on_call: int = 1,
                 path_substr: Optional[str] = None):
        self.crash_on_call = int(crash_on_call)
        self.path_substr = path_substr
        self.calls = 0
        self._orig_replace = None
        self._orig_rename = None

    def _wrap(self, orig):
        def patched(src, dst, *a, **k):
            if self.path_substr is None or self.path_substr in str(dst):
                self.calls += 1
                if self.calls == self.crash_on_call:
                    raise InjectedFault(
                        f"injected crash before installing {dst}")
            return orig(src, dst, *a, **k)
        return patched

    def __enter__(self) -> "TornWrites":
        self._orig_replace = os.replace
        self._orig_rename = os.rename
        os.replace = self._wrap(self._orig_replace)
        os.rename = self._wrap(self._orig_rename)
        return self

    def __exit__(self, *exc) -> None:
        os.replace = self._orig_replace
        os.rename = self._orig_rename


# -------------------------------------------------------------- serving

class ReplicaPoison:
    """Poison hook for ``ParallelInference``: the target replica's next
    ``failures`` dispatches (serving AND probe) raise
    :class:`InjectedFault`; afterwards the replica heals. Install via
    :func:`poison_replica` or pass as ``poison_hook=``."""

    def __init__(self, replica: int, failures: int):
        self.replica = int(replica)
        self.remaining = int(failures)
        self.hits = 0

    def __call__(self, replica_idx: int, shape: Sequence[int]) -> None:
        if replica_idx == self.replica and self.remaining > 0:
            self.remaining -= 1
            self.hits += 1
            raise InjectedFault(
                f"injected device fault on replica {replica_idx}")


def poison_replica(engine, replica: int = 0, failures: int = 2
                   ) -> ReplicaPoison:
    """Arm a :class:`ReplicaPoison` on a live engine (the engine's
    ``poison_hook`` seam); returns the handle so the test can watch
    ``remaining``/``hits``. ``failures=2`` defeats the single same-replica
    retry and forces a quarantine; the next probe then heals it."""
    poison = ReplicaPoison(replica, failures)
    engine._poison_hook = poison
    return poison


class ModelPoison:
    """Model-scoped poison hook for a multi-model ``ParallelInference``:
    dispatches (serving AND probe) of the target ``model`` — any
    replica, optionally one ``version`` — raise :class:`InjectedFault`
    for the next ``failures`` hits; afterwards the model heals.
    ``wants_model=True`` makes the engine pass the dispatch's model
    name to the hook. The recovery contract under test: the model's
    circuit breaker opens (its batch fails with ``ModelQuarantined``
    and its submits reject at admission), replicas stay in the pool for
    cotenant models, and a probe closes the breaker once healed."""

    wants_model = True

    def __init__(self, model: str, failures: int,
                 version: Optional[int] = None):
        self.model = model
        self.version = version  # None = any version of the model
        self.remaining = int(failures)
        self.hits = 0

    def __call__(self, replica_idx: int, shape: Sequence[int],
                 model: Optional[str]) -> None:
        if model == self.model and self.remaining > 0:
            self.remaining -= 1
            self.hits += 1
            raise InjectedFault(
                f"injected device fault for model {model!r} "
                f"on replica {replica_idx}")


def poison_model(engine, model: str, failures: Optional[int] = None,
                 version: Optional[int] = None) -> ModelPoison:
    """Arm a :class:`ModelPoison` on a live registry-mode engine.
    ``failures`` counts per-dispatch-attempt hits: opening the breaker
    takes ``breaker_threshold`` FAILED BATCHES, each burning
    ``1 + max_batch_retries`` attempts — the default arms exactly that
    many (e.g. 4 with the stock 1-retry engine and threshold 2), so the
    model's breaker opens and then the very next probe heals it.
    Cotenant models keep serving throughout."""
    if failures is None:
        threshold = 2
        if getattr(engine, "_registry", None) is not None:
            threshold = engine._registry.breaker_threshold
        failures = threshold * (1 + engine.max_batch_retries)
    poison = ModelPoison(model, failures, version)
    engine._poison_hook = poison
    return poison


# ----------------------------------------------------------------- mesh

class ChipFailure(InjectedFault):
    """A chip (subset of the mesh's devices) died mid-run. Carries the
    SURVIVING device ids so the recovery path can rebuild a smaller
    MeshPlane from exactly the devices the drill left alive."""

    def __init__(self, message: str, survivor_ids: Sequence[int]):
        super().__init__(message)
        self.survivor_ids = tuple(int(i) for i in survivor_ids)


class MeshShrink:
    """Deterministic mesh-shrink drill: at training step
    ``fail_at_step`` (0-based, counted across :meth:`step` calls) the
    drill raises :class:`ChipFailure` naming ``survivors`` devices
    chosen by a SEEDED rng from the ``total`` the mesh started with —
    the stand-in for chips dropping out of the plane mid-epoch.

    The recovery contract under test (tests/test_mesh_plane.py, marker
    ``faultinject``): the training loop falls back to its newest
    checkpoint, rebuilds a MeshPlane from the survivors, restores via
    ``restore_checkpoint(..., mesh=...)`` (saved shards re-lowered onto
    the smaller topology) and resumes — with a bitwise-identical
    forward on the restored step across drill reruns. Same
    ``(seed, fail_at_step, survivors)`` → identical failure step AND
    identical survivor set, so a failing drill replays exactly."""

    def __init__(self, fail_at_step: int, survivors: int,
                 total: Optional[int] = None, seed: int = 0):
        if survivors < 1:
            raise ValueError(f"survivors must be >= 1, got {survivors}")
        self.fail_at_step = int(fail_at_step)
        self.survivors = int(survivors)
        self.total = total
        self.seed = int(seed)
        self.steps_seen = 0
        self.fired = False

    def survivor_ids(self, total: Optional[int] = None) -> tuple:
        """The seeded choice of surviving device ids out of ``total``
        (ascending — a stable mesh rebuild order)."""
        n = int(total if total is not None else self.total)
        if self.survivors > n:
            raise ValueError(f"{self.survivors} survivors > {n} devices")
        rng = random.Random(self.seed)
        return tuple(sorted(rng.sample(range(n), self.survivors)))

    def step(self, total: Optional[int] = None) -> int:
        """Account one training step; raises :class:`ChipFailure` when
        the schedule says the chips die. Returns the step index."""
        idx = self.steps_seen
        self.steps_seen += 1
        if idx == self.fail_at_step and not self.fired:
            self.fired = True
            ids = self.survivor_ids(total)
            raise ChipFailure(
                f"injected chip failure at step {idx}: "
                f"{self.survivors} of {total if total is not None else self.total} "
                f"devices survive ({list(ids)})", ids)
        return idx


class SliceKill:
    """Kill-a-chip injector for a live SERVING SLICE (the ISSUE-12
    drill): from ``fail_at`` (0-based count of engine dispatches —
    classify batches, decode bursts and probes all tick the same
    clock), every dispatch raises :class:`ChipFailure` naming the
    slice's SURVIVORS — the seeded ``victim`` chip chosen from the
    slice's devices is gone for good, which is why the schedule never
    heals: a dead chip's dispatches stay dead until the fleet rebuilds
    the slice from the survivors (``LocalFleet.rebuild_slice``).

    Installable as BOTH engine seams at once: the ``poison_hook``
    (classify dispatches; ``wants_model`` so multi-model engines work)
    and the continuous scheduler's ``burst_hook`` (decode bursts) —
    ``LocalFleet.kill_chip`` arms both. Same ``(devices, seed,
    fail_at)`` ⇒ same victim, same survivor set, same failure tick:
    the drill replays bit-identically."""

    wants_model = True

    def __init__(self, plane_or_devices, victim: Optional[int] = None,
                 seed: int = 0, fail_at: int = 0):
        mesh = getattr(plane_or_devices, "mesh", None)
        if mesh is not None:
            devices = sorted(int(d.id) for d in mesh.devices.flat)
        else:
            devices = sorted(int(i) for i in plane_or_devices)
        if not devices:
            raise ValueError("SliceKill needs the slice's devices")
        self.devices = tuple(devices)
        if victim is not None:
            victim = int(victim)
            if victim not in self.devices:
                raise ValueError(
                    f"victim chip {victim} not in slice {devices}")
        else:
            victim = devices[random.Random(seed).randrange(len(devices))]
        self.victim = victim
        self.survivors = tuple(i for i in self.devices if i != victim)
        self.fail_at = int(fail_at)
        self.calls = 0
        self.hits = 0

    def __call__(self, *args) -> None:
        idx = self.calls
        self.calls += 1
        if idx >= self.fail_at:
            self.hits += 1
            raise ChipFailure(
                f"injected chip {self.victim} failure in slice "
                f"{list(self.devices)} at dispatch {idx} "
                f"(survivors {list(self.survivors)})", self.survivors)


# -------------------------------------------------------------- routing

class BurstKill:
    """Kill-mid-burst injector for the continuous decode scheduler
    (``ContinuousDecodeScheduler``'s ``burst_hook`` /
    ``ParallelInference(decode_burst_hook=...)`` seam): the hook fires
    once per accounted burst dispatch, and burst indices
    ``[after, after + failures)`` raise :class:`InjectedFault` BEFORE
    the device program runs — a deterministic stand-in for a dispatch
    dying under live sequences. The recovery contract under test: the
    scheduler fails every riding sequence's future with a typed
    ``DecodeBurstError``, frees their KV blocks immediately (pool free
    count returns to total after drain — never a leaked block), and
    keeps serving later admissions. Optionally scoped to one ``lane``
    key (a (model, version) pair) in multi-model schedulers."""

    def __init__(self, after: int = 1, failures: int = 1,
                 lane: Optional[tuple] = None):
        self.after = int(after)
        self.failures = int(failures)
        self.lane = lane
        self.calls = 0
        self.hits = 0

    def __call__(self, lane_key, burst_index: int) -> None:
        if self.lane is not None and tuple(lane_key) != tuple(self.lane):
            return
        idx = self.calls
        self.calls += 1
        if self.after <= idx < self.after + self.failures:
            self.hits += 1
            raise InjectedFault(
                f"injected burst kill at dispatch {idx} (lane {lane_key})")


class WedgeEndpoint:
    """Wedge injector for the serving fleet: the named member keeps
    heartbeating (liveness intact) but silently drops every consumed
    request — zero progress with work queued, the failure mode a
    heartbeat-only health plane can NEVER see. Context-managed so the
    drill always unwedges::

        with WedgeEndpoint(fleet, "engine-0"):
            ...  # router's wedge watchdog must eject + migrate

    The recovery contract under test: the router's progress watchdog
    (``wedge_timeout_s``) observes flat ``resolved``/``served``/burst
    counters while its own inflight count is nonzero, ejects the
    endpoint exactly like a crash, and the endpoint's in-flight
    requests resolve through timeout → failover (streams migrate with
    their journaled prefix)."""

    def __init__(self, fleet, name: str):
        self.fleet = fleet
        self.name = name
        self.active = False

    def wedge(self) -> "WedgeEndpoint":
        self.fleet.wedge(self.name)
        self.active = True
        return self

    def heal(self) -> None:
        if self.active:
            self.active = False
            try:
                self.fleet.unwedge(self.name)
            except KeyError:
                pass  # the member was removed while wedged

    def __enter__(self) -> "WedgeEndpoint":
        return self.wedge()

    def __exit__(self, *exc) -> None:
        self.heal()


class HostTierPressure:
    """Budget-squeeze injector for the paged pool's host-RAM tier (the
    KV-tiering PR's ``set_host_budget`` seam): while active, the
    targeted pools' host budgets shrink to ``budget`` blocks, so
    swap-outs, prefix-cache demotions and shipped-block imports hit
    the REFUSAL path (``swap_out``/``host_insert`` return None) and
    the caller must take its pre-tier fallback — free, cache-drop, or
    journaled re-prefill. Existing host entries are never dropped
    (the pool's shrink contract), so hibernated sessions stay exact
    under pressure; only NEW demotions are squeezed. Context-managed,
    restoring the original budgets on exit::

        with HostTierPressure(engine, budget=0):
            ...  # every swap-out refused; resume must still be exact

    Targets a ``PagedKVCachePool``, a ``ContinuousDecodeScheduler``,
    or a live continuous ``ParallelInference`` (every lane pool of the
    scheduler is squeezed). Deterministic by construction — no clocks,
    no rng; the squeeze window is the ``with`` block."""

    def __init__(self, target, budget: int = 0):
        if hasattr(target, "set_host_budget"):
            pools = [target]
        elif hasattr(target, "_pools"):
            pools = list(target._pools.values())
        elif getattr(target, "_scheduler", None) is not None:
            pools = list(target._scheduler._pools.values())
        else:
            raise ValueError(
                "HostTierPressure needs a PagedKVCachePool, a "
                "continuous scheduler, or a continuous engine with a "
                "built scheduler")
        self.pools = pools
        self.budget = max(0, int(budget))
        self._saved: list = []
        self.active = False

    def squeeze(self) -> "HostTierPressure":
        if not self.active:
            self._saved = [p.host_budget() for p in self.pools]
            for p in self.pools:
                p.set_host_budget(self.budget)
            self.active = True
        return self

    def heal(self) -> None:
        if self.active:
            self.active = False
            for p, old in zip(self.pools, self._saved):
                p.set_host_budget(old)

    def __enter__(self) -> "HostTierPressure":
        return self.squeeze()

    def __exit__(self, *exc) -> None:
        self.heal()


def kill_endpoint(fleet, name: str) -> str:
    """Process-kill injector for the serving fleet: abruptly stop the
    named endpoint's engine worker — consumed requests vanish without
    replies and heartbeats go silent (SIGKILL's wire signature; thread
    mode stops the worker threads, process mode delivers the real
    signal). Returns the name so tests can ``fleet.restart(name)``
    after asserting the failover. The router must keep every affected
    future resolving (timeout → failover) and eject the endpoint."""
    fleet.kill(name)
    return name


class NetworkPartition(MessageBroker):
    """Broker wrapper that partitions deterministically: while
    ``active``, operations on topics matching ``topic_substr`` (all
    topics when None) fail with ``exc`` (default: swallow publishes /
    return-None consumes when ``silent=True`` — a black-holing
    partition — else raise ``ConnectionError``, a detectable one).
    ``heal()`` reconnects. Wrap the broker handed to one side of a
    channel to partition exactly that side."""

    def __init__(self, wrapped: MessageBroker,
                 topic_substr: Optional[str] = None,
                 silent: bool = False, exc=ConnectionError):
        self._wrapped = wrapped
        self.topic_substr = topic_substr
        self.silent = bool(silent)
        self._exc = exc
        self.active = False
        self.dropped = 0

    def partition(self) -> "NetworkPartition":
        self.active = True
        return self

    def heal(self) -> None:
        self.active = False

    def _cut(self, topic: str) -> bool:
        return self.active and (self.topic_substr is None
                                or self.topic_substr in topic)

    def publish(self, topic: str, payload: bytes) -> None:
        if self._cut(topic):
            self.dropped += 1
            if self.silent:
                return  # black hole: the message is gone
            raise self._exc(f"injected partition on publish to {topic}")
        self._wrapped.publish(topic, payload)

    def consume(self, topic: str, timeout: Optional[float] = None):
        if self._cut(topic):
            self.dropped += 1
            if self.silent:
                if timeout:
                    time.sleep(min(timeout, 0.05))
                return None  # looks exactly like an idle topic
            raise self._exc(f"injected partition on consume of {topic}")
        return self._wrapped.consume(topic, timeout=timeout)

    def ping(self) -> float:
        if self.active and self.topic_substr is None:
            self.dropped += 1
            raise self._exc("injected partition on ping")
        return self._wrapped.ping()

    def close(self) -> None:
        self._wrapped.close()


# ------------------------------------------------------ composed drill
# (imported last: chaos.py composes the injectors defined above)

from deeplearning4j_tpu.faultinject.chaos import (  # noqa: E402,F401
    ACTIONS as CHAOS_ACTIONS,
    SLICE_ACTIONS,
    ChaosEvent,
    ChaosSchedule,
    run_chaos_drill,
    run_hibernation_drill,
    run_slice_drill,
)
