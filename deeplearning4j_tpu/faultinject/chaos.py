"""Composed chaos drill: every injector on ONE seeded event clock.

Each faultinject class proves one recovery path in isolation; real
outages compose — an endpoint dies while another is wedged, a burst is
killed during a heartbeat partition, a checkpoint install tears while
the canary model is poisoned. :class:`ChaosSchedule` generates a
deterministic event schedule from a seed (same seed ⇒ the same ticks,
actions and targets, bit for bit) and :func:`run_chaos_drill` replays
it against a live 3-endpoint serving fleet (thread-mode
``LocalFleet`` + ``InferenceRouter``) under mixed decode-stream +
classify load, composing:

- ``kill`` — :func:`~deeplearning4j_tpu.faultinject.kill_endpoint`
  (abrupt worker death; SIGKILL wire signature) + restart;
- ``partition_hb`` — :class:`~deeplearning4j_tpu.faultinject.
  NetworkPartition` black-holing one endpoint's heartbeats (the
  router must pull it from the pool on staleness alone) + heal;
- ``wedge`` — :class:`~deeplearning4j_tpu.faultinject.WedgeEndpoint`
  (liveness without progress; the wedge watchdog's fault);
- ``burst_kill`` — :class:`~deeplearning4j_tpu.faultinject.BurstKill`
  under a live decode stream (typed ``DecodeBurstError`` → the stream
  MIGRATES with its journaled prefix);
- ``replica_poison`` / ``poison_model`` — scheduled device faults on
  one replica / one model (quarantine + breaker + probe heal);
- ``torn_write`` — :class:`~deeplearning4j_tpu.faultinject.TornWrites`
  crashing a checkpoint install mid-drill (the previous artifact must
  survive and restore).

The drill's verdict is a set of GLOBAL invariants checked after drain,
and they are the whole point: **no request ever observes the
failure** — every submitted future resolves (zero stranded), every
decode stream delivers exactly the uninterrupted token sequence (zero
lost, zero duplicated offsets — greedy and seeded-sampled pinned
against ``generate_eager``), every KV pool drains back to fully free
(zero leaked blocks — the drill engines run the cross-request prefix
cache, so refcounted/shared blocks are in play and the caches release
their pins before the audit; a double free raises out of it), and the
fleet converges healthy. The returned
summary contains only schedule- and invariant-valued fields, so a
passing drill is bitwise-deterministic across reruns — the contract
``scripts/stress_faultinject.py --chaos`` enforces in fresh
subprocesses with rotating seeds.

The drill also runs END-TO-END REQUEST TRACING (monitor/reqtrace.py)
over its own traffic: every delivered decode stream's merged trace
must be parent-complete, and any stream that migrated with a journaled
prefix must have its migration gap fully attributed (silence_wait /
repin / resume dispatch / resume re-prefill / first resumed burst —
``check_telemetry_schema.validate_migration_coverage``).
``trace_violations`` in the summary is the count (0 on a passing
drill, so determinism holds); any invariant failure fires a
flight-recorder trigger so the evidence rings dump when armed.
"""

from __future__ import annotations

import importlib.util
import os
import random
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional, Tuple


def _load_schema_checker():
    """scripts/check_telemetry_schema.py loaded by path (the repo
    layout keeps scripts/ beside the package); None when the tree is
    installed without it — trace validation then degrades to the
    inline parent-completeness check."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "scripts", "check_telemetry_schema.py")
    if not os.path.exists(path):
        return None
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema_chaos", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _audit_stream_traces(tracer, futs) -> List[str]:
    """Per-delivered-stream trace invariants: parent-complete merged
    span tree; for streams that migrated with a journaled prefix, the
    migration gap fully attributed (the extended schema checker's
    coverage rule). Returns the violation list (empty on a passing
    drill — keeps the summary deterministic)."""
    if tracer is None:
        return []
    schema = _load_schema_checker()
    violations: List[str] = []
    for kind, fut, _oracle, _coll, _r in futs:
        if kind != "decode" or not fut.done() \
                or fut.exception() is not None:
            continue
        tid = getattr(fut, "trace_id", None)
        if tid is None:
            violations.append("delivered stream has no trace id")
            continue
        entry = tracer.completed_trace(tid)
        if entry is None:
            violations.append(f"{tid}: no completed trace")
            continue
        spans = entry["spans"]
        if schema is not None:
            violations.extend(schema.validate_trace_spans(spans, tid))
        else:
            ids = {s["span"] for s in spans}
            violations.extend(
                f"{tid}: orphan span {s['span']}" for s in spans
                if s["parent"] is not None and s["parent"] not in ids)
        resumed = any(s["name"] == "dispatch"
                      and (s.get("attrs") or {}).get("resume_prefix")
                      for s in spans)
        migrated = any(s["name"] == "silence_wait" for s in spans)
        if resumed and schema is not None:
            violations.extend(
                schema.validate_migration_coverage(spans, tid))
        elif migrated and not any(s["name"] == "repin" for s in spans):
            violations.append(f"{tid}: migrated stream without a "
                              f"repin span")
    return violations

#: the composable action set, index-addressed by the seeded schedule
ACTIONS: Tuple[str, ...] = ("kill", "partition_hb", "wedge", "burst_kill",
                            "replica_poison", "poison_model", "torn_write")

#: the slice-drill action set (run_slice_drill): chip death inside a
#: live mesh slice composes with the transport/progress faults — the
#: heal of a slice_kill is an ELASTIC REBUILD (narrower slice restored
#: from the mesh-portable checkpoint), never a restart of the dead chip
SLICE_ACTIONS: Tuple[str, ...] = ("slice_kill", "partition_hb", "wedge")


class ChaosEvent:
    """One scheduled fault: fire at request-count ``tick`` against
    endpoint ``target``; disruptive actions heal at ``heal_tick`` (the
    event clock is the open-loop submission counter, not wall time —
    that is what makes the schedule replayable)."""

    __slots__ = ("tick", "action", "target", "heal_tick")

    def __init__(self, tick: int, action: str, target: int,
                 heal_tick: int):
        self.tick = int(tick)
        self.action = action
        self.target = int(target)
        self.heal_tick = int(heal_tick)

    def __repr__(self) -> str:
        return (f"{self.action}@{self.tick}->e{self.target}"
                f"(heal@{self.heal_tick})")


class ChaosSchedule:
    """Seeded, deterministic composition schedule. Same
    ``(seed, n_events, n_endpoints, actions)`` ⇒ the identical event
    list — the replay contract every stress rerun pins."""

    def __init__(self, seed: int, n_events: int = 6, n_endpoints: int = 3,
                 actions: Tuple[str, ...] = ACTIONS,
                 min_gap: int = 2, max_gap: int = 4):
        self.seed = int(seed)
        self.n_endpoints = int(n_endpoints)
        rng = random.Random(self.seed * 7919 + 13)
        tick = 0
        self.events: List[ChaosEvent] = []
        for _ in range(int(n_events)):
            tick += rng.randint(int(min_gap), int(max_gap))
            action = actions[rng.randrange(len(actions))]
            target = rng.randrange(self.n_endpoints)
            self.events.append(
                ChaosEvent(tick, action, target, tick + rng.randint(1, 2)))

    def signature(self) -> str:
        return ";".join(repr(e) for e in self.events)


class _StreamCollector:
    """Per-stream delivery audit: tokens must arrive append-only —
    offset == len(received) on every delivery, across migrations."""

    def __init__(self):
        self.tokens: List[int] = []
        self.dups = 0
        self.gaps = 0

    def __call__(self, off, toks) -> None:
        import numpy as np
        for i, t in enumerate(np.asarray(toks).reshape(-1).tolist()):
            idx = int(off) + i
            if idx < len(self.tokens):
                self.dups += 1
            elif idx == len(self.tokens):
                self.tokens.append(int(t))
            else:
                self.gaps += 1


def _clf_net(n_in: int, n_out: int):
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    conf = (NeuralNetConfiguration.builder().seed(11).learning_rate(0.05)
            .updater("adam").activation("tanh")
            .list()
            .layer(DenseLayer(n_in=n_in, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=n_out, activation="softmax",
                               loss_function="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def run_chaos_drill(seed: int = 0, n_requests: int = 16, n_events: int = 4,
                    max_new: int = 6, timeout_s: float = 120.0,
                    per_try_timeout_s: float = 4.0,
                    wedge_timeout_s: float = 1.0,
                    pace_s: float = 0.02) -> Dict[str, Any]:
    """Run the composed drill; returns the invariant summary (see the
    module docstring). Deterministic by construction when it passes:
    every field is either derived from the seeded schedule or pinned
    to an invariant value by the assertions the caller makes."""
    import numpy as np

    from deeplearning4j_tpu.faultinject import (BurstKill, InjectedFault,
                                                NetworkPartition,
                                                TornWrites, kill_endpoint,
                                                poison_model, poison_replica)
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import (InferenceRouter, LocalFleet,
                                            ModelRegistry, RetryAfter)
    from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                          write_model)

    from deeplearning4j_tpu.monitor import reqtrace

    vocab, n_in, n_cls = 11, 6, 3
    lm = gpt(vocab_size=vocab, d_model=16, n_layers=2, num_heads=2,
             max_len=32, compute_dtype="float32", learning_rate=0.01,
             seed=0).init()
    clf = _clf_net(n_in, n_cls)
    schedule = ChaosSchedule(seed, n_events=n_events, n_endpoints=3)
    rng = np.random.default_rng(int(seed) * 104729 + 7)
    # the drill runs under request tracing: the per-stream merged
    # traces are themselves drill invariants (parent-complete; a
    # resumed migration's gap fully attributed)
    prev_tracer = reqtrace.request_tracer()
    tracer = reqtrace.enable_request_tracing(completed_capacity=4096)

    engines: List[ParallelInference] = []

    def engine_factory():
        mreg = ModelRegistry()
        mreg.register("lm", net=lm)
        mreg.register("clf", net=clf)
        # prefix_cache ON: the drill is the refcount/COW accounting
        # proof — every kill/preempt/evict interleaving must drain to
        # zero leaked and zero double-freed blocks with shared blocks
        # in play (the caches release their pins before the audit)
        eng = ParallelInference(registry=mreg, replicas=1,
                                max_batch_size=8, max_latency_ms=1.0,
                                queue_capacity=512, continuous=True,
                                decode_slots=4, decode_burst=4,
                                kv_block_size=4, prefix_cache=True)
        engines.append(eng)
        return eng

    router = InferenceRouter(per_try_timeout_s=per_try_timeout_s,
                             eject_backoff_s=0.1, max_attempts=6,
                             wedge_timeout_s=wedge_timeout_s)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=per_try_timeout_s,
                       heartbeat_timeout_s=0.5)
    for _ in range(3):
        fleet.add_endpoint()
    fleet.wait_ready(30)
    names = fleet.names()
    # pre-arm a heartbeat partition per endpoint (swapped in as the
    # endpoint's hb consumer so one side can be cut live)
    partitions = {}
    for name in names:
        part = NetworkPartition(fleet._broker,
                                topic_substr=name + ".hb", silent=True)
        fleet.endpoint(name)._hb_broker = part
        partitions[name] = part

    killed: Dict[str, bool] = {}
    ckpt_fallback_ok: Optional[bool] = None
    ckpt_dir = tempfile.mkdtemp(prefix="dl4j-chaos-")
    ckpt_path = os.path.join(ckpt_dir, "unit-model.zip")
    write_model(clf, ckpt_path)

    def _engine_of(name: str):
        m = fleet._members.get(name)
        return None if m is None or m.worker is None else m.worker.engine

    def apply(ev: ChaosEvent) -> Callable[[], None]:
        """Fire one event; returns its heal thunk (no-op when the
        injector self-limits)."""
        nonlocal ckpt_fallback_ok
        name = names[ev.target % len(names)]
        if ev.action == "kill":
            if killed.get(name):
                fleet.restart(name)
                killed[name] = False
                return lambda: None
            kill_endpoint(fleet, name)
            killed[name] = True

            def heal_kill():
                if killed.get(name):
                    fleet.restart(name)
                    killed[name] = False
            return heal_kill
        if ev.action == "partition_hb":
            part = partitions[name].partition()
            return part.heal
        if ev.action == "wedge":
            if killed.get(name):
                return lambda: None
            fleet.wedge(name)
            return lambda: fleet.unwedge(name)
        if ev.action == "burst_kill":
            eng = _engine_of(name)
            if eng is not None and not eng._closed:
                hook = BurstKill(after=0, failures=1)
                if eng._scheduler is not None:
                    eng._scheduler._burst_hook = hook
                else:
                    eng._decode_burst_hook = hook
            return lambda: None
        if ev.action == "replica_poison":
            eng = _engine_of(name)
            if eng is not None and not eng._closed:
                poison_replica(eng, replica=0, failures=2)
            return lambda: None
        if ev.action == "poison_model":
            eng = _engine_of(name)
            if eng is not None and not eng._closed:
                poison_model(eng, "clf")
            return lambda: None
        if ev.action == "torn_write":
            # checkpoint domain, composed in: the install crashes
            # between tmp write and rename; the PREVIOUS artifact must
            # survive and restore
            try:
                with TornWrites(crash_on_call=1, path_substr="unit-model"):
                    write_model(clf, ckpt_path)
            except InjectedFault:
                pass
            try:
                restore_model(ckpt_path)
                ok = True
            except BaseException:
                ok = False
            ckpt_fallback_ok = ok if ckpt_fallback_ok is None \
                else (ckpt_fallback_ok and ok)
            return lambda: None
        raise ValueError(f"unknown chaos action {ev.action!r}")

    # ---- open-loop load on the event clock ------------------------------
    pending_events = list(schedule.events)
    pending_heals: List[Tuple[int, Callable[[], None]]] = []
    futs: List[list] = []  # [kind, fut, oracle, collector, request]
    submitted = 0

    def _fire(r: Dict[str, Any], attempt: int = 0):
        """(future, collector) for one dispatch of a logical request;
        a retry gets a FRESH stream/session so its delivery audit
        stands alone."""
        if r["kind"] == "decode":
            coll = _StreamCollector()
            fut = router.submit_generate(
                r["x"], max_new, temperature=r["temp"], seed=r["seed"],
                model="lm", session=f"chaos-{r['seed']}-{attempt}",
                on_tokens=coll)
            return fut, coll
        return router.submit(r["x"], model="clf"), None

    try:
        for tick in range(n_requests):
            for _, heal in [h for h in pending_heals if h[0] <= tick]:
                heal()
            pending_heals = [h for h in pending_heals if h[0] > tick]
            for ev in [e for e in pending_events if e.tick <= tick]:
                pending_heals.append((ev.heal_tick, apply(ev)))
            pending_events = [e for e in pending_events if e.tick > tick]

            decode = tick % 2 == 0
            if decode:
                t0 = int(rng.integers(3, 6))
                prompt = rng.integers(1, vocab, (1, t0))
                temp = 0.7 if tick % 4 == 0 else 0.0
                oracle = generate_eager(lm, prompt, max_new,
                                        temperature=temp, seed=tick)
                req = {"kind": "decode", "x": prompt, "temp": temp,
                       "seed": tick, "oracle": oracle}
            else:
                x = rng.standard_normal((1, n_in)).astype(np.float32)
                req = {"kind": "classify", "x": x,
                       "oracle": np.asarray(clf.output(x))}

            for _ in range(200):  # shed ⇒ bounded retry-after loop
                try:
                    fut, coll = _fire(req)
                    futs.append([req["kind"], fut, req["oracle"], coll,
                                 req])
                    submitted += 1
                    break
                except RetryAfter:
                    time.sleep(0.05)
            time.sleep(pace_s)

        # ---- heal the world, then drain ---------------------------------
        for _, heal in pending_heals:
            heal()
        for ev in pending_events:  # events past the last tick: skipped
            pass
        for name in names:
            partitions[name].heal()
            try:
                fleet.unwedge(name)
            except BaseException:
                pass
            if killed.get(name):
                fleet.restart(name)
                killed[name] = False
        router.probe_now()
        for eng in engines:
            if not eng._closed:
                try:
                    eng.probe_now()
                except BaseException:
                    pass

        deadline = time.monotonic() + timeout_s
        for entry in futs:
            try:
                entry[1].result(
                    timeout=max(0.1, deadline - time.monotonic()))
            except BaseException:
                pass
        # a request that exhausted its failover budget WHILE every
        # endpoint was simultaneously bad fails typed — correct router
        # behavior (fail fast, never strand). The world is healed now,
        # so the drill does what any real client does with a typed
        # failure: bounded resubmission. The zero-lost/zero-dup audit
        # applies to each delivered stream (the final attempt).
        for retry_round in range(1, 4):
            pending = [e for e in futs
                       if e[1].done() and e[1].exception() is not None]
            if not pending:
                break
            for entry in pending:
                for _ in range(100):
                    try:
                        entry[1], entry[3] = _fire(entry[4], retry_round)
                        break
                    except RetryAfter:
                        time.sleep(0.05)
            for entry in pending:
                try:
                    entry[1].result(
                        timeout=max(0.1, deadline - time.monotonic()))
                except BaseException:
                    pass
        failed = sum(1 for _, f, _, _, _ in futs
                     if f.done() and f.exception() is not None)
        stranded = sum(1 for _, f, _, _, _ in futs if not f.done())

        mismatches = 0
        dup_offsets = 0
        gap_events = 0
        for kind, fut, oracle, coll, _r in futs:
            if not fut.done() or fut.exception() is not None:
                continue
            got = np.asarray(fut.result())
            if not np.array_equal(got, oracle):
                mismatches += 1
            if coll is not None:
                dup_offsets += coll.dups
                gap_events += coll.gaps
                if coll.tokens != [int(t) for t in oracle[0, -max_new:]]:
                    mismatches += 1

        # ---- healthz convergence: traffic probes the half-open pool -----
        healthy = 0
        conv_deadline = time.monotonic() + 30
        x = rng.standard_normal((1, n_in)).astype("float32")
        while time.monotonic() < conv_deadline:
            router.probe_now()
            try:
                router.output(x, model="clf", timeout=10)
            except BaseException:
                pass
            snap = router.fleet_snapshot()
            healthy = snap["healthy_endpoints"]
            if healthy >= 3:
                break
            time.sleep(0.05)

        # ---- zero leaked KV blocks, across EVERY engine ever alive ------
        # (the prefix caches hold block references BY DESIGN — they
        # release them here, and any refcount corruption the drill
        # caused surfaces as a leak or a double-free raise)
        leaked = 0
        for eng in engines:
            if not eng._closed:
                eng.drain(timeout=30)
            sched = eng._scheduler
            if sched is None:
                continue
            for c in sched.prefix_caches():
                c.clear()
            free_deadline = time.monotonic() + 10
            while time.monotonic() < free_deadline:
                pool = sched.stats()["pool"]
                if pool["blocks_free"] >= pool["blocks_total"]:
                    break
                time.sleep(0.02)
            pool = sched.stats()["pool"]
            leaked += int(pool["blocks_total"] - pool["blocks_free"])

        # ---- per-stream trace invariants (monitor/reqtrace.py) ----------
        trace_violations = _audit_stream_traces(tracer, futs)
    finally:
        try:
            fleet.shutdown(drain=False)
        except BaseException:
            pass
        router.close()
        reqtrace.set_request_tracer(prev_tracer)

    if (failed or stranded or mismatches or dup_offsets or gap_events
            or leaked or trace_violations):
        # invariant failure is a flight-recorder trigger: the recent
        # traces + structured events dump as JSONL when armed — the
        # post-mortem evidence for the failing rerun
        reqtrace.flight_trigger(
            "invariant", drill="chaos", seed=int(seed), failed=failed,
            stranded=stranded, mismatches=mismatches,
            leaked=leaked, trace_violations=len(trace_violations))

    return {
        "seed": int(seed),
        "schedule": schedule.signature(),
        "submitted": submitted,
        "completed": submitted - failed - stranded,
        "failed": failed,
        "stranded_futures": stranded,
        "token_mismatches": mismatches,
        "dup_offsets": dup_offsets,
        "gap_events": gap_events,
        "leaked_blocks": leaked,
        "healthy_endpoints": healthy,
        "ckpt_fallback_ok": ckpt_fallback_ok,
        "trace_violations": len(trace_violations),
    }


def run_hibernation_drill(seed: int = 0, n_sessions: int = 4,
                          turn1: int = 5, total: int = 12,
                          timeout_s: float = 120.0,
                          per_try_timeout_s: float = 10.0
                          ) -> Dict[str, Any]:
    """The SESSION-HIBERNATION composed drill (the KV-tiering PR): a
    3-endpoint fleet whose engines run the host-RAM tier
    (``kv_host_blocks``), ``n_sessions`` concurrent sessions each
    generate a first turn with ``hibernate=True`` (KV parks in the
    origin's host tier; the worker SHIPS the payload to the router
    before the terminal frame), then ONE seeded endpoint is killed
    abruptly and every session resumes — those pinned to a survivor
    ride the local swap-in rung, those pinned to the corpse ride the
    shipped-payload rung on a survivor, and the second half of the
    resumes run under :class:`~deeplearning4j_tpu.faultinject.
    HostTierPressure` (every live pool's host budget squeezed to 0),
    forcing the shipped-block landing dock to refuse so the restore
    degrades to the journaled-prefix rung.

    Invariants (the whole point — every rung is EXACT): all
    ``n_sessions`` resumed outputs are bitwise what an uninterrupted
    ``generate_eager`` run produces, streamed offsets are append-only
    across the hibernation gap (dup=0, gap=0), the router's handle
    table drains to empty, and every engine ever alive — the corpse
    included — leaks ZERO blocks on BOTH tiers (device free==total,
    host occupancy 0). The summary contains only seed-derived and
    invariant-valued fields, so a passing drill replays bitwise —
    the ``scripts/stress_faultinject.py --hibernation`` contract."""
    import numpy as np

    from deeplearning4j_tpu.faultinject import (HostTierPressure,
                                                kill_endpoint)
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import (InferenceRouter, LocalFleet,
                                            ModelRegistry, RetryAfter)

    vocab = 11
    lm = gpt(vocab_size=vocab, d_model=16, n_layers=2, num_heads=2,
             max_len=32, compute_dtype="float32", learning_rate=0.01,
             seed=0).init()
    rng = np.random.default_rng(int(seed) * 104729 + 7)
    engines: List[ParallelInference] = []

    def engine_factory():
        mreg = ModelRegistry()
        mreg.register("lm", net=lm)
        eng = ParallelInference(registry=mreg, replicas=1,
                                max_batch_size=8, max_latency_ms=1.0,
                                queue_capacity=512, continuous=True,
                                decode_slots=4, decode_burst=4,
                                kv_block_size=4, prefix_cache=True,
                                kv_host_blocks=64)
        engines.append(eng)
        return eng

    router = InferenceRouter(per_try_timeout_s=per_try_timeout_s,
                             eject_backoff_s=0.1, max_attempts=6)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=per_try_timeout_s,
                       heartbeat_timeout_s=0.5)
    for _ in range(3):
        fleet.add_endpoint()
    fleet.wait_ready(30)
    names = fleet.names()
    victim = names[random.Random(int(seed) * 7919 + 29).randrange(
        len(names))]

    sessions: List[Dict[str, Any]] = []
    for i in range(int(n_sessions)):
        t0 = int(rng.integers(3, 6))
        prompt = rng.integers(1, vocab, (1, t0))
        temp = 0.7 if i % 2 == 0 else 0.0
        oracle = generate_eager(lm, prompt, int(total), temperature=temp,
                                seed=int(seed) * 31 + i)
        sessions.append({
            "sid": f"hib-{seed}-{i}", "prompt": prompt, "temp": temp,
            "seed": int(seed) * 31 + i,
            "oracle": np.asarray(oracle), "coll": _StreamCollector()})

    mismatches = dup_offsets = gap_events = 0
    handles_shipped = 0
    resumed = 0
    pressure = None
    try:
        # ---- turn 1: hibernate every session -----------------------
        futs = []
        for s in sessions:
            for _ in range(200):
                try:
                    futs.append(router.submit_generate(
                        s["prompt"], int(turn1), temperature=s["temp"],
                        seed=s["seed"], model="lm", session=s["sid"],
                        hibernate=True, on_tokens=s["coll"]))
                    break
                except RetryAfter:
                    time.sleep(0.05)
        deadline = time.monotonic() + timeout_s
        for s, f in zip(sessions, futs):
            got = np.asarray(f.result(
                timeout=max(0.1, deadline - time.monotonic())))
            t0 = s["prompt"].shape[1]
            if not np.array_equal(got, s["oracle"][:, :t0 + int(turn1)]):
                mismatches += 1
            if router.hibernation_handle(s["sid"]) is None:
                mismatches += 1
            elif "payload" in router.hibernation_handle(s["sid"]):
                handles_shipped += 1

        # ---- the outage: one endpoint dies with parked sessions ----
        kill_endpoint(fleet, victim)

        # ---- resume ALL sessions on whatever survives --------------
        # second half under host-tier pressure: the survivors' landing
        # docks refuse the shipped blocks, so those resumes MUST take
        # the journaled-prefix rung — and stay exact
        half = len(sessions) // 2
        for j, s in enumerate(sessions):
            if j == half:
                pressure = [HostTierPressure(e, budget=0).squeeze()
                            for e in engines
                            if not e._closed
                            and e._scheduler is not None]
            fut = router.resume_generate(
                s["sid"], int(total), model="lm",
                temperature=s["temp"], seed=s["seed"],
                on_tokens=s["coll"])
            got = np.asarray(fut.result(
                timeout=max(0.1, deadline - time.monotonic())))
            if not np.array_equal(got, s["oracle"]):
                mismatches += 1
            t0 = s["prompt"].shape[1]
            want = [int(t) for t in s["oracle"][0, t0:]]
            if s["coll"].tokens != want:
                mismatches += 1
            dup_offsets += s["coll"].dups
            gap_events += s["coll"].gaps
            resumed += 1

        # ---- both tiers drain to empty on every engine ever alive --
        leaked = leaked_host = 0
        for eng in engines:
            if not eng._closed:
                eng.drain(timeout=30)
            sched = eng._scheduler
            if sched is None:
                continue
            for c in sched.prefix_caches():
                c.clear()
            free_deadline = time.monotonic() + 10
            while time.monotonic() < free_deadline:
                st = sched.stats()
                pool = st["pool"]
                if (pool["blocks_free"] >= pool["blocks_total"]
                        and st["kvtier"]["host_blocks_used"] == 0):
                    break
                time.sleep(0.02)
            st = sched.stats()
            leaked += int(st["pool"]["blocks_total"]
                          - st["pool"]["blocks_free"])
            leaked_host += int(st["kvtier"]["host_blocks_used"])
        stranded_handles = len(router.hibernated_sessions())
    finally:
        for p in pressure or ():
            p.heal()
        try:
            fleet.shutdown(drain=False)
        except BaseException:
            pass
        router.close()

    return {
        "seed": int(seed),
        "victim": victim,
        "sessions": len(sessions),
        "handles_shipped": handles_shipped,
        "resumed": resumed,
        "token_mismatches": mismatches,
        "dup_offsets": dup_offsets,
        "gap_events": gap_events,
        "leaked_blocks": leaked,
        "leaked_host_blocks": leaked_host,
        "stranded_handles": stranded_handles,
    }


def run_slice_drill(seed: int = 0, n_requests: int = 12, n_events: int = 2,
                    max_new: int = 6, slice_width: int = 2,
                    n_slices: int = 2, timeout_s: float = 120.0,
                    per_try_timeout_s: float = 4.0,
                    wedge_timeout_s: float = 1.0,
                    pace_s: float = 0.02) -> Dict[str, Any]:
    """The MESH-SLICE composed drill (ISSUE 12): ``n_slices`` serving
    endpoints, each a ``slice_width``-chip mesh slice restored from ONE
    mesh-portable model artifact, under mixed decode-stream + classify
    load while the seeded clock composes ``slice_kill`` (a chip dies
    INSIDE a slice → the engine poisons itself with typed
    ``SliceDegraded`` → streams migrate via the journal/resume path →
    the heal tick REBUILDS the slice at half width from the survivors),
    heartbeat partitions and wedges. Invariants after drain: every
    request resolves with the exact single-device output (bitwise
    classify, token-for-token greedy/sampled streams — the house bar
    holds THROUGH chip death), append-only delivery (dup=0, gap=0),
    zero leaked KV blocks across every engine ever alive (dead slices
    included), and the fleet converges with every endpoint back in the
    pool."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.faultinject import NetworkPartition
    from deeplearning4j_tpu.models.zoo.transformer import gpt
    from deeplearning4j_tpu.nn.generate import generate_eager
    from deeplearning4j_tpu.parallel.inference import ParallelInference
    from deeplearning4j_tpu.serving import (InferenceRouter, LocalFleet,
                                            RetryAfter)
    from deeplearning4j_tpu.util.model_serializer import (restore_model,
                                                          write_model)

    need = slice_width * n_slices
    if len(jax.devices()) < need:
        raise RuntimeError(
            f"slice drill needs {need} devices, have {len(jax.devices())}")

    vocab = 11

    def make_lm():
        return gpt(vocab_size=vocab, d_model=16, n_layers=2, num_heads=2,
                   max_len=32, compute_dtype="float32", learning_rate=0.01,
                   seed=0).init()

    lm = make_lm()  # the single-device oracle
    art_dir = tempfile.mkdtemp(prefix="dl4j-slice-drill-")
    art = os.path.join(art_dir, "lm.zip")
    write_model(lm, art)

    engines: List[ParallelInference] = []

    def engine_factory(plane):
        # ONE saved artifact deploys onto ANY slice width — the
        # mesh-portable contract; apply_serving_slice re-lowers it
        net = restore_model(art)
        eng = ParallelInference(net=net, slice_plane=plane,
                                max_batch_size=4, max_latency_ms=1.0,
                                queue_capacity=256, continuous=True,
                                decode_slots=2, decode_burst=4,
                                kv_block_size=4)
        engines.append(eng)
        return eng

    router = InferenceRouter(per_try_timeout_s=per_try_timeout_s,
                             eject_backoff_s=0.1, max_attempts=6,
                             wedge_timeout_s=wedge_timeout_s)
    fleet = LocalFleet(engine_factory, router=router, heartbeat_s=0.05,
                       request_timeout_s=per_try_timeout_s,
                       heartbeat_timeout_s=0.5,
                       slice_width=slice_width,
                       slice_devices=jax.devices()[:need])
    for _ in range(n_slices):
        fleet.add_endpoint()
    fleet.wait_ready(30)
    names = fleet.names()
    schedule = ChaosSchedule(seed, n_events=n_events,
                             n_endpoints=n_slices, actions=SLICE_ACTIONS)
    rng = np.random.default_rng(int(seed) * 104729 + 7)
    partitions = {}
    for name in names:
        part = NetworkPartition(fleet._broker,
                                topic_substr=name + ".hb", silent=True)
        fleet.endpoint(name)._hb_broker = part
        partitions[name] = part

    rebuilt_widths: List[int] = []
    dead: Dict[str, bool] = {}

    def apply(ev: ChaosEvent) -> Callable[[], None]:
        name = names[ev.target % len(names)]
        if ev.action == "slice_kill":
            if dead.get(name):
                return lambda: None
            fleet.kill_chip(name, seed=seed * 31 + ev.tick)
            dead[name] = True
            # trip the armed injector deterministically: the poisoned
            # chip fails the very next dispatch, and the engine
            # declares the slice degraded in its heartbeats
            eng = fleet._members[name].worker.engine
            try:
                eng.output(np.zeros((1, 4), np.float32), timeout=10)
            except BaseException:
                pass

            def heal():
                # ELASTIC REBUILD: half width from the survivors —
                # never a restart of the dead chip
                rebuilt_widths.append(fleet.rebuild_slice(name))
                dead[name] = False
            return heal
        if ev.action == "partition_hb":
            part = partitions[name].partition()
            return part.heal
        if ev.action == "wedge":
            if dead.get(name):
                return lambda: None
            fleet.wedge(name)
            return lambda: fleet.unwedge(name)
        raise ValueError(f"unknown slice action {ev.action!r}")

    pending_events = list(schedule.events)
    pending_heals: List[Tuple[int, Callable[[], None]]] = []
    futs: List[list] = []
    submitted = 0

    def _fire(r: Dict[str, Any], attempt: int = 0):
        if r["kind"] == "decode":
            coll = _StreamCollector()
            fut = router.submit_generate(
                r["x"], max_new, temperature=r["temp"], seed=r["seed"],
                session=f"slice-{r['seed']}-{attempt}", on_tokens=coll)
            return fut, coll
        return router.submit(r["x"]), None

    try:
        for tick in range(n_requests):
            for _, heal in [h for h in pending_heals if h[0] <= tick]:
                heal()
            pending_heals = [h for h in pending_heals if h[0] > tick]
            for ev in [e for e in pending_events if e.tick <= tick]:
                pending_heals.append((ev.heal_tick, apply(ev)))
            pending_events = [e for e in pending_events if e.tick > tick]

            if tick % 2 == 0:
                t0 = int(rng.integers(3, 6))
                prompt = rng.integers(1, vocab, (1, t0))
                temp = 0.7 if tick % 4 == 0 else 0.0
                oracle = generate_eager(lm, prompt, max_new,
                                        temperature=temp, seed=tick)
                req = {"kind": "decode", "x": prompt, "temp": temp,
                       "seed": tick, "oracle": oracle}
            else:
                ids = rng.integers(1, vocab, (1, 6))
                req = {"kind": "classify", "x": ids,
                       "oracle": np.asarray(lm.output(ids))}
            for _ in range(200):
                try:
                    fut, coll = _fire(req)
                    futs.append([req["kind"], fut, req["oracle"], coll,
                                 req])
                    submitted += 1
                    break
                except RetryAfter:
                    time.sleep(0.05)
            time.sleep(pace_s)

        for _, heal in pending_heals:
            heal()
        for name in names:
            partitions[name].heal()
            try:
                fleet.unwedge(name)
            except BaseException:
                pass
            if dead.get(name):
                rebuilt_widths.append(fleet.rebuild_slice(name))
                dead[name] = False
        router.probe_now()

        deadline = time.monotonic() + timeout_s
        for entry in futs:
            try:
                entry[1].result(
                    timeout=max(0.1, deadline - time.monotonic()))
            except BaseException:
                pass
        # typed failures during the all-bad window get bounded
        # resubmission against the healed fleet, exactly like the main
        # drill — the exactness audit applies to each delivered stream
        for retry_round in range(1, 4):
            pending = [e for e in futs
                       if e[1].done() and e[1].exception() is not None]
            if not pending:
                break
            for entry in pending:
                for _ in range(100):
                    try:
                        entry[1], entry[3] = _fire(entry[4], retry_round)
                        break
                    except RetryAfter:
                        time.sleep(0.05)
            for entry in pending:
                try:
                    entry[1].result(
                        timeout=max(0.1, deadline - time.monotonic()))
                except BaseException:
                    pass
        failed = sum(1 for _, f, _, _, _ in futs
                     if f.done() and f.exception() is not None)
        stranded = sum(1 for _, f, _, _, _ in futs if not f.done())

        mismatches = dup_offsets = gap_events = 0
        for kind, fut, oracle, coll, _r in futs:
            if not fut.done() or fut.exception() is not None:
                continue
            got = np.asarray(fut.result())
            if not np.array_equal(got, oracle):
                mismatches += 1
            if coll is not None:
                dup_offsets += coll.dups
                gap_events += coll.gaps
                if coll.tokens != [int(t) for t in oracle[0, -max_new:]]:
                    mismatches += 1

        healthy = 0
        conv_deadline = time.monotonic() + 30
        probe = rng.integers(1, vocab, (1, 4))
        while time.monotonic() < conv_deadline:
            router.probe_now()
            try:
                router.output(probe, timeout=10)
            except BaseException:
                pass
            healthy = router.fleet_snapshot()["healthy_endpoints"]
            if healthy >= n_slices:
                break
            time.sleep(0.05)

        leaked = 0
        for eng in engines:
            if not eng._closed and eng._slice_dead is None:
                eng.drain(timeout=30)
            sched = eng._scheduler
            if sched is None:
                continue
            free_deadline = time.monotonic() + 10
            while time.monotonic() < free_deadline:
                pool = sched.stats()["pool"]
                if pool["blocks_free"] >= pool["blocks_total"]:
                    break
                time.sleep(0.02)
            pool = sched.stats()["pool"]
            leaked += int(pool["blocks_total"] - pool["blocks_free"])
    finally:
        try:
            fleet.shutdown(drain=False)
        except BaseException:
            pass
        router.close()

    return {
        "seed": int(seed),
        "schedule": schedule.signature(),
        "submitted": submitted,
        "completed": submitted - failed - stranded,
        "failed": failed,
        "stranded_futures": stranded,
        "token_mismatches": mismatches,
        "dup_offsets": dup_offsets,
        "gap_events": gap_events,
        "leaked_blocks": leaked,
        "healthy_endpoints": healthy,
        "slice_rebuilds": len(rebuilt_widths),
        "rebuilt_widths": sorted(rebuilt_widths),
    }
