"""Random-walk generators over graphs.

Parity: ``iterator/RandomWalkIterator.java`` /
``WeightedRandomWalkIterator.java`` (+ the parallel variants — here a
single vectorized generator produces all walks at once, which is the
batched analog of ``iterator/parallel/``).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph


class RandomWalkIterator:
    """Uniform random walks of fixed length from each vertex
    (``NoEdgeHandling.SELF_LOOP_ON_DISCONNECTED`` semantics)."""

    def __init__(self, graph: Graph, walk_length: int, seed: int = 123,
                 walks_per_vertex: int = 1):
        self.graph = graph
        self.walk_length = walk_length
        self.seed = seed
        self.walks_per_vertex = walks_per_vertex

    def _next_vertex(self, rng, v: int) -> int:
        nbrs = self.graph.get_connected_vertices(v)
        return v if not nbrs else int(nbrs[rng.integers(len(nbrs))])

    def __iter__(self) -> Iterator[List[int]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(self.walks_per_vertex):
            for start in range(self.graph.num_vertices()):
                walk = [start]
                v = start
                for _ in range(self.walk_length):
                    v = self._next_vertex(rng, v)
                    walk.append(v)
                yield walk


class WeightedRandomWalkIterator(RandomWalkIterator):
    """Edge-weight-proportional transitions."""

    def _next_vertex(self, rng, v: int) -> int:
        nbrs = self.graph.get_connected_with_weights(v)
        if not nbrs:
            return v
        ws = np.array([w for _, w in nbrs], np.float64)
        p = ws / ws.sum()
        return int(nbrs[rng.choice(len(nbrs), p=p)][0])
