"""In-memory graph structure + loaders.

Parity: ``deeplearning4j-graph``'s ``api/IGraph.java``,
``graph/Graph.java``, ``data/GraphLoader.java`` (edge-list files) —
SURVEY.md §2.4.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Vertex:
    idx: int
    value: Any = None


@dataclasses.dataclass
class Edge:
    frm: int
    to: int
    weight: float = 1.0
    directed: bool = False


class Graph:
    """Adjacency-list graph (``graph/Graph.java``)."""

    def __init__(self, num_vertices: int, directed: bool = False):
        self.directed = directed
        self.vertices = [Vertex(i) for i in range(num_vertices)]
        self._adj: List[List[Tuple[int, float]]] = [[] for _ in range(num_vertices)]

    def num_vertices(self) -> int:
        return len(self.vertices)

    def add_edge(self, frm: int, to: int, weight: float = 1.0):
        self._adj[frm].append((to, weight))
        if not self.directed:
            self._adj[to].append((frm, weight))

    def get_connected_vertices(self, v: int) -> List[int]:
        return [t for t, _ in self._adj[v]]

    def get_connected_with_weights(self, v: int) -> List[Tuple[int, float]]:
        return list(self._adj[v])

    def degree(self, v: int) -> int:
        return len(self._adj[v])


def load_edge_list(path: str, num_vertices: Optional[int] = None,
                   directed: bool = False, delimiter: Optional[str] = None) -> Graph:
    """``GraphLoader.loadUndirectedGraphEdgeListFile`` — 'from to [weight]'
    lines."""
    edges = []
    max_v = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split(delimiter)
            a, b = int(parts[0]), int(parts[1])
            w = float(parts[2]) if len(parts) > 2 else 1.0
            edges.append((a, b, w))
            max_v = max(max_v, a, b)
    g = Graph(num_vertices or (max_v + 1), directed)
    for a, b, w in edges:
        g.add_edge(a, b, w)
    return g
