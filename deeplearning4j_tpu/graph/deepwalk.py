"""DeepWalk — skip-gram embeddings over random walks.

Parity: ``models/deepwalk/DeepWalk.java:31`` (skip-gram with
hierarchical softmax over walk windows, ``GraphHuffman`` tree keyed by
vertex degree, ``InMemoryGraphLookupTable``). Serialization matches
``models/loader/GraphVectorSerializer.java`` (text rows of vertex id +
vector).

TPU formulation: walks are sequences of vertex-id tokens, so training
reuses the batched SequenceVectors HS/SGNS steps verbatim — the reference
duplicated the word2vec math for graphs; here it is literally the same
compiled kernel.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.graph.graph import Graph
from deeplearning4j_tpu.graph.walks import RandomWalkIterator
from deeplearning4j_tpu.models.embeddings.lookup_table import WordVectors
from deeplearning4j_tpu.models.sequencevectors.engine import SequenceVectors


class DeepWalk:
    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 2,
                 learning_rate: float = 0.025, epochs: int = 1,
                 use_hierarchic_softmax: bool = True, negative: int = 5,
                 batch_size: int = 2048, seed: int = 123):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.use_hs = use_hierarchic_softmax
        self.negative = negative
        self.batch_size = batch_size
        self.seed = seed
        self._sv: Optional[SequenceVectors] = None
        self.graph: Optional[Graph] = None

    def fit(self, graph: Graph, walk_iterator: Optional[RandomWalkIterator] = None):
        self.graph = graph
        it = walk_iterator or RandomWalkIterator(
            graph, self.walk_length, self.seed, self.walks_per_vertex)
        walks = [[str(v) for v in walk] for walk in it]
        self._sv = SequenceVectors(
            vector_length=self.vector_size, window=self.window_size,
            epochs=self.epochs, learning_rate=self.learning_rate,
            negative=self.negative, use_hierarchic_softmax=self.use_hs,
            batch_size=self.batch_size, seed=self.seed)
        self._sv.fit(walks)

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self._sv.word_vectors().get_word_vector(str(v))

    def similarity(self, a: int, b: int) -> float:
        return self._sv.word_vectors().similarity(str(a), str(b))

    def verts_nearest(self, v: int, n: int = 10) -> List[int]:
        return [int(w) for w in self._sv.word_vectors().words_nearest(str(v), n)]

    def save(self, path: str):
        """``GraphVectorSerializer.writeGraphVectors`` — 'id v1 v2 ...'."""
        wv = self._sv.word_vectors()
        with open(path, "w") as f:
            for i in range(self.graph.num_vertices()):
                if wv.has_word(str(i)):
                    vec = " ".join(f"{x:.6f}" for x in wv.get_word_vector(str(i)))
                    f.write(f"{i} {vec}\n")

    @staticmethod
    def load(path: str, graph: Graph) -> "WordVectors":
        from deeplearning4j_tpu.models.word2vec.vocab import VocabCache
        ids, vecs = [], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                ids.append(parts[0])
                vecs.append([float(x) for x in parts[1:]])
        vocab = VocabCache()
        for k, i in enumerate(ids):
            vocab.add_token(i, len(ids) - k)
        vocab.finish()
        return WordVectors(vocab, np.asarray(vecs, np.float32))
