from deeplearning4j_tpu.graph.graph import Graph, Vertex, Edge  # noqa: F401
from deeplearning4j_tpu.graph.walks import RandomWalkIterator, WeightedRandomWalkIterator  # noqa: F401
from deeplearning4j_tpu.graph.deepwalk import DeepWalk  # noqa: F401
