"""Streaming train / inference pipelines over a MessageBroker.

Parity: ``dl4j-streaming/.../pipeline/spark/SparkStreamingPipeline.java``
(consume DataSets from Kafka, fit per micro-batch) and
``routes/DL4jServeRouteBuilder.java`` (serve route: features in →
predictions out). A stream here is just a ``DataSetIterator`` whose
``has_next`` blocks on the broker, so it feeds the SAME compiled
fit/output hot paths as batch training — micro-batching is the
device-efficiency knob (bigger batches = better MXU utilisation), not a
separate execution engine.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.monitor import (FAULT_DEAD_LETTER_COUNTER,
                                        get_registry, record_fault, span)
from deeplearning4j_tpu.streaming.broker import MessageBroker
from deeplearning4j_tpu.streaming.serde import (
    dataset_from_bytes, dataset_to_bytes, ndarray_from_bytes, ndarray_to_bytes)

logger = logging.getLogger("deeplearning4j_tpu")

_STOP = b"__dl4j_tpu_stream_stop__"

DEAD_LETTER_SUFFIX = ".deadletter"


def dead_letter(broker: MessageBroker, topic: str, payload: bytes,
                error: BaseException, dead_letter_topic: Optional[str] = None
                ) -> None:
    """Route an undecodable message to the dead-letter topic (default
    ``<topic>.deadletter``) instead of killing the consume thread — the
    Kafka DLQ discipline: one poison message must not take down the
    route; the payload stays inspectable on the DLQ."""
    dlq = dead_letter_topic or topic + DEAD_LETTER_SUFFIX
    record_fault("transport")
    get_registry().counter(
        FAULT_DEAD_LETTER_COUNTER,
        "Undecodable messages routed to a dead-letter topic",
        topic=topic).inc()
    logger.warning("stream %s: undecodable message (%s: %s) routed to %s",
                   topic, type(error).__name__, error, dlq)
    try:
        broker.publish(dlq, payload)
    except BaseException:
        logger.exception("stream %s: dead-letter publish to %s failed "
                         "(message dropped)", topic, dlq)


def publish_dataset(broker: MessageBroker, topic: str, ds: DataSet) -> None:
    broker.publish(topic, dataset_to_bytes(ds))


def publish_stop(broker: MessageBroker, topic: str) -> None:
    """Poison pill: downstream iterators/trainers drain and exit."""
    broker.publish(topic, _STOP)


class StreamingDataSetIterator(DataSetIterator):
    """Broker topic → blocking DataSetIterator.

    Accumulates incoming DataSets until ``batch_size`` examples are
    buffered (micro-batching), then emits one concatenated DataSet.
    ``has_next`` returns False after a stop pill or an idle period of
    ``idle_timeout`` seconds (None = wait forever). An undecodable
    message goes to ``dead_letter_topic`` (default
    ``<topic>.deadletter``) and consumption continues.
    """

    def __init__(self, broker: MessageBroker, topic: str, batch_size: int = 32,
                 idle_timeout: Optional[float] = None,
                 dead_letter_topic: Optional[str] = None):
        self.broker = broker
        self.topic = topic
        self.batch_size = batch_size
        self.idle_timeout = idle_timeout
        self.dead_letter_topic = dead_letter_topic or topic + DEAD_LETTER_SUFFIX
        self._buffer: List[DataSet] = []
        self._buffered = 0
        self._pending: Optional[DataSet] = None
        self._stopped = False

    def _pull(self) -> bool:
        """Fetch one message into the buffer; False on stop/timeout
        (a poison message dead-letters and counts as a successful pull
        so the caller keeps consuming)."""
        with span("data_load", path="stream_consume", topic=self.topic):
            payload = self.broker.consume(self.topic, timeout=self.idle_timeout)
            if payload is None or payload == _STOP:
                self._stopped = True
                return False
            try:
                ds = dataset_from_bytes(payload)
            except Exception as e:
                dead_letter(self.broker, self.topic, payload, e,
                            self.dead_letter_topic)
                return True
        self._buffer.append(ds)
        self._buffered += ds.num_examples()
        return True

    def _emit(self) -> Optional[DataSet]:
        if not self._buffer:
            return None
        parts = self._buffer
        self._buffer, self._buffered = [], 0
        if len(parts) == 1:
            return parts[0]

        def cat_masks(masks, shapes):
            # mixed presence: a missing mask means "all valid" — fill
            # with ones so no part's padding info is dropped
            if all(m is None for m in masks):
                return None
            return np.concatenate(
                [np.ones(shape, np.float32) if m is None else m
                 for m, shape in zip(masks, shapes)], axis=0)

        return DataSet(
            features=np.concatenate([p.features for p in parts], axis=0),
            labels=np.concatenate([p.labels for p in parts], axis=0),
            features_mask=cat_masks([p.features_mask for p in parts],
                                    [p.features.shape[:2] for p in parts]),
            labels_mask=cat_masks([p.labels_mask for p in parts],
                                  [p.labels.shape[:2] for p in parts]))

    def has_next(self) -> bool:
        if self._pending is not None:
            return True
        while not self._stopped and self._buffered < self.batch_size:
            if not self._pull():
                break
        self._pending = self._emit()
        return self._pending is not None

    def _next_impl(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        out, self._pending = self._pending, None
        return out

    def batch(self) -> int:
        return self.batch_size

    def reset(self) -> None:  # streams don't rewind (Kafka offset semantics)
        pass

    def async_supported(self) -> bool:
        return True


class StreamingTrainer:
    """Consume DataSets from a topic and fit the model per micro-batch
    (``SparkStreamingPipeline`` train role). Runs inline (``run``) or on
    a daemon thread (``start``/``join``)."""

    def __init__(self, net, broker: MessageBroker, topic: str,
                 batch_size: int = 32, idle_timeout: Optional[float] = None,
                 dead_letter_topic: Optional[str] = None):
        self.net = net
        self.iterator = StreamingDataSetIterator(
            broker, topic, batch_size=batch_size, idle_timeout=idle_timeout,
            dead_letter_topic=dead_letter_topic)
        self.batches_fit = 0
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def run(self, max_batches: Optional[int] = None) -> int:
        reg = get_registry()
        batches = reg.counter("dl4j_stream_batches_total",
                              "Micro-batches fit from the stream",
                              topic=self.iterator.topic)
        examples = reg.counter("dl4j_stream_examples_total",
                               "Examples fit from the stream",
                               topic=self.iterator.topic)
        while self.iterator.has_next():
            ds = self.iterator.next()
            self.net.fit(ds)  # the model's own data_load/device_step spans
            self.batches_fit += 1
            batches.inc()
            examples.inc(ds.num_examples())
            reg.gauge("dl4j_stream_buffer_examples",
                      "Examples buffered awaiting a micro-batch",
                      topic=self.iterator.topic).set(self.iterator._buffered)
            if max_batches is not None and self.batches_fit >= max_batches:
                break
        return self.batches_fit

    def start(self, max_batches: Optional[int] = None) -> "StreamingTrainer":
        def _target():
            try:
                self.run(max_batches)
            except BaseException as e:  # surfaced in join()
                self._error = e
        self._thread = threading.Thread(target=_target, daemon=True,
                                        name="dl4j-tpu-stream-train")
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> int:
        if self._thread:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("streaming trainer still running")
        if self._error is not None:
            raise self._error
        return self.batches_fit


class StreamingInference:
    """Serve route (``DL4jServeRouteBuilder``): consume feature arrays
    from ``in_topic``, publish predictions to ``out_topic`` until a stop
    pill (or idle timeout) arrives.

    The serve loop dispatches through a ``ParallelInference`` engine
    (``parallel/inference.py``): the consume thread only deserializes
    and ``submit()``s — concurrent requests coalesce into padded
    micro-batches on the engine's replicas while a publisher thread
    awaits each Future in arrival order, serializes, and publishes, so
    serde never sits on the device-dispatch critical path and ordering
    on ``out_topic`` is preserved. Pass an ``engine`` to share replicas
    across routes (and ``warmup()`` it before traffic), or
    ``engine=False`` for the legacy inline per-request ``net.output``
    loop (the bench baseline)."""

    def __init__(self, net, broker: MessageBroker, in_topic: str,
                 out_topic: str, idle_timeout: Optional[float] = None,
                 engine=None, max_batch_size: int = 32,
                 max_latency_ms: float = 5.0,
                 dead_letter_topic: Optional[str] = None):
        self.net = net
        self.broker = broker
        self.in_topic = in_topic
        self.out_topic = out_topic
        self.idle_timeout = idle_timeout
        self.dead_letter_topic = dead_letter_topic or (
            in_topic + DEAD_LETTER_SUFFIX)
        self.engine = engine
        self.max_batch_size = max_batch_size
        self.max_latency_ms = max_latency_ms
        self.served = 0
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def _run_inline(self, requests, max_requests: Optional[int]) -> int:
        while True:
            with span("data_load", path="stream_serve", topic=self.in_topic):
                payload = self.broker.consume(self.in_topic,
                                              timeout=self.idle_timeout)
            if payload is None or payload == _STOP:
                break
            try:
                x = ndarray_from_bytes(payload)
            except Exception as e:
                dead_letter(self.broker, self.in_topic, payload, e,
                            self.dead_letter_topic)
                continue
            with span("inference", topic=self.in_topic):
                pred = np.asarray(self.net.output(x))
                self.broker.publish(self.out_topic, ndarray_to_bytes(pred))
            self.served += 1
            requests.inc()
            if max_requests is not None and self.served >= max_requests:
                break
        return self.served

    def run(self, max_requests: Optional[int] = None) -> int:
        requests = get_registry().counter(
            "dl4j_stream_requests_total", "Inference requests served",
            topic=self.in_topic)
        if self.engine is False:
            return self._run_inline(requests, max_requests)
        from deeplearning4j_tpu.parallel.inference import ParallelInference
        engine = self.engine
        own = engine is None
        if own:
            engine = ParallelInference(self.net,
                                       max_batch_size=self.max_batch_size,
                                       max_latency_ms=self.max_latency_ms,
                                       replicas=1)
        import queue as _queue
        done = object()
        out_q: "_queue.Queue" = _queue.Queue()
        pub_error: List[BaseException] = []

        def _publish():
            # awaits futures in submit order: out_topic keeps the
            # in_topic arrival order even though batches complete on
            # whichever replica finishes first
            while True:
                fut = out_q.get()
                if fut is done:
                    return
                try:
                    pred = fut.result()
                    self.broker.publish(self.out_topic, ndarray_to_bytes(pred))
                except BaseException as e:
                    if not pub_error:
                        pub_error.append(e)
                    continue
                self.served += 1
                requests.inc()

        publisher = threading.Thread(target=_publish, daemon=True,
                                     name="dl4j-tpu-stream-publish")
        publisher.start()
        submitted = 0
        try:
            while True:
                with span("data_load", path="stream_serve",
                          topic=self.in_topic):
                    payload = self.broker.consume(self.in_topic,
                                                  timeout=self.idle_timeout)
                if payload is None or payload == _STOP:
                    break
                try:
                    x = ndarray_from_bytes(payload)
                except Exception as e:
                    # poison request: dead-letter it; the publisher and
                    # engine never see it, ordering of good requests holds
                    dead_letter(self.broker, self.in_topic, payload, e,
                                self.dead_letter_topic)
                    continue
                out_q.put(engine.submit(x))
                submitted += 1
                if max_requests is not None and submitted >= max_requests:
                    break
        finally:
            out_q.put(done)
            publisher.join()
            if own:
                try:
                    engine.shutdown()
                except BaseException as e:
                    if not pub_error:
                        pub_error.append(e)
        if pub_error:
            raise pub_error[0]
        return self.served

    def start(self, max_requests: Optional[int] = None) -> "StreamingInference":
        def _target():
            try:
                self.run(max_requests)
            except BaseException as e:
                self._error = e
        self._thread = threading.Thread(target=_target, daemon=True,
                                        name="dl4j-tpu-stream-serve")
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> int:
        if self._thread:
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError("streaming inference still running")
        if self._error is not None:
            raise self._error
        return self.served
