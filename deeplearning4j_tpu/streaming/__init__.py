"""Streaming ingest/serve plane.

Parity: ``dl4j-streaming`` (SURVEY.md §2.6) — Kafka NDArray/DataSet
publish+consume (``streaming/kafka/NDArrayKafkaClient.java``), Camel
routes (``routes/DL4jServeRouteBuilder.java``), and Spark-streaming
train/inference pipelines (``pipeline/spark/SparkStreamingPipeline.java``).

TPU-first re-design: the broker is an SPI (``MessageBroker``) with an
in-process queue impl and a dependency-free TCP impl (the Kafka role on
a zero-egress pod; a real Kafka client would plug into the same SPI).
Wire format is npz — self-describing, dtype/shape-safe, zero-copy into
numpy. Pipelines feed the SAME compiled fit/output paths as batch
training: a stream is just a DataSetIterator whose ``has_next`` blocks.
"""

from deeplearning4j_tpu.streaming.broker import (  # noqa: F401
    BrokerUnavailable,
    InMemoryBroker,
    MessageBroker,
    TcpBroker,
    TcpBrokerServer,
)
from deeplearning4j_tpu.streaming.pipeline import (  # noqa: F401
    StreamingDataSetIterator,
    StreamingInference,
    StreamingTrainer,
)
from deeplearning4j_tpu.streaming.serde import (  # noqa: F401
    dataset_from_bytes,
    dataset_to_bytes,
    ndarray_from_bytes,
    ndarray_to_bytes,
)
