"""NDArray / DataSet wire serialization.

Parity: ``dl4j-streaming/.../serde/`` + ``NDArrayKafkaClient.java``
(base64-JSON NDArray payloads). Here the payload is npz bytes:
self-describing (dtype+shape embedded), portable, and loads straight
into numpy without a codec layer.
"""

from __future__ import annotations

import io
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


def ndarray_to_bytes(arr: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(arr), allow_pickle=False)
    return buf.getvalue()


def ndarray_from_bytes(data: bytes) -> np.ndarray:
    return np.load(io.BytesIO(data), allow_pickle=False)


def dataset_to_bytes(ds: DataSet) -> bytes:
    arrays = {"features": ds.features, "labels": ds.labels}
    if ds.features_mask is not None:
        arrays["features_mask"] = ds.features_mask
    if ds.labels_mask is not None:
        arrays["labels_mask"] = ds.labels_mask
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def dataset_from_bytes(data: bytes) -> DataSet:
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        def opt(name) -> Optional[np.ndarray]:
            return z[name] if name in z.files else None
        return DataSet(features=z["features"], labels=z["labels"],
                       features_mask=opt("features_mask"),
                       labels_mask=opt("labels_mask"))
