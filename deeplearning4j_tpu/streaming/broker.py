"""Message broker SPI + in-memory and TCP implementations.

Parity: the Kafka producer/consumer pair in
``dl4j-streaming/.../kafka/NDArrayKafkaClient.java`` (+
``NDArrayPublisher``/``NDArrayConsumer``). The SPI keeps the pipeline
layer transport-agnostic; ``InMemoryBroker`` is the test/dev transport,
``TcpBroker(Server)`` is a dependency-free network transport with
length-prefixed frames and per-topic FIFO queues (at-most-once, one
consumer group — the subset of Kafka semantics the reference pipelines
actually use).

The server runs a ``selectors``-based reactor by default: one event
loop owns every connection, the topic queues, and the long-poll parking
lot, so the data plane needs no server-side locks at all and scales to
thousands of idle long-pollers without a thread each. The pre-reactor
thread-per-connection server is kept behind ``reactor=False`` as the
measured baseline for ``bench.py router_saturation``.
"""

from __future__ import annotations

import collections
import logging
import queue
import random
import selectors
import socket
import socketserver
import struct
import threading
import time
from typing import Deque, Dict, Optional, Tuple

from deeplearning4j_tpu.monitor import record_fault

logger = logging.getLogger("deeplearning4j_tpu")

_MAX_FRAME = 1 << 30

#: Wire-v4 ping prologue. ``ping()`` rides the v4 binary header: the 'G'
#: payload opens with this magic + the speaker's wire version, and the
#: server echoes its own. Mirrored from ``serving.wire`` (which imports
#: the serving package and therefore, transitively, this module — the
#: constants live here to keep the layering acyclic; a lint pins them
#: equal to ``wire.WIRE_MAGIC``/``wire.WIRE_VERSION``).
PING_MAGIC = b"\xd4\x0a"
PING_VERSION = 4


class BrokerUnavailable(ConnectionError):
    """The broker could not be reached within the bounded reconnect
    budget. Distinct from ``consume`` returning ``None`` — that is a
    genuine long-poll timeout (broker healthy, topic empty); this means
    the transport itself is down and the caller should fail over or
    surface the outage instead of treating it as an idle stream."""


class MessageBroker:
    """Transport SPI: byte payloads on named topics.

    Liveness: ``ping()`` performs one cheap round-trip against the
    transport (raises on a dead one) and every successful operation
    refreshes ``last_seen`` (``time.monotonic()``), so a health plane
    can read connection liveness directly instead of inferring death
    from consume timeouts."""

    #: monotonic timestamp of the last successful broker round-trip
    #: (None until the first one).
    last_seen: Optional[float] = None

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        """Pop the next payload, blocking up to ``timeout`` seconds.
        Returns None on timeout."""
        raise NotImplementedError

    def ping(self) -> float:
        """One liveness round-trip; returns the RTT in seconds and
        refreshes ``last_seen``. Raises (e.g.
        :class:`BrokerUnavailable`) when the transport is dead."""
        t0 = time.monotonic()
        self.last_seen = time.monotonic()
        return time.monotonic() - t0

    def close(self) -> None:
        pass


class InMemoryBroker(MessageBroker):
    """Per-topic FIFO queues in-process."""

    def __init__(self):
        self._topics: Dict[str, "queue.Queue[bytes]"] = {}
        self._lock = threading.Lock()
        self.last_seen: Optional[float] = None

    def ping(self) -> float:
        t0 = time.monotonic()
        with self._lock:
            pass  # in-process: the lock round-trip IS the transport
        self.last_seen = time.monotonic()
        return self.last_seen - t0

    def _q(self, topic: str) -> "queue.Queue[bytes]":
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = queue.Queue()
            return self._topics[topic]

    def publish(self, topic: str, payload: bytes) -> None:
        self._q(topic).put(bytes(payload))
        self.last_seen = time.monotonic()

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            msg = self._q(topic).get(timeout=timeout)
        except queue.Empty:
            msg = None
        self.last_seen = time.monotonic()
        return msg


# --- TCP transport ----------------------------------------------------------
# Frame: 1-byte op ('P' publish / 'C' consume / 'G' ping) + u16 topic len +
#        topic utf-8 + u32 payload len + payload.
# Reply: 1-byte status (1 = payload follows / 0 = none-or-ack) + u32 len +
#        payload. The status byte keeps zero-length payloads distinguishable
#        from a consume poll timeout. 'G' frames carry an empty topic; their
#        payload opens with PING_MAGIC + the client's wire version and the
#        server echoes PING_MAGIC + its own version (status 1) — a liveness
#        round-trip that doubles as wire-version discovery and refreshes the
#        server's per-peer last_seen table. Pre-v4 peers send/ack empty 'G'
#        frames; both sides treat a missing magic as "wire v3 peer".

def _send_frame(sock: socket.socket, op: bytes, topic: str, payload: bytes) -> None:
    t = topic.encode()
    sock.sendall(op + struct.pack(">HI", len(t), len(payload)) + t + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def _ping_reply() -> Tuple[bytes, bytes]:
    return b"\x01", PING_MAGIC + bytes([PING_VERSION])


class _BrokerHandler(socketserver.BaseRequestHandler):
    """Thread-per-connection handler (legacy ``reactor=False`` path)."""

    def handle(self):
        broker: InMemoryBroker = self.server._broker  # type: ignore[attr-defined]
        timeout = self.server._poll_timeout  # type: ignore[attr-defined]
        peers = self.server._peers  # type: ignore[attr-defined]
        peer = "%s:%s" % self.client_address[:2]
        try:
            while True:
                try:
                    op = _recv_exact(self.request, 1)
                except ConnectionError:
                    return
                tlen, plen = struct.unpack(">HI", _recv_exact(self.request, 6))
                if plen > _MAX_FRAME:
                    return
                topic = _recv_exact(self.request, tlen).decode()
                payload = _recv_exact(self.request, plen)
                if op == b"P":
                    broker.publish(topic, payload)
                    status, reply = b"\x00", b""
                elif op == b"C":
                    msg = broker.consume(topic, timeout=timeout)
                    status = b"\x00" if msg is None else b"\x01"
                    reply = msg or b""
                elif op == b"G":
                    if payload.startswith(PING_MAGIC):
                        status, reply = _ping_reply()
                    else:
                        status, reply = b"\x00", b""
                else:
                    return
                peers[peer] = time.monotonic()
                self.request.sendall(
                    status + struct.pack(">I", len(reply)) + reply)
        finally:
            peers.pop(peer, None)


class _Conn:
    """Reactor-side connection state. ``rbuf`` is the one preallocated
    recv buffer for the connection's lifetime (grown geometrically,
    never reallocated per frame); ``rlen`` is the filled prefix."""

    __slots__ = ("sock", "peer", "rbuf", "rlen", "out", "waiting")

    def __init__(self, sock: socket.socket, peer: str):
        self.sock = sock
        self.peer = peer
        self.rbuf = bytearray(64 << 10)
        self.rlen = 0
        self.out = bytearray()
        # (topic, deadline) while parked on an empty-topic long poll.
        self.waiting: Optional[Tuple[str, float]] = None


class _Reactor:
    """Single-threaded ``selectors`` event loop owning every broker
    connection, the topic queues, and the long-poll parking lot.

    All state below is loop-confined: only the reactor thread touches
    ``_topics``/``_parked``/connection objects, so the server side of
    the data plane holds zero locks (``peers()``/``address`` read
    snapshot-safe primitives under the GIL). Long polls park the
    connection instead of blocking a thread: a publish fulfils the
    oldest parked waiter inline, and the loop tick expires the rest."""

    def __init__(self, host: str, port: int, poll_timeout: float):
        self._poll_timeout = float(poll_timeout)
        self._listen = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listen.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listen.bind((host, port))
        self._listen.listen(1024)
        self._listen.setblocking(False)
        self.address = self._listen.getsockname()[:2]
        self._sel = selectors.DefaultSelector()
        self._sel.register(self._listen, selectors.EVENT_READ, None)
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._sel.register(self._wake_r, selectors.EVENT_READ, None)
        self._topics: Dict[str, Deque[bytes]] = {}
        self._parked: Dict[str, Deque[_Conn]] = {}
        self.peers: Dict[str, float] = {}
        self._stopping = False

    # ------------------------------------------------------------ loop

    def run(self) -> None:
        try:
            while not self._stopping:
                timeout = self._poll_timeout
                if any(self._parked.values()):
                    now = time.monotonic()
                    soonest = min(c.waiting[1]
                                  for dq in self._parked.values() for c in dq)
                    timeout = min(timeout, max(0.0, soonest - now))
                for key, mask in self._sel.select(timeout):
                    if key.fileobj is self._listen:
                        self._accept()
                    elif key.fileobj is self._wake_r:
                        try:
                            self._wake_r.recv(4096)
                        except OSError:
                            pass
                    else:
                        conn: _Conn = key.data
                        if mask & selectors.EVENT_WRITE:
                            self._flush(conn)
                        if mask & selectors.EVENT_READ and conn.sock.fileno() >= 0:
                            self._readable(conn)
                self._expire_parked()
        finally:
            for key in list(self._sel.get_map().values()):
                if isinstance(key.data, _Conn):
                    self._close_conn(key.data)
            self._sel.close()
            for s in (self._listen, self._wake_r, self._wake_w):
                try:
                    s.close()
                except OSError:
                    pass

    def wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def stop(self) -> None:
        self._stopping = True
        self.wake()

    # ------------------------------------------------------ connections

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self._listen.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = _Conn(sock, "%s:%s" % addr[:2])
            self._sel.register(sock, selectors.EVENT_READ, conn)

    def _close_conn(self, conn: _Conn) -> None:
        if conn.waiting is not None:
            topic = conn.waiting[0]
            dq = self._parked.get(topic)
            if dq is not None:
                try:
                    dq.remove(conn)
                except ValueError:
                    pass
            conn.waiting = None
        try:
            self._sel.unregister(conn.sock)
        except (KeyError, ValueError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        self.peers.pop(conn.peer, None)

    def _set_interest(self, conn: _Conn, write: bool) -> None:
        mask = selectors.EVENT_READ
        if write:
            mask |= selectors.EVENT_WRITE
        try:
            self._sel.modify(conn.sock, mask, conn)
        except (KeyError, ValueError, OSError):
            pass

    # ------------------------------------------------------------- read

    def _readable(self, conn: _Conn) -> None:
        if conn.rlen == len(conn.rbuf):
            conn.rbuf.extend(bytes(len(conn.rbuf)))  # grow 2x, keep prefix
        try:
            with memoryview(conn.rbuf) as mv:
                got = conn.sock.recv_into(mv[conn.rlen:])
        except BlockingIOError:
            return
        except OSError:
            self._close_conn(conn)
            return
        if got == 0:
            self._close_conn(conn)
            return
        conn.rlen += got
        self._process(conn)

    def _process(self, conn: _Conn) -> None:
        consumed = 0
        # A parked connection stops parsing: its client is mid-long-poll
        # and serialized, so anything else in the buffer waits its turn.
        while conn.waiting is None:
            avail = conn.rlen - consumed
            if avail < 7:
                break
            tlen, plen = struct.unpack_from(">HI", conn.rbuf, consumed + 1)
            if plen > _MAX_FRAME:
                self._close_conn(conn)
                return
            total = 7 + tlen + plen
            if avail < total:
                need = consumed + total
                while len(conn.rbuf) < need:
                    conn.rbuf.extend(bytes(len(conn.rbuf)))
                break
            op = conn.rbuf[consumed]
            topic = bytes(conn.rbuf[consumed + 7:consumed + 7 + tlen]).decode()
            payload = bytes(conn.rbuf[consumed + 7 + tlen:consumed + total])
            consumed += total
            self.peers[conn.peer] = time.monotonic()
            if op == ord("P"):
                self._publish(topic, payload)
                self._reply(conn, b"\x00", b"")
            elif op == ord("C"):
                dq = self._topics.get(topic)
                if dq:
                    self._reply(conn, b"\x01", dq.popleft())
                else:
                    conn.waiting = (topic,
                                    time.monotonic() + self._poll_timeout)
                    self._parked.setdefault(
                        topic, collections.deque()).append(conn)
            elif op == ord("G"):
                if payload.startswith(PING_MAGIC):
                    self._reply(conn, *_ping_reply())
                else:
                    self._reply(conn, b"\x00", b"")
            else:
                self._close_conn(conn)
                return
        if consumed:
            remaining = conn.rlen - consumed
            if remaining:
                conn.rbuf[0:remaining] = conn.rbuf[consumed:conn.rlen]
            conn.rlen = remaining

    # ------------------------------------------------------- topics/poll

    def _publish(self, topic: str, payload: bytes) -> None:
        dq = self._parked.get(topic)
        while dq:
            waiter = dq.popleft()
            if waiter.waiting is None:
                continue
            waiter.waiting = None
            self._reply(waiter, b"\x01", payload)
            self._process(waiter)  # parse frames queued behind the poll
            return
        self._topics.setdefault(topic, collections.deque()).append(payload)

    def _expire_parked(self) -> None:
        now = time.monotonic()
        for topic in list(self._parked):
            dq = self._parked[topic]
            while dq and dq[0].waiting is not None and dq[0].waiting[1] <= now:
                waiter = dq.popleft()
                waiter.waiting = None
                self._reply(waiter, b"\x00", b"")
                self._process(waiter)
            while dq and dq[0].waiting is None:
                dq.popleft()
            if not dq:
                del self._parked[topic]

    # ------------------------------------------------------------ write

    def _reply(self, conn: _Conn, status: bytes, payload: bytes) -> None:
        conn.out += status + struct.pack(">I", len(payload)) + payload
        self._flush(conn)

    def _flush(self, conn: _Conn) -> None:
        try:
            while conn.out:
                sent = conn.sock.send(conn.out)
                if sent == 0:
                    break
                del conn.out[:sent]
        except BlockingIOError:
            pass
        except OSError:
            self._close_conn(conn)
            return
        self._set_interest(conn, write=bool(conn.out))


class TcpBrokerServer:
    """Broker daemon: any number of TCP clients publish/consume.
    ``port=0`` auto-picks. ``reactor=True`` (default) serves every
    connection from one ``selectors`` event loop — long polls park the
    connection instead of pinning a thread, and the topic state needs no
    locks because only the loop touches it. ``reactor=False`` keeps the
    pre-v4 thread-per-connection ``socketserver`` implementation (topics
    in an ``InMemoryBroker``) as a measured baseline."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 poll_timeout: float = 0.25, reactor: bool = True):
        self.reactor = bool(reactor)
        self._thread: Optional[threading.Thread] = None
        if self.reactor:
            self._core: Optional[_Reactor] = _Reactor(host, port, poll_timeout)
            self._srv = None
        else:
            self._core = None
            self._srv = socketserver.ThreadingTCPServer(
                (host, port), _BrokerHandler)
            self._srv.daemon_threads = True
            self._srv._broker = InMemoryBroker()  # type: ignore[attr-defined]
            self._srv._poll_timeout = poll_timeout  # type: ignore[attr-defined]
            self._srv._peers = {}  # type: ignore[attr-defined]

    @property
    def address(self):
        if self._core is not None:
            return self._core.address
        return self._srv.server_address[:2]

    def peers(self) -> Dict[str, float]:
        """Connected clients → monotonic ``last_seen`` of their most
        recent completed frame (a peer that vanished without a clean
        close disappears once the loop — or its handler thread on the
        legacy path — notices the dead socket)."""
        if self._core is not None:
            return dict(self._core.peers)
        return dict(self._srv._peers)  # type: ignore[attr-defined]

    def start(self) -> "TcpBrokerServer":
        target = self._core.run if self._core is not None \
            else self._srv.serve_forever
        self._thread = threading.Thread(target=target,
                                        name="dl4j-tpu-broker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._core is not None:
            self._core.stop()
        else:
            self._srv.shutdown()
            self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class TcpBroker(MessageBroker):
    """Client half: a ``MessageBroker`` over one TCP connection to a
    :class:`TcpBrokerServer`. Consume long-polls: the server replies
    empty after its poll timeout and the client retries until the
    caller's ``timeout`` budget runs out.

    Transport resilience: a dropped connection (broker restart, network
    blip) triggers reconnect-and-resend with jittered exponential
    backoff, bounded by ``max_retries``; when the budget is exhausted
    every operation raises :class:`BrokerUnavailable` — so ``consume``
    returning ``None`` ALWAYS means "topic idle", never "transport
    dead". The jitter RNG is seeded (deterministic fleets don't
    thundering-herd a restarting broker on the same schedule). Retried
    publishes are at-least-once: the op may have been applied just
    before the connection died.

    Socket hygiene: ``TCP_NODELAY`` is set (Nagle would stall the small
    per-burst chunk frames behind unacked data), and replies land in one
    preallocated per-connection recv buffer instead of per-frame
    ``bytes`` concatenation. Transport-fault metrics are recorded after
    ``_lock`` is released (``record_fault`` takes registry locks; the
    hot path must not nest them under the connection lock)."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0,
                 max_retries: int = 4, backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0, seed: int = 0):
        self._host = host
        self._port = port
        self._connect_timeout = connect_timeout
        self.max_retries = max(0, int(max_retries))
        self._backoff_base = float(backoff_base_s)
        self._backoff_max = float(backoff_max_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._rbuf = bytearray(64 << 10)
        self._closed = False
        self._fault_backlog = 0
        #: wire version advertised by the server on the last ``ping()``
        #: (None until one completes; 3 when the peer predates v4).
        self.peer_wire: Optional[int] = None
        self.last_seen: Optional[float] = None
        try:
            with self._lock:
                self._ensure_connected(initial=True)
        finally:
            self._drain_faults()

    # ----------------------------------------------------- connection

    def _connect_once(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        self._sock.settimeout(None)  # long-poll replies block
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _backoff(self, attempt: int) -> float:
        delay = min(self._backoff_max, self._backoff_base * (2 ** attempt))
        return delay * (0.5 + self._rng.random() / 2)  # jitter: [0.5, 1.0)x

    def _note_fault(self) -> None:
        # Deferred: counted under _lock, recorded by _drain_faults()
        # outside it, so registry locks never nest under the conn lock.
        self._fault_backlog += 1

    def _drain_faults(self) -> None:
        n, self._fault_backlog = self._fault_backlog, 0
        for _ in range(n):
            record_fault("transport")

    def _ensure_connected(self, initial: bool = False) -> None:
        if self._closed:
            raise BrokerUnavailable("broker client is closed")
        if self._sock is not None:
            return
        last: Optional[Exception] = None
        for attempt in range(1 + self.max_retries):
            if attempt > 0 or not initial:
                time.sleep(self._backoff(attempt))
            try:
                self._connect_once()
                if last is not None:
                    logger.info("TcpBroker: reconnected to %s:%s after %d "
                                "attempt(s)", self._host, self._port, attempt)
                return
            except OSError as e:
                last = e
                self._note_fault()
                logger.warning(
                    "TcpBroker: connect to %s:%s failed (%s: %s), attempt "
                    "%d/%d", self._host, self._port, type(e).__name__, e,
                    attempt + 1, 1 + self.max_retries)
        raise BrokerUnavailable(
            f"broker {self._host}:{self._port} unreachable after "
            f"{1 + self.max_retries} attempts") from last

    # ------------------------------------------------------ transport

    def _recv_into(self, n: int) -> memoryview:
        """Read exactly ``n`` bytes into the connection's preallocated
        recv buffer (grown geometrically when a reply outsizes it) and
        return a view of the filled prefix. The view is only valid
        until the next ``_recv_into`` call."""
        if len(self._rbuf) < n:
            self._rbuf = bytearray(max(n, 2 * len(self._rbuf)))
        got = 0
        with memoryview(self._rbuf) as mv:
            while got < n:
                r = self._sock.recv_into(mv[got:n])
                if not r:
                    raise ConnectionError("peer closed mid-frame")
                got += r
        return memoryview(self._rbuf)[:n]

    def _roundtrip(self, op: bytes, topic: str, payload: bytes):
        try:
            with self._lock:
                return self._roundtrip_locked(op, topic, payload)
        finally:
            self._drain_faults()

    def _roundtrip_locked(self, op: bytes, topic: str, payload: bytes):
        last: Optional[Exception] = None
        for attempt in range(1 + self.max_retries):
            try:
                self._ensure_connected()
                _send_frame(self._sock, op, topic, payload)
                with self._recv_into(5) as head:
                    ok = head[0] == 1
                    (rlen,) = struct.unpack_from(">I", head, 1)
                with self._recv_into(rlen) as body:
                    reply = bytes(body)
                self.last_seen = time.monotonic()
                return ok, reply
            except BrokerUnavailable:
                raise
            except (OSError, ConnectionError, struct.error) as e:
                last = e
                self._note_fault()
                logger.warning(
                    "TcpBroker: %s on %s failed mid-roundtrip (%s: %s) — "
                    "reconnecting", op, topic, type(e).__name__, e)
                self._drop()
        raise BrokerUnavailable(
            f"broker {self._host}:{self._port} lost mid-operation and "
            f"unreachable after {1 + self.max_retries} attempts") from last

    def publish(self, topic: str, payload: bytes) -> None:
        self._roundtrip(b"P", topic, payload)

    def ping(self) -> float:
        """One 'G' liveness round-trip; returns the RTT in seconds and
        refreshes ``last_seen``. The ping rides the wire-v4 header
        (PING_MAGIC + version) and records the server's echoed version
        in ``peer_wire`` (3 when the peer predates v4). Raises
        :class:`BrokerUnavailable` when the reconnect budget is
        exhausted — a clean positive death signal, so health planes
        never have to infer a dead transport from consume timeouts."""
        t0 = time.monotonic()
        ok, reply = self._roundtrip(
            b"G", "", PING_MAGIC + bytes([PING_VERSION]))
        if ok and reply[:2] == PING_MAGIC and len(reply) >= 3:
            self.peer_wire = reply[2]
        else:
            self.peer_wire = 3
        return time.monotonic() - t0

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            found, reply = self._roundtrip(b"C", topic, b"")
            if found:
                return reply
            if deadline is not None and time.monotonic() >= deadline:
                return None  # genuine poll timeout — broker is healthy

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._drop()
