"""Message broker SPI + in-memory and TCP implementations.

Parity: the Kafka producer/consumer pair in
``dl4j-streaming/.../kafka/NDArrayKafkaClient.java`` (+
``NDArrayPublisher``/``NDArrayConsumer``). The SPI keeps the pipeline
layer transport-agnostic; ``InMemoryBroker`` is the test/dev transport,
``TcpBroker(Server)`` is a dependency-free network transport with
length-prefixed frames and per-topic FIFO queues (at-most-once, one
consumer group — the subset of Kafka semantics the reference pipelines
actually use).
"""

from __future__ import annotations

import queue
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional

_MAX_FRAME = 1 << 30


class MessageBroker:
    """Transport SPI: byte payloads on named topics."""

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        """Pop the next payload, blocking up to ``timeout`` seconds.
        Returns None on timeout."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryBroker(MessageBroker):
    """Per-topic FIFO queues in-process."""

    def __init__(self):
        self._topics: Dict[str, "queue.Queue[bytes]"] = {}
        self._lock = threading.Lock()

    def _q(self, topic: str) -> "queue.Queue[bytes]":
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = queue.Queue()
            return self._topics[topic]

    def publish(self, topic: str, payload: bytes) -> None:
        self._q(topic).put(bytes(payload))

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            return self._q(topic).get(timeout=timeout)
        except queue.Empty:
            return None


# --- TCP transport ----------------------------------------------------------
# Frame: 1-byte op ('P' publish / 'C' consume) + u16 topic len + topic utf-8
#        + u32 payload len + payload.
# Reply: 1-byte status (1 = payload follows / 0 = none-or-ack) + u32 len +
#        payload. The status byte keeps zero-length payloads distinguishable
#        from a consume poll timeout.

def _send_frame(sock: socket.socket, op: bytes, topic: str, payload: bytes) -> None:
    t = topic.encode()
    sock.sendall(op + struct.pack(">HI", len(t), len(payload)) + t + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _BrokerHandler(socketserver.BaseRequestHandler):
    def handle(self):
        broker: InMemoryBroker = self.server._broker  # type: ignore[attr-defined]
        timeout = self.server._poll_timeout  # type: ignore[attr-defined]
        while True:
            try:
                op = _recv_exact(self.request, 1)
            except ConnectionError:
                return
            tlen, plen = struct.unpack(">HI", _recv_exact(self.request, 6))
            if plen > _MAX_FRAME:
                return
            topic = _recv_exact(self.request, tlen).decode()
            payload = _recv_exact(self.request, plen)
            if op == b"P":
                broker.publish(topic, payload)
                status, reply = b"\x00", b""
            elif op == b"C":
                msg = broker.consume(topic, timeout=timeout)
                status = b"\x00" if msg is None else b"\x01"
                reply = msg or b""
            else:
                return
            self.request.sendall(status + struct.pack(">I", len(reply)) + reply)


class TcpBrokerServer:
    """Broker daemon: topics live server-side in an ``InMemoryBroker``;
    any number of TCP clients publish/consume. ``port=0`` auto-picks."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 poll_timeout: float = 0.25):
        self._srv = socketserver.ThreadingTCPServer((host, port), _BrokerHandler)
        self._srv.daemon_threads = True
        self._srv._broker = InMemoryBroker()  # type: ignore[attr-defined]
        self._srv._poll_timeout = poll_timeout  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._srv.server_address[:2]

    def start(self) -> "TcpBrokerServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="dl4j-tpu-broker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class TcpBroker(MessageBroker):
    """Client half: a ``MessageBroker`` over one TCP connection to a
    :class:`TcpBrokerServer`. Consume long-polls: the server replies
    empty after its poll timeout and the client retries until the
    caller's ``timeout`` budget runs out."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0):
        self._sock = socket.create_connection((host, port), timeout=connect_timeout)
        self._sock.settimeout(None)  # long-poll replies block
        self._lock = threading.Lock()

    def _roundtrip(self, op: bytes, topic: str, payload: bytes):
        with self._lock:
            _send_frame(self._sock, op, topic, payload)
            status = _recv_exact(self._sock, 1)
            (rlen,) = struct.unpack(">I", _recv_exact(self._sock, 4))
            return status == b"\x01", _recv_exact(self._sock, rlen)

    def publish(self, topic: str, payload: bytes) -> None:
        self._roundtrip(b"P", topic, payload)

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        import time
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            found, reply = self._roundtrip(b"C", topic, b"")
            if found:
                return reply
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
