"""Message broker SPI + in-memory and TCP implementations.

Parity: the Kafka producer/consumer pair in
``dl4j-streaming/.../kafka/NDArrayKafkaClient.java`` (+
``NDArrayPublisher``/``NDArrayConsumer``). The SPI keeps the pipeline
layer transport-agnostic; ``InMemoryBroker`` is the test/dev transport,
``TcpBroker(Server)`` is a dependency-free network transport with
length-prefixed frames and per-topic FIFO queues (at-most-once, one
consumer group — the subset of Kafka semantics the reference pipelines
actually use).
"""

from __future__ import annotations

import logging
import queue
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, Optional

from deeplearning4j_tpu.monitor import record_fault

logger = logging.getLogger("deeplearning4j_tpu")

_MAX_FRAME = 1 << 30


class BrokerUnavailable(ConnectionError):
    """The broker could not be reached within the bounded reconnect
    budget. Distinct from ``consume`` returning ``None`` — that is a
    genuine long-poll timeout (broker healthy, topic empty); this means
    the transport itself is down and the caller should fail over or
    surface the outage instead of treating it as an idle stream."""


class MessageBroker:
    """Transport SPI: byte payloads on named topics.

    Liveness: ``ping()`` performs one cheap round-trip against the
    transport (raises on a dead one) and every successful operation
    refreshes ``last_seen`` (``time.monotonic()``), so a health plane
    can read connection liveness directly instead of inferring death
    from consume timeouts."""

    #: monotonic timestamp of the last successful broker round-trip
    #: (None until the first one).
    last_seen: Optional[float] = None

    def publish(self, topic: str, payload: bytes) -> None:
        raise NotImplementedError

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        """Pop the next payload, blocking up to ``timeout`` seconds.
        Returns None on timeout."""
        raise NotImplementedError

    def ping(self) -> float:
        """One liveness round-trip; returns the RTT in seconds and
        refreshes ``last_seen``. Raises (e.g.
        :class:`BrokerUnavailable`) when the transport is dead."""
        t0 = time.monotonic()
        self.last_seen = time.monotonic()
        return time.monotonic() - t0

    def close(self) -> None:
        pass


class InMemoryBroker(MessageBroker):
    """Per-topic FIFO queues in-process."""

    def __init__(self):
        self._topics: Dict[str, "queue.Queue[bytes]"] = {}
        self._lock = threading.Lock()
        self.last_seen: Optional[float] = None

    def ping(self) -> float:
        t0 = time.monotonic()
        with self._lock:
            pass  # in-process: the lock round-trip IS the transport
        self.last_seen = time.monotonic()
        return self.last_seen - t0

    def _q(self, topic: str) -> "queue.Queue[bytes]":
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = queue.Queue()
            return self._topics[topic]

    def publish(self, topic: str, payload: bytes) -> None:
        self._q(topic).put(bytes(payload))
        self.last_seen = time.monotonic()

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        try:
            msg = self._q(topic).get(timeout=timeout)
        except queue.Empty:
            msg = None
        self.last_seen = time.monotonic()
        return msg


# --- TCP transport ----------------------------------------------------------
# Frame: 1-byte op ('P' publish / 'C' consume / 'G' ping) + u16 topic len +
#        topic utf-8 + u32 payload len + payload.
# Reply: 1-byte status (1 = payload follows / 0 = none-or-ack) + u32 len +
#        payload. The status byte keeps zero-length payloads distinguishable
#        from a consume poll timeout. 'G' frames carry an empty topic and
#        payload and are acked with status 0 — a pure liveness round-trip
#        that also refreshes the server's per-peer last_seen table.

def _send_frame(sock: socket.socket, op: bytes, topic: str, payload: bytes) -> None:
    t = topic.encode()
    sock.sendall(op + struct.pack(">HI", len(t), len(payload)) + t + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


class _BrokerHandler(socketserver.BaseRequestHandler):
    def handle(self):
        broker: InMemoryBroker = self.server._broker  # type: ignore[attr-defined]
        timeout = self.server._poll_timeout  # type: ignore[attr-defined]
        peers = self.server._peers  # type: ignore[attr-defined]
        peer = "%s:%s" % self.client_address[:2]
        try:
            while True:
                try:
                    op = _recv_exact(self.request, 1)
                except ConnectionError:
                    return
                tlen, plen = struct.unpack(">HI", _recv_exact(self.request, 6))
                if plen > _MAX_FRAME:
                    return
                topic = _recv_exact(self.request, tlen).decode()
                payload = _recv_exact(self.request, plen)
                if op == b"P":
                    broker.publish(topic, payload)
                    status, reply = b"\x00", b""
                elif op == b"C":
                    msg = broker.consume(topic, timeout=timeout)
                    status = b"\x00" if msg is None else b"\x01"
                    reply = msg or b""
                elif op == b"G":
                    status, reply = b"\x00", b""
                else:
                    return
                peers[peer] = time.monotonic()
                self.request.sendall(
                    status + struct.pack(">I", len(reply)) + reply)
        finally:
            peers.pop(peer, None)


class TcpBrokerServer:
    """Broker daemon: topics live server-side in an ``InMemoryBroker``;
    any number of TCP clients publish/consume. ``port=0`` auto-picks."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 poll_timeout: float = 0.25):
        self._srv = socketserver.ThreadingTCPServer((host, port), _BrokerHandler)
        self._srv.daemon_threads = True
        self._srv._broker = InMemoryBroker()  # type: ignore[attr-defined]
        self._srv._poll_timeout = poll_timeout  # type: ignore[attr-defined]
        self._srv._peers = {}  # type: ignore[attr-defined]
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self):
        return self._srv.server_address[:2]

    def peers(self) -> Dict[str, float]:
        """Connected clients → monotonic ``last_seen`` of their most
        recent completed frame (a peer that vanished without a clean
        close disappears once its handler thread notices the dead
        socket)."""
        return dict(self._srv._peers)  # type: ignore[attr-defined]

    def start(self) -> "TcpBrokerServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="dl4j-tpu-broker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=5)


class TcpBroker(MessageBroker):
    """Client half: a ``MessageBroker`` over one TCP connection to a
    :class:`TcpBrokerServer`. Consume long-polls: the server replies
    empty after its poll timeout and the client retries until the
    caller's ``timeout`` budget runs out.

    Transport resilience: a dropped connection (broker restart, network
    blip) triggers reconnect-and-resend with jittered exponential
    backoff, bounded by ``max_retries``; when the budget is exhausted
    every operation raises :class:`BrokerUnavailable` — so ``consume``
    returning ``None`` ALWAYS means "topic idle", never "transport
    dead". The jitter RNG is seeded (deterministic fleets don't
    thundering-herd a restarting broker on the same schedule). Retried
    publishes are at-least-once: the op may have been applied just
    before the connection died."""

    def __init__(self, host: str, port: int, connect_timeout: float = 5.0,
                 max_retries: int = 4, backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0, seed: int = 0):
        self._host = host
        self._port = port
        self._connect_timeout = connect_timeout
        self.max_retries = max(0, int(max_retries))
        self._backoff_base = float(backoff_base_s)
        self._backoff_max = float(backoff_max_s)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._closed = False
        self.last_seen: Optional[float] = None
        with self._lock:
            self._ensure_connected(initial=True)

    # ----------------------------------------------------- connection

    def _connect_once(self) -> None:
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._connect_timeout)
        self._sock.settimeout(None)  # long-poll replies block

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _backoff(self, attempt: int) -> float:
        delay = min(self._backoff_max, self._backoff_base * (2 ** attempt))
        return delay * (0.5 + self._rng.random() / 2)  # jitter: [0.5, 1.0)x

    def _ensure_connected(self, initial: bool = False) -> None:
        if self._closed:
            raise BrokerUnavailable("broker client is closed")
        if self._sock is not None:
            return
        last: Optional[Exception] = None
        for attempt in range(1 + self.max_retries):
            if attempt > 0 or not initial:
                time.sleep(self._backoff(attempt))
            try:
                self._connect_once()
                if last is not None:
                    logger.info("TcpBroker: reconnected to %s:%s after %d "
                                "attempt(s)", self._host, self._port, attempt)
                return
            except OSError as e:
                last = e
                record_fault("transport")
                logger.warning(
                    "TcpBroker: connect to %s:%s failed (%s: %s), attempt "
                    "%d/%d", self._host, self._port, type(e).__name__, e,
                    attempt + 1, 1 + self.max_retries)
        raise BrokerUnavailable(
            f"broker {self._host}:{self._port} unreachable after "
            f"{1 + self.max_retries} attempts") from last

    # ------------------------------------------------------ transport

    def _roundtrip(self, op: bytes, topic: str, payload: bytes):
        with self._lock:
            last: Optional[Exception] = None
            for attempt in range(1 + self.max_retries):
                try:
                    self._ensure_connected()
                    _send_frame(self._sock, op, topic, payload)
                    status = _recv_exact(self._sock, 1)
                    (rlen,) = struct.unpack(">I", _recv_exact(self._sock, 4))
                    reply = _recv_exact(self._sock, rlen)
                    self.last_seen = time.monotonic()
                    return status == b"\x01", reply
                except BrokerUnavailable:
                    raise
                except (OSError, ConnectionError, struct.error) as e:
                    last = e
                    record_fault("transport")
                    logger.warning(
                        "TcpBroker: %s on %s failed mid-roundtrip (%s: %s) — "
                        "reconnecting", op, topic, type(e).__name__, e)
                    self._drop()
            raise BrokerUnavailable(
                f"broker {self._host}:{self._port} lost mid-operation and "
                f"unreachable after {1 + self.max_retries} attempts") from last

    def publish(self, topic: str, payload: bytes) -> None:
        self._roundtrip(b"P", topic, payload)

    def ping(self) -> float:
        """One 'G' liveness round-trip; returns the RTT in seconds and
        refreshes ``last_seen``. Raises :class:`BrokerUnavailable` when
        the reconnect budget is exhausted — a clean positive death
        signal, so health planes never have to infer a dead transport
        from consume timeouts."""
        t0 = time.monotonic()
        self._roundtrip(b"G", "", b"")
        return time.monotonic() - t0

    def consume(self, topic: str, timeout: Optional[float] = None) -> Optional[bytes]:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            found, reply = self._roundtrip(b"C", topic, b"")
            if found:
                return reply
            if deadline is not None and time.monotonic() >= deadline:
                return None  # genuine poll timeout — broker is healthy

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._drop()
