from deeplearning4j_tpu.text.tokenization import (  # noqa: F401
    DefaultTokenizer,
    DefaultTokenizerFactory,
    CommonPreprocessor,
    LowCasePreprocessor,
    StemmingPreprocessor,
)
from deeplearning4j_tpu.text.sentenceiterator import (  # noqa: F401
    SentenceIterator,
    CollectionSentenceIterator,
    LineSentenceIterator,
    FileSentenceIterator,
    BasicLineIterator,
)
from deeplearning4j_tpu.text.annotation import (  # noqa: F401
    AnnotatedTokenizerFactory,
    AnnotationPipeline,
    Annotator,
    default_pipeline,
)
