"""Constituency trees, shallow tree parsing, and sentiment lexicon.

Parity (VERDICT r2 missing #5 — treebank/UIMA depth): the role of
``deeplearning4j-nlp-uima/.../text/corpora/treeparser/TreeParser.java``
(+ ``Tree.java``, ``TreeFactory``) — turn sentences into labeled
constituency trees for tree-structured models — and the SentiWordNet
lexicon those pipelines attach sentiment scores from
(``.../corpora/sentiwordnet/SWN3.java`` role).

Re-design notes: the reference drives a full UIMA + OpenNLP treebank
parser; vendoring a statistical parser is out of scope for a TPU
framework, so ``ShallowTreeParser`` builds the standard rule-based
shallow constituency structure (NP/VP/PP chunks under S) from the
repo's own POS annotator (``text/annotation.py``) — same Tree API,
pluggable for a heavier parser. The sentiment lexicon keeps
SentiWordNet's (positive, negative) per-word scoring with a seed
lexicon, TSV loading for the real SWN file format, and the classic
negation-flip aggregation.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.text.annotation import (
    AnnotationPipeline,
    default_pipeline,
)


class Tree:
    """Labeled constituency tree (``treeparser/Tree.java`` role): a
    node has a label and children; leaves carry tokens."""

    def __init__(self, label: str, children: Optional[List["Tree"]] = None,
                 token: Optional[str] = None):
        self.label = label
        self.children = children or []
        self.token = token

    def is_leaf(self) -> bool:
        return self.token is not None

    def is_preterminal(self) -> bool:
        return len(self.children) == 1 and self.children[0].is_leaf()

    def yield_tokens(self) -> List[str]:
        """Leaf tokens left-to-right (``Tree.yield`` role)."""
        if self.is_leaf():
            return [self.token]
        out: List[str] = []
        for c in self.children:
            out.extend(c.yield_tokens())
        return out

    def subtrees(self) -> Iterator["Tree"]:
        yield self
        for c in self.children:
            yield from c.subtrees()

    def depth(self) -> int:
        if self.is_leaf():
            return 0
        return 1 + max((c.depth() for c in self.children), default=0)

    def to_sexpr(self) -> str:
        """Penn-treebank-style s-expression."""
        if self.is_leaf():
            return self.token
        inner = " ".join(c.to_sexpr() for c in self.children)
        return f"({self.label} {inner})"

    def __repr__(self) -> str:
        return self.to_sexpr()


# POS tag → chunk phrase mapping for the shallow grammar (coarse tags
# from text/annotation.py PosAnnotator)
_NP_TAGS = {"DET", "ADJ", "NOUN", "PRON", "NUM"}
_VP_TAGS = {"VERB", "ADV", "PART"}
_PP_HEAD = {"ADP"}


class ShallowTreeParser:
    """``TreeParser.java`` role: sentence → labeled tree. Chunks
    contiguous POS runs into NP/VP/PP phrases under an S root; each
    token becomes a (POS (token)) preterminal."""

    def __init__(self, pipeline: Optional[AnnotationPipeline] = None):
        self.pipeline = pipeline or default_pipeline()

    def parse(self, text: str) -> List[Tree]:
        """One tree per sentence (``getTrees`` role)."""
        doc = self.pipeline.annotate(text)
        trees = []
        for i in range(len(doc.sentences)):
            toks = [t for t in doc.tokens if t.sentence == i
                    and (t.pos or "X") != "PUNCT"]
            if toks:
                trees.append(self._parse_tokens(
                    [(t.text, t.pos or "X") for t in toks]))
        return trees

    def _parse_tokens(self, tagged: Sequence[Tuple[str, str]]) -> Tree:
        chunks: List[Tree] = []
        run: List[Tree] = []
        run_label: Optional[str] = None

        def flush():
            nonlocal run, run_label
            if run:  # run_label is always set when run is non-empty
                chunks.append(Tree(run_label, run))
                run, run_label = [], None

        def chunk_of(pos: str) -> Optional[str]:
            if pos in _NP_TAGS:
                return "NP"
            if pos in _VP_TAGS:
                return "VP"
            if pos in _PP_HEAD:
                return "PP"
            return None

        for tok, pos in tagged:
            pre = Tree(pos, [Tree(pos, token=tok)])
            label = chunk_of(pos)
            if label == "PP":
                # PP opens a new chunk and absorbs the following NP run
                flush()
                run, run_label = [pre], "PP"
            elif run and label is not None and (
                    run_label == label
                    or (run_label == "PP" and label == "NP")):
                run.append(pre)
            else:
                flush()
                if label is None:
                    chunks.append(pre)
                else:
                    run, run_label = [pre], label
        flush()
        return Tree("S", chunks)


# --------------------------------------------------------------- sentiment

# Seed lexicon: (positive, negative) in [0, 1], the SentiWordNet score
# convention; a real deployment loads the full SWN distribution via
# ``load_tsv`` — the scoring machinery is identical.
_SEED_SENTIMENT: Dict[str, Tuple[float, float]] = {
    "good": (0.75, 0.0), "great": (0.88, 0.0), "excellent": (0.9, 0.0),
    "happy": (0.8, 0.0), "love": (0.85, 0.0), "like": (0.5, 0.0),
    "best": (0.8, 0.0), "wonderful": (0.85, 0.0), "nice": (0.6, 0.0),
    "amazing": (0.85, 0.0), "fantastic": (0.85, 0.0), "enjoy": (0.7, 0.0),
    "bad": (0.0, 0.75), "terrible": (0.0, 0.88), "awful": (0.0, 0.88),
    "sad": (0.0, 0.75), "hate": (0.0, 0.85), "worst": (0.0, 0.85),
    "horrible": (0.0, 0.85), "poor": (0.1, 0.6), "wrong": (0.0, 0.6),
    "boring": (0.0, 0.65), "disappointing": (0.0, 0.7), "fail": (0.0, 0.7),
}

_NEGATORS = {"not", "no", "never", "n't", "without", "hardly"}


class SentiWordNetLexicon:
    """SentiWordNet-style lexicon (``SWN3.java`` role): per-word
    (positive, negative) scores, net polarity, and negation-aware
    sentence aggregation."""

    def __init__(self, entries: Optional[Dict[str, Tuple[float, float]]] = None):
        self.entries = dict(entries if entries is not None else _SEED_SENTIMENT)

    def load_tsv(self, path: str) -> "SentiWordNetLexicon":
        """``word<TAB>pos_score<TAB>neg_score`` per line (the flattened
        SWN distribution format)."""
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split("\t")
                if len(parts) >= 3:
                    self.entries[parts[0].lower()] = (float(parts[1]),
                                                      float(parts[2]))
        return self

    def scores(self, word: str) -> Tuple[float, float]:
        return self.entries.get(word.lower(), (0.0, 0.0))

    def polarity(self, word: str) -> float:
        p, n = self.scores(word)
        return p - n

    def score_tokens(self, tokens: Sequence[str]) -> float:
        """Mean net polarity over scored tokens, with the classic
        negation flip (a negator inverts the next scored word)."""
        total, count = 0.0, 0
        negate = False
        for tok in tokens:
            low = tok.lower()
            if low in _NEGATORS:
                negate = True
                continue
            pol = self.polarity(low)
            if pol != 0.0:
                total += -pol if negate else pol
                count += 1
                negate = False
        return total / count if count else 0.0

    def score_tree(self, tree: Tree) -> float:
        return self.score_tokens(tree.yield_tokens())
