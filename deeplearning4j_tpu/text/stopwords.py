"""Stop-word list + removing preprocessor.

Parity: ``deeplearning4j-nlp/.../text/stopwords/StopWords.java`` (the
reference ships a bundled english stopword resource consumed by the
vectorizers and tokenizer pipelines).
"""

from __future__ import annotations

from typing import Iterable, List, Set

from deeplearning4j_tpu.text.tokenization import TokenPreProcess

# the classic english list the reference bundles (stopwords resource)
ENGLISH_STOP_WORDS: Set[str] = {
    "a", "about", "above", "after", "again", "against", "all", "am", "an",
    "and", "any", "are", "aren't", "as", "at", "be", "because", "been",
    "before", "being", "below", "between", "both", "but", "by", "can't",
    "cannot", "could", "couldn't", "did", "didn't", "do", "does", "doesn't",
    "doing", "don't", "down", "during", "each", "few", "for", "from",
    "further", "had", "hadn't", "has", "hasn't", "have", "haven't", "having",
    "he", "he'd", "he'll", "he's", "her", "here", "here's", "hers", "herself",
    "him", "himself", "his", "how", "how's", "i", "i'd", "i'll", "i'm",
    "i've", "if", "in", "into", "is", "isn't", "it", "it's", "its", "itself",
    "let's", "me", "more", "most", "mustn't", "my", "myself", "no", "nor",
    "not", "of", "off", "on", "once", "only", "or", "other", "ought", "our",
    "ours", "ourselves", "out", "over", "own", "same", "shan't", "she",
    "she'd", "she'll", "she's", "should", "shouldn't", "so", "some", "such",
    "than", "that", "that's", "the", "their", "theirs", "them", "themselves",
    "then", "there", "there's", "these", "they", "they'd", "they'll",
    "they're", "they've", "this", "those", "through", "to", "too", "under",
    "until", "up", "very", "was", "wasn't", "we", "we'd", "we'll", "we're",
    "we've", "were", "weren't", "what", "what's", "when", "when's", "where",
    "where's", "which", "while", "who", "who's", "whom", "why", "why's",
    "with", "won't", "would", "wouldn't", "you", "you'd", "you'll", "you're",
    "you've", "your", "yours", "yourself", "yourselves",
}


def get_stop_words() -> List[str]:
    """``StopWords.getStopWords()``."""
    return sorted(ENGLISH_STOP_WORDS)


def remove_stop_words(tokens: Iterable[str],
                      stop_words: Iterable[str] = frozenset()) -> List[str]:
    sw = set(stop_words) or ENGLISH_STOP_WORDS
    return [t for t in tokens if t.lower() not in sw]


class StopWordsPreprocessor(TokenPreProcess):
    """Token preprocessor mapping stop words to '' (callers drop empty
    tokens) — composes with the tokenizer-factory SPI."""

    def __init__(self, stop_words: Iterable[str] = frozenset()):
        self.stop_words = set(stop_words) or ENGLISH_STOP_WORDS

    def pre_process(self, token: str) -> str:
        return "" if token.lower() in self.stop_words else token
