"""Annotation pipeline: sentence split → tokenize → POS → lemma.

Parity: ``deeplearning4j-nlp-uima`` (SURVEY.md §2.5) — the reference
runs a UIMA ``AnalysisEngine`` pipeline (``text/annotator/
{SentenceAnnotator,TokenizerAnnotator,PoStagger,StemmerAnnotator}``)
whose net effect on the framework is: sentence boundaries, tokens with
part-of-speech tags, and lemmatized token streams feeding
``UimaTokenizerFactory``. This module provides that seam without the
UIMA runtime: ``Annotator`` is the SPI (an ``AnalysisEngine`` role),
``AnnotationPipeline`` the aggregate engine, and the bundled annotators
are dependency-free rule/lexicon implementations. Heavier taggers
(a real treebank parser, SentiWordNet) plug in as ``Annotator``
subclasses — the pipeline contract, not the linguistics, is the parity
surface.

``AnnotatedTokenizerFactory`` adapts a pipeline into the tokenizer SPI
(``UimaTokenizerFactory`` role) so Word2Vec/BOW/paragraph-vectors can
consume lemmatized, POS-filtered token streams.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Iterable, List, Optional, Sequence

from deeplearning4j_tpu.text.tokenization import (
    Tokenizer, TokenizerFactory, register_tokenizer_factory)


@dataclasses.dataclass
class TokenAnnotation:
    """One token's annotations (UIMA ``Token`` feature-structure role)."""

    text: str
    start: int                 # char offset into the document
    end: int
    sentence: int              # sentence index
    pos: Optional[str] = None  # coarse tag: NOUN/VERB/ADJ/ADV/PRON/DET/ADP/NUM/PUNCT/X
    lemma: Optional[str] = None


@dataclasses.dataclass
class AnnotatedDocument:
    """The CAS role: raw text + accumulated annotations."""

    text: str
    sentences: List[str] = dataclasses.field(default_factory=list)
    # (start, end) char spans per sentence
    sentence_spans: List[tuple] = dataclasses.field(default_factory=list)
    tokens: List[TokenAnnotation] = dataclasses.field(default_factory=list)


class Annotator:
    """AnalysisEngine SPI: mutate/extend the document's annotations."""

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        raise NotImplementedError


class AnnotationPipeline(Annotator):
    """Aggregate engine (``AnalysisEngineFactory.createEngine`` chain)."""

    def __init__(self, annotators: Sequence[Annotator]):
        self.annotators = list(annotators)

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        for a in self.annotators:
            doc = a.process(doc)
        return doc

    def annotate(self, text: str) -> AnnotatedDocument:
        return self.process(AnnotatedDocument(text=text))


_SENT_END = re.compile(r"(?<=[.!?])[\"')\]]*\s+(?=[A-Z0-9\"'(\[])")
_ABBREV = {"mr", "mrs", "ms", "dr", "prof", "st", "vs", "etc", "e.g", "i.e",
           "jr", "sr", "inc", "ltd", "co", "fig", "al"}


class SentenceAnnotator(Annotator):
    """Rule-based sentence splitter (``SentenceAnnotator`` role):
    terminal punctuation followed by whitespace and an upper-case/digit
    opener, with an abbreviation guard."""

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        text = doc.text
        starts = [0]
        for m in _SENT_END.finditer(text):
            prev = text[:m.start()].rstrip(".!?\"')]")
            last_word = prev.rsplit(None, 1)[-1].lower() if prev.split() else ""
            if last_word in _ABBREV:
                continue
            starts.append(m.end())
        spans = []
        for i, s in enumerate(starts):
            e = starts[i + 1] if i + 1 < len(starts) else len(text)
            if text[s:e].strip():
                spans.append((s, e))
        doc.sentence_spans = spans
        doc.sentences = [text[s:e].strip() for s, e in spans]
        return doc


_TOKEN = re.compile(r"[A-Za-z]+(?:'[A-Za-z]+)?|\d+(?:[.,]\d+)*|[^\w\s]")


class TokenizerAnnotator(Annotator):
    """Offset-preserving tokenizer (``TokenizerAnnotator`` role)."""

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        if not doc.sentence_spans:
            doc = SentenceAnnotator().process(doc)
        doc.tokens = []
        for si, (s, e) in enumerate(doc.sentence_spans):
            for m in _TOKEN.finditer(doc.text[s:e]):
                doc.tokens.append(TokenAnnotation(
                    text=m.group(), start=s + m.start(), end=s + m.end(),
                    sentence=si))
        return doc


# compact closed-class lexicon + suffix rules; coarse universal-ish tags
_POS_LEXICON = {
    "DET": {"the", "a", "an", "this", "that", "these", "those", "each",
            "every", "some", "any", "no", "all", "both"},
    "PRON": {"i", "you", "he", "she", "it", "we", "they", "me", "him", "her",
             "us", "them", "my", "your", "his", "its", "our", "their", "who",
             "whom", "which", "what", "mine", "yours", "hers", "ours",
             "theirs", "myself", "itself", "themselves"},
    "ADP": {"in", "on", "at", "by", "for", "with", "about", "against",
            "between", "into", "through", "during", "before", "after",
            "above", "below", "to", "from", "up", "down", "of", "off",
            "over", "under"},
    "CONJ": {"and", "or", "but", "nor", "so", "yet", "because", "although",
             "while", "if", "unless", "since", "when", "whereas"},
    "VERB": {"is", "am", "are", "was", "were", "be", "been", "being", "have",
             "has", "had", "do", "does", "did", "will", "would", "can",
             "could", "shall", "should", "may", "might", "must", "go", "goes",
             "went", "gone", "say", "says", "said", "get", "gets", "got",
             "make", "makes", "made", "see", "sees", "saw", "seen", "know",
             "knows", "knew", "known", "take", "takes", "took", "taken"},
    "ADV": {"not", "very", "too", "also", "just", "only", "then", "there",
            "here", "now", "never", "always", "often", "again", "still",
            "well", "more", "most", "less", "least"},
}
_POS_BY_WORD = {w: tag for tag, words in _POS_LEXICON.items() for w in words}


class PosAnnotator(Annotator):
    """Coarse POS tagging (``PoStagger`` role): closed-class lexicon
    first, then suffix heuristics, default NOUN."""

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        for t in doc.tokens:
            w = t.text
            lw = w.lower()
            if not w[0].isalnum():
                t.pos = "PUNCT"
            elif w[0].isdigit():
                t.pos = "NUM"
            elif lw in _POS_BY_WORD:
                t.pos = _POS_BY_WORD[lw]
            elif lw.endswith("ly"):
                t.pos = "ADV"
            elif lw.endswith(("ing", "ed", "ize", "ise", "ify", "ate")) and len(lw) > 4:
                t.pos = "VERB"
            elif lw.endswith(("ous", "ful", "ive", "able", "ible", "al",
                              "ic", "less", "ish", "est", "er")) and len(lw) > 4:
                t.pos = "ADJ"
            else:
                t.pos = "NOUN"
        return doc


class CallableTagAnnotator(Annotator):
    """Adapter: plug ANY external tagger — a trained model, a service —
    into the pipeline as a plain callable ``tokens -> tags`` (or
    ``tokens -> lemmas`` with ``attr="lemma"``). This is the seam the
    reference filled with downloaded OpenNLP models behind UIMA
    AnalysisEngines; a list shorter than the tokens leaves the tail
    untouched."""

    def __init__(self, fn, attr: str = "pos"):
        if attr not in ("pos", "lemma"):
            raise ValueError(f"attr must be 'pos' or 'lemma', got {attr!r}")
        self._fn = fn
        self._attr = attr

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        tags = self._fn([t.text for t in doc.tokens])
        for t, tag in zip(doc.tokens, tags):
            setattr(t, self._attr, tag)
        return doc


_IRREGULAR_LEMMAS = {
    "was": "be", "were": "be", "is": "be", "are": "be", "am": "be",
    "been": "be", "being": "be", "has": "have", "had": "have",
    "does": "do", "did": "do", "done": "do", "went": "go", "gone": "go",
    "said": "say", "got": "get", "made": "make", "saw": "see", "seen": "see",
    "knew": "know", "known": "know", "took": "take", "taken": "take",
    "ran": "run", "sat": "sit", "came": "come", "gave": "give",
    "found": "find", "told": "tell", "left": "leave", "felt": "feel",
    "kept": "keep", "began": "begin", "brought": "bring", "bought": "buy",
    "thought": "think", "wrote": "write", "written": "write",
    "stood": "stand", "heard": "hear", "held": "hold", "met": "meet",
    "paid": "pay", "sent": "send", "sold": "sell", "spoke": "speak",
    "spoken": "speak", "spent": "spend", "taught": "teach", "wore": "wear",
    "worn": "wear", "won": "win", "lost": "lose", "built": "build",
    "caught": "catch", "chose": "choose", "chosen": "choose",
    "drew": "draw", "drawn": "draw", "drove": "drive", "driven": "drive",
    "ate": "eat", "eaten": "eat", "fell": "fall", "fallen": "fall",
    "flew": "fly", "flown": "fly", "grew": "grow", "grown": "grow",
    "lay": "lie", "led": "lead", "meant": "mean", "rose": "rise",
    "risen": "rise", "threw": "throw", "thrown": "throw",
    "understood": "understand",
    "children": "child", "men": "man", "women": "woman", "feet": "foot",
    "teeth": "tooth", "mice": "mouse", "people": "person", "better": "good",
    "best": "good", "worse": "bad", "worst": "bad",
}
_VOWELS = set("aeiou")


class LemmaAnnotator(Annotator):
    """Rule-based English lemmatizer (``StemmerAnnotator`` role, but
    producing dictionary forms rather than Snowball stems)."""

    @staticmethod
    def _lemma(w: str, pos: Optional[str]) -> str:
        lw = w.lower()
        if lw in _IRREGULAR_LEMMAS:
            return _IRREGULAR_LEMMAS[lw]
        if pos in ("PUNCT", "NUM", "PRON", "DET", "ADP", "CONJ"):
            return lw
        for suf, rep in (("sses", "ss"), ("ies", "y"), ("ches", "ch"),
                         ("shes", "sh"), ("xes", "x"), ("zes", "z")):
            if lw.endswith(suf):
                return lw[: -len(suf)] + rep
        if lw.endswith("s") and not lw.endswith(("ss", "us", "is")) and len(lw) > 3:
            return lw[:-1]
        if lw.endswith("ing") and len(lw) > 5:
            stem = lw[:-3]
            if len(stem) > 2 and stem[-1] == stem[-2]:      # running -> run
                return stem[:-1]
            if stem[-1] not in _VOWELS and len(stem) > 2:    # making -> make
                return stem + "e" if stem[-1] in "kvzcg" else stem
            return stem
        if lw.endswith("ed") and len(lw) > 4:
            stem = lw[:-2]
            if len(stem) > 2 and stem[-1] == stem[-2]:       # stopped -> stop
                return stem[:-1]
            if stem.endswith("i"):                           # tried -> try
                return stem[:-1] + "y"
            return stem
        return lw

    def process(self, doc: AnnotatedDocument) -> AnnotatedDocument:
        for t in doc.tokens:
            t.lemma = self._lemma(t.text, t.pos)
        return doc


def default_pipeline() -> AnnotationPipeline:
    return AnnotationPipeline([SentenceAnnotator(), TokenizerAnnotator(),
                               PosAnnotator(), LemmaAnnotator()])


class AnnotatedTokenizerFactory(TokenizerFactory):
    """``UimaTokenizerFactory`` role: tokenizer SPI whose tokens are
    pipeline lemmas, optionally filtered by POS (e.g. drop PUNCT) —
    plugs into Word2Vec/BOW exactly like any other factory."""

    def __init__(self, pipeline: Optional[AnnotationPipeline] = None,
                 use_lemmas: bool = True,
                 drop_pos: Iterable[str] = ("PUNCT",)):
        self.pipeline = pipeline or default_pipeline()
        self.use_lemmas = use_lemmas
        self.drop_pos = frozenset(drop_pos)
        self._pre = None

    def create(self, text: str) -> Tokenizer:
        doc = self.pipeline.annotate(text)
        toks = [(t.lemma if self.use_lemmas and t.lemma else t.text)
                for t in doc.tokens if t.pos not in self.drop_pos]
        return Tokenizer(toks, self._pre)


register_tokenizer_factory("annotated", AnnotatedTokenizerFactory)
