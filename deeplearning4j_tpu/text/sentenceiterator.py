"""Sentence / document iterators.

Parity: ``text/sentenceiterator/`` (12 classes) — the corpus-feeding
SPI: ``SentenceIterator`` (nextSentence/hasNext/reset + preprocessor),
collection/line/file-backed implementations, and the labeled-document
variant used by ParagraphVectors (``documentiterator/LabelAwareIterator``).
"""

from __future__ import annotations

import os
import queue as _queue
from typing import Callable, Iterable, List, Optional, Tuple


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    def __init__(self, preprocessor: Optional[SentencePreProcessor] = None):
        self._pre = preprocessor

    def set_pre_processor(self, pre: SentencePreProcessor):
        self._pre = pre

    def _apply(self, s: str) -> str:
        return self._pre.pre_process(s) if self._pre else s

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: List[str], preprocessor=None):
        super().__init__(preprocessor)
        self._sentences = list(sentences)
        self._i = 0

    def next_sentence(self) -> str:
        s = self._sentences[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._sentences)

    def reset(self) -> None:
        self._i = 0


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (``LineSentenceIterator``)."""

    def __init__(self, path: str, preprocessor=None):
        super().__init__(preprocessor)
        self._path = path
        self._fh = None
        self._next = None
        self.reset()

    def reset(self):
        if self._fh:
            self._fh.close()
        self._fh = open(self._path, "r", encoding="utf-8", errors="replace")
        self._advance()

    def _advance(self):
        line = self._fh.readline()
        self._next = line.rstrip("\n") if line else None

    def has_next(self):
        return self._next is not None

    def next_sentence(self):
        s = self._next
        self._advance()
        return self._apply(s)


BasicLineIterator = LineSentenceIterator  # reference alias


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory (``FileSentenceIterator``)."""

    def __init__(self, directory: str, preprocessor=None):
        super().__init__(preprocessor)
        self._dir = directory
        self.reset()

    def reset(self):
        self._files = sorted(
            os.path.join(r, f)
            for r, _, fs in os.walk(self._dir) for f in fs)
        self._lines: List[str] = []
        self._fi = 0
        self._li = 0
        self._load_next_file()

    def _load_next_file(self):
        self._lines, self._li = [], 0
        while self._fi < len(self._files) and not self._lines:
            with open(self._files[self._fi], encoding="utf-8", errors="replace") as f:
                self._lines = [l.rstrip("\n") for l in f if l.strip()]
            self._fi += 1

    def has_next(self):
        return self._li < len(self._lines)

    def next_sentence(self):
        s = self._lines[self._li]
        self._li += 1
        if self._li >= len(self._lines):
            self._load_next_file()
        return self._apply(s)


class LabelledDocument:
    """``documentiterator/LabelledDocument`` — content + labels."""

    def __init__(self, content: str, labels: List[str]):
        self.content = content
        self.labels = list(labels)


class LabelAwareIterator:
    """``documentiterator/LabelAwareIterator`` — documents with labels
    (the ParagraphVectors input SPI)."""

    def __init__(self, documents: Iterable[Tuple[str, List[str]]]):
        self._docs = [LabelledDocument(c, l) for c, l in documents]
        self._i = 0

    def has_next(self) -> bool:
        return self._i < len(self._docs)

    def next_document(self) -> LabelledDocument:
        d = self._docs[self._i]
        self._i += 1
        return d

    def reset(self):
        self._i = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()


class AggregatingSentenceIterator(SentenceIterator):
    """``AggregatingSentenceIterator`` — chains several sentence
    iterators into one stream (build corpora from mixed sources)."""

    def __init__(self, iterators: List[SentenceIterator],
                 preprocessor: Optional[SentencePreProcessor] = None):
        super().__init__(preprocessor)
        self._its = list(iterators)
        self.reset()

    def reset(self):
        for it in self._its:
            it.reset()
        self._idx = 0

    def has_next(self) -> bool:
        while self._idx < len(self._its):
            if self._its[self._idx].has_next():
                return True
            self._idx += 1
        return False

    def next_sentence(self) -> str:
        if not self.has_next():
            raise StopIteration
        return self._apply(self._its[self._idx].next_sentence())


class PrefetchingSentenceIterator(SentenceIterator):
    """``PrefetchingSentenceIterator`` — a background thread pulls from
    the wrapped iterator into a bounded queue so corpus IO (file reads,
    preprocessing) overlaps training. A worker exception propagates to
    the consumer (no silently truncated corpora); ``reset`` signals the
    worker to stop (cost ≤ queue depth, not the remaining corpus) and
    restarts from a fresh queue."""

    _END = object()

    def __init__(self, wrapped: SentenceIterator, fetch_size: int = 1000,
                 preprocessor: Optional[SentencePreProcessor] = None):
        super().__init__(preprocessor)
        self._wrapped = wrapped
        self._fetch = fetch_size
        self._queue = None
        self._thread = None
        self._stop = None
        self._peek = None
        self._done = False

    def _worker(self, q, stop):
        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except Exception:  # queue.Full
                    continue
            return False

        try:
            while not stop.is_set() and self._wrapped.has_next():
                if not put(self._wrapped.next_sentence()):
                    return
        except Exception as e:  # surface to the consumer, don't truncate
            put(e)
            return
        put(self._END)

    def _start(self):
        import threading

        self._queue = _queue.Queue(maxsize=self._fetch)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker,
                                        args=(self._queue, self._stop),
                                        daemon=True)
        self._thread.start()

    def reset(self):
        if self._thread is not None and self._thread.is_alive():
            self._stop.set()  # worker exits without draining the source
            self._thread.join()
        self._thread = None
        self._queue = None
        self._peek = None
        self._done = False
        self._wrapped.reset()

    def has_next(self) -> bool:
        if self._peek is not None:
            return True
        if self._done:
            return False
        if self._thread is None:
            self._start()
        while True:
            try:
                item = self._queue.get(timeout=0.2)
                break
            except _queue.Empty:
                # A worker killed by close(), or one that died on a
                # BaseException that skipped the except-Exception
                # handler, never enqueues _END — surface that as
                # end-of-stream instead of blocking forever. The worker
                # may have enqueued its final items (incl. _END) in the
                # gap between our timeout and this liveness check, so
                # drain non-blocking before declaring EOS. Snapshot the
                # thread: a concurrent close() nulls self._thread.
                th = self._thread
                if self._done or th is None or not th.is_alive():
                    try:
                        item = self._queue.get_nowait()
                        break
                    except _queue.Empty:
                        self._done = True
                        return False
        if isinstance(item, Exception):
            self._done = True
            raise item
        if item is self._END:
            self._done = True
            return False
        self._peek = item
        return True

    def next_sentence(self) -> str:
        if not self.has_next():
            raise StopIteration
        s, self._peek = self._peek, None
        return self._apply(s)

    def close(self) -> None:
        """Stop the worker without consuming the rest of the corpus —
        call when abandoning the iterator mid-stream (``__del__`` also
        signals it, so a dropped iterator cannot leak its polling
        thread or pin the wrapped source forever)."""
        self._done = True  # a consumer that keeps iterating sees EOS
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=1.0)
        self._thread = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            if self._stop is not None:
                self._stop.set()  # no join in __del__ (GC context)
        except Exception:
            pass


class LabelAwareListSentenceIterator(LabelAwareIterator):
    """``LabelAwareListSentenceIterator`` — sentences with one label
    each (defaults to positional labels), as a LabelAwareIterator."""

    def __init__(self, sentences: List[str],
                 labels: Optional[List[str]] = None):
        if labels is not None and len(labels) != len(sentences):
            raise ValueError(
                f"{len(labels)} labels for {len(sentences)} sentences")
        labs = labels or [f"doc_{i}" for i in range(len(sentences))]
        super().__init__([(s, [l]) for s, l in zip(sentences, labs)])


class SynchronizedSentenceIterator(SentenceIterator):
    """Thread-safe wrapper over any SentenceIterator — one lock around
    every SPI method (``SynchronizedSentenceIterator.java:10``), for
    sharing a single corpus stream between fit workers."""

    def __init__(self, wrapped: SentenceIterator):
        super().__init__()
        self._wrapped = wrapped
        import threading
        self._lock = threading.Lock()

    def has_next(self) -> bool:
        with self._lock:
            return self._wrapped.has_next()

    def next_sentence(self) -> str:
        with self._lock:
            return self._wrapped.next_sentence()

    def reset(self) -> None:
        with self._lock:
            self._wrapped.reset()

    def set_pre_processor(self, pre: SentencePreProcessor) -> None:
        with self._lock:
            self._wrapped.set_pre_processor(pre)

    def close(self) -> None:
        """Delegated cleanup — wrapping a PrefetchingSentenceIterator
        must still be able to stop its worker thread. Deliberately
        LOCK-FREE: a consumer may be blocked inside the wrapped
        iterator's has_next() while holding our lock, and close() is
        exactly the call that unblocks it (the prefetcher's close() is
        safe to run concurrently with its readers)."""
        for name in ("close", "finish"):
            fn = getattr(self._wrapped, name, None)
            if fn is not None:
                fn()
                return

    finish = close  # reference SPI name


class BasicResultSetIterator(SentenceIterator):
    """Sentences from a database query (``BasicResultSetIterator.java:16``
    — the JDBC ResultSet role, over PEP 249 cursors here).

    DB-API cursors are forward-only, so reset() re-executes: pass a
    zero-arg ``execute`` callable returning a FRESH cursor (e.g.
    ``lambda: conn.execute("SELECT text FROM docs")``). ``column``
    selects by name (via ``cursor.description``) or positional index.
    Mirrors the reference's peeked-row bookkeeping so ``has_next`` never
    skips data."""

    def __init__(self, execute: Callable[[], object], column=0,
                 preprocessor: Optional[SentencePreProcessor] = None):
        super().__init__(preprocessor)
        self._execute = execute
        self._column = column
        self._cursor = None
        self._peek = None
        self._exhausted = False
        self._col = None  # resolved once per cursor, not per row

    def _col_index(self) -> int:
        if isinstance(self._column, int):
            return self._column
        names = [d[0] for d in self._cursor.description]
        try:
            return names.index(self._column)
        except ValueError:
            raise KeyError(
                f"column {self._column!r} not in result set {names}")

    def _ensure(self):
        if self._cursor is None:
            self._cursor = self._execute()
            self._peek = None
            self._exhausted = False
            self._col = self._col_index()

    def has_next(self) -> bool:
        self._ensure()
        if self._peek is not None:
            return True
        if self._exhausted:
            return False
        row = self._cursor.fetchone()
        if row is None:
            self._exhausted = True
            return False
        self._peek = row
        return True

    def next_sentence(self) -> str:
        if not self.has_next():
            raise StopIteration
        row, self._peek = self._peek, None
        return self._apply(str(row[self._col]))

    def reset(self) -> None:
        close = getattr(self._cursor, "close", None)
        if close is not None:
            close()
        self._cursor = None  # next use re-executes the query

    def finish(self) -> None:
        self.reset()


class LabelsSource:
    """Positional label generator (``labels/LabelsSource.java``):
    template-formatted labels, remembered in order."""

    def __init__(self, template: str = "SENT_%d"):
        self.template = template
        self.labels: List[str] = []

    def next_label(self) -> str:
        label = self.template % len(self.labels)
        self.labels.append(label)
        return label

    def reset(self) -> None:
        self.labels = []


class SentenceIteratorConverter(LabelAwareIterator):
    """Adapts any SentenceIterator into the LabelAwareIterator SPI
    (``interoperability/SentenceIteratorConverter.java:20``): each
    sentence becomes a document labeled from a :class:`LabelsSource`
    (positional by default), so plain corpora feed ParagraphVectors."""

    def __init__(self, iterator: SentenceIterator,
                 generator: Optional[LabelsSource] = None):
        self._it = iterator
        self._gen = generator or LabelsSource()

    def has_next(self) -> bool:
        return self._it.has_next()

    def next_document(self) -> LabelledDocument:
        return LabelledDocument(self._it.next_sentence(),
                                [self._gen.next_label()])

    def reset(self) -> None:
        self._it.reset()
        self._gen.reset()

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()


class LabelAwareFileSentenceIterator(LabelAwareIterator):
    """Sentences from files under a directory, labeled by the file's
    parent directory name (``labelaware/LabelAwareFileSentenceIterator``
    — the one-folder-per-class corpus layout)."""

    def __init__(self, directory: str,
                 preprocessor: Optional[SentencePreProcessor] = None):
        self._dir = directory
        self._pre = preprocessor
        self.reset()

    def reset(self) -> None:
        self._files = sorted(
            os.path.join(r, f)
            for r, _, fs in os.walk(self._dir) for f in fs)
        self._fi = 0
        self._lines: List[str] = []
        self._li = 0
        self._label = ""
        self._load_next()

    def _load_next(self) -> None:
        self._lines, self._li = [], 0
        while self._fi < len(self._files) and not self._lines:
            path = self._files[self._fi]
            with open(path, encoding="utf-8", errors="replace") as f:
                self._lines = [l.rstrip("\n") for l in f if l.strip()]
            self._label = os.path.basename(os.path.dirname(path))
            self._fi += 1

    def has_next(self) -> bool:
        return self._li < len(self._lines)

    def next_document(self) -> LabelledDocument:
        s = self._lines[self._li]
        self._li += 1
        label = self._label
        if self._li >= len(self._lines):
            self._load_next()
        if self._pre is not None:
            s = self._pre.pre_process(s)
        return LabelledDocument(s, [label])
