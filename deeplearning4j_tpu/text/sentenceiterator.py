"""Sentence / document iterators.

Parity: ``text/sentenceiterator/`` (12 classes) — the corpus-feeding
SPI: ``SentenceIterator`` (nextSentence/hasNext/reset + preprocessor),
collection/line/file-backed implementations, and the labeled-document
variant used by ParagraphVectors (``documentiterator/LabelAwareIterator``).
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional, Tuple


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    def __init__(self, preprocessor: Optional[SentencePreProcessor] = None):
        self._pre = preprocessor

    def set_pre_processor(self, pre: SentencePreProcessor):
        self._pre = pre

    def _apply(self, s: str) -> str:
        return self._pre.pre_process(s) if self._pre else s

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences: List[str], preprocessor=None):
        super().__init__(preprocessor)
        self._sentences = list(sentences)
        self._i = 0

    def next_sentence(self) -> str:
        s = self._sentences[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self) -> bool:
        return self._i < len(self._sentences)

    def reset(self) -> None:
        self._i = 0


class LineSentenceIterator(SentenceIterator):
    """One sentence per line of a file (``LineSentenceIterator``)."""

    def __init__(self, path: str, preprocessor=None):
        super().__init__(preprocessor)
        self._path = path
        self._fh = None
        self._next = None
        self.reset()

    def reset(self):
        if self._fh:
            self._fh.close()
        self._fh = open(self._path, "r", encoding="utf-8", errors="replace")
        self._advance()

    def _advance(self):
        line = self._fh.readline()
        self._next = line.rstrip("\n") if line else None

    def has_next(self):
        return self._next is not None

    def next_sentence(self):
        s = self._next
        self._advance()
        return self._apply(s)


BasicLineIterator = LineSentenceIterator  # reference alias


class FileSentenceIterator(SentenceIterator):
    """All lines of all files under a directory (``FileSentenceIterator``)."""

    def __init__(self, directory: str, preprocessor=None):
        super().__init__(preprocessor)
        self._dir = directory
        self.reset()

    def reset(self):
        self._files = sorted(
            os.path.join(r, f)
            for r, _, fs in os.walk(self._dir) for f in fs)
        self._lines: List[str] = []
        self._fi = 0
        self._li = 0
        self._load_next_file()

    def _load_next_file(self):
        self._lines, self._li = [], 0
        while self._fi < len(self._files) and not self._lines:
            with open(self._files[self._fi], encoding="utf-8", errors="replace") as f:
                self._lines = [l.rstrip("\n") for l in f if l.strip()]
            self._fi += 1

    def has_next(self):
        return self._li < len(self._lines)

    def next_sentence(self):
        s = self._lines[self._li]
        self._li += 1
        if self._li >= len(self._lines):
            self._load_next_file()
        return self._apply(s)


class LabelledDocument:
    """``documentiterator/LabelledDocument`` — content + labels."""

    def __init__(self, content: str, labels: List[str]):
        self.content = content
        self.labels = list(labels)


class LabelAwareIterator:
    """``documentiterator/LabelAwareIterator`` — documents with labels
    (the ParagraphVectors input SPI)."""

    def __init__(self, documents: Iterable[Tuple[str, List[str]]]):
        self._docs = [LabelledDocument(c, l) for c, l in documents]
        self._i = 0

    def has_next(self) -> bool:
        return self._i < len(self._docs)

    def next_document(self) -> LabelledDocument:
        d = self._docs[self._i]
        self._i += 1
        return d

    def reset(self):
        self._i = 0

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_document()
